"""ParagraphVectors (doc2vec).

Analog of the reference's models/paragraphvectors/ParagraphVectors.java
with the sequence learning algorithms DM (distributed memory: window mean
+ doc vector predicts the center word) and DBOW (doc vector alone
predicts each word) from models/embeddings/learning/impl/sequence/.

Doc vectors live in their own table; infer_vector trains a FRESH doc row
with the word tables frozen (reference: ParagraphVectors.inferVector).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.batching import (
    BatchPlan,
    generate_batches,
    group_batches,
    keep_probabilities,
    subsample,
)
from deeplearning4j_tpu.nlp.learning import (
    make_embedding_scan_step,
    make_embedding_step,
)
from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    VectorsConfiguration,
)
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)


class ParagraphVectors(SequenceVectors):
    def __init__(self, conf: VectorsConfiguration,
                 documents: Iterable[str], labels: Sequence[str],
                 tokenizer: Optional[TokenizerFactory] = None,
                 sequence_learning_algorithm: str = "dm"):
        self.tokenizer = tokenizer or DefaultTokenizerFactory()
        seqs = [self.tokenizer.create(d).get_tokens() for d in documents]
        super().__init__(conf, seqs)
        self.labels = list(labels)
        if len(self.labels) != len(seqs):
            raise ValueError("labels must align with documents")
        self.sequence_algo = sequence_learning_algorithm
        if self.sequence_algo not in ("dm", "dbow"):
            raise ValueError("sequence_learning_algorithm must be dm|dbow")
        self._label_index = {l: i for i, l in enumerate(self.labels)}
        if len(self._label_index) != len(self.labels):
            raise ValueError("duplicate document labels")
        self.doc_vectors = None  # [num_docs, D]

    def fit(self, sequences=None):
        conf = self.conf
        self.build_vocab()
        indexed = self._index_sentences(self._sequences)
        D = conf.layer_size
        n_docs = len(self.labels)
        key = jax.random.PRNGKey(conf.seed ^ 0xD0C)
        self.doc_vectors = (
            (jax.random.uniform(key, (n_docs, D), jnp.float32) - 0.5) / D
        )

        plan = BatchPlan(
            batch_size=conf.batch_size,
            context_size=1 if self.sequence_algo == "dbow" else 2 * conf.window,
            hs_arrays=self.huffman.arrays() if self.huffman else None,
            negative=conf.negative,
            unigram=(
                self.lookup.unigram_table() if conf.negative > 0 else None
            ),
            with_doc=True,
        )
        step = make_embedding_scan_step(
            use_hs=conf.use_hierarchic_softmax, negative=conf.negative,
            with_doc=True,
        )
        keep = keep_probabilities(self.vocab.counts(), conf.sampling)
        # distinct placeholder buffers — donation forbids duplicates
        dummy = lambda: jnp.zeros((1, D), jnp.float32)
        syn0 = self.lookup.syn0
        syn1 = self.lookup.syn1 if self.lookup.syn1 is not None else dummy()
        syn1neg = (
            self.lookup.syn1neg if self.lookup.syn1neg is not None else dummy()
        )
        doc = self.doc_vectors

        unigram_dev = jnp.zeros((1,), jnp.int32)  # host-side negatives
        base_key = jax.random.PRNGKey(conf.seed ^ 0x5EED)
        # dm/dbow emit ~one example per word position
        total_examples = max(
            sum(int(s.size) for s in indexed) * conf.epochs * conf.iterations,
            1,
        )
        seen = 0
        for _ in range(conf.epochs):
            sents = [subsample(s, keep, self._rng) for s in indexed]
            for _ in range(conf.iterations):
                for group, lrs, n_rows in group_batches(
                    generate_batches(
                        iter(sents), plan, window=conf.window,
                        mode=self.sequence_algo, rng=self._rng,
                        doc_ids=range(len(sents)),
                    ),
                    plan, conf.scan_size,
                    lambda s: max(
                        conf.learning_rate * (1.0 - (seen + s) / total_examples),
                        conf.min_learning_rate,
                    ),
                ):
                    syn0, syn1, syn1neg, doc, loss = step(
                        syn0, syn1, syn1neg, doc, unigram_dev, group, lrs,
                        jax.random.fold_in(base_key, seen),
                    )
                    seen += n_rows
        self.lookup.syn0 = syn0
        if self.lookup.syn1 is not None:
            self.lookup.syn1 = syn1
        if self.lookup.syn1neg is not None:
            self.lookup.syn1neg = syn1neg
        self.doc_vectors = doc
        return self

    # -- doc vector access ---------------------------------------------------

    def doc_vector(self, label: str) -> np.ndarray:
        return np.asarray(self.doc_vectors[self._label_index[label]])

    def similarity_to_label(self, text: str, label: str) -> float:
        v = self.infer_vector(text)
        d = self.doc_vector(label)
        denom = np.linalg.norm(v) * np.linalg.norm(d)
        return float(v @ d / denom) if denom else 0.0

    def nearest_labels(self, text_or_vec, top_n: int = 5):
        v = (
            self.infer_vector(text_or_vec)
            if isinstance(text_or_vec, str) else np.asarray(text_or_vec)
        )
        table = np.asarray(self.doc_vectors)
        sims = (table @ v) / np.maximum(
            np.linalg.norm(table, axis=1) * (np.linalg.norm(v) + 1e-12), 1e-12
        )
        order = np.argsort(-sims)[:top_n]
        return [(self.labels[i], float(sims[i])) for i in order]

    def infer_vector(self, text: str, steps: int = 5,
                     learning_rate: Optional[float] = None) -> np.ndarray:
        """Train a fresh doc vector against FROZEN word tables
        (reference: ParagraphVectors.inferVector)."""
        conf = self.conf
        lr0 = learning_rate if learning_rate is not None else conf.learning_rate
        tokens = self.tokenizer.create(text).get_tokens()
        sent = self._index_sentences([tokens])[0]
        D = conf.layer_size
        rng = np.random.default_rng(abs(hash(text)) % (2**31))
        vec = jnp.asarray(
            (rng.random((1, D), np.float32) - 0.5) / D
        )
        if sent.size == 0:
            return np.asarray(vec[0])
        plan = BatchPlan(
            batch_size=max(int(sent.size), 1),
            context_size=1 if self.sequence_algo == "dbow" else 2 * conf.window,
            hs_arrays=self.huffman.arrays() if self.huffman else None,
            negative=conf.negative,
            unigram=(
                self.lookup.unigram_table() if conf.negative > 0 else None
            ),
            with_doc=True,
        )
        if getattr(self, "_infer_step", None) is None:
            self._infer_step = make_embedding_step(
                use_hs=conf.use_hierarchic_softmax, negative=conf.negative,
                with_doc=True, train_words=False, donate=False,
            )
        step = self._infer_step
        dummy = lambda: jnp.zeros((1, D), jnp.float32)
        syn1 = self.lookup.syn1 if self.lookup.syn1 is not None else dummy()
        syn1neg = (
            self.lookup.syn1neg if self.lookup.syn1neg is not None else dummy()
        )
        for it in range(steps):
            lr = lr0 * (1.0 - it / steps)
            for batch in generate_batches(
                iter([sent]), plan, window=conf.window,
                mode=self.sequence_algo, rng=rng, doc_ids=iter([0]),
            ):
                _, _, _, vec, _ = step(
                    self.lookup.syn0, syn1, syn1neg, vec,
                    {k: jnp.asarray(v) for k, v in batch.items()},
                    jnp.asarray(lr, jnp.float32),
                )
        return np.asarray(vec[0])
