"""Keras model import (reference: deeplearning4j-modelimport, SURVEY §2.5).

Reads Keras-1.x HDF5 archives (``model_config``/``training_config`` JSON
attributes + ``model_weights`` groups — KerasModel.java:73-75,550-556) and
emits networks built through the native config DSL, copying weights with
the dim-ordering transposes the TPU-native NHWC/HWIO layout requires.
"""

from deeplearning4j_tpu.modelimport.keras import (
    KerasImportError,
    import_keras_model_and_weights,
    import_keras_model_config,
    import_keras_sequential_config,
    import_keras_sequential_model_and_weights,
)

__all__ = [
    "KerasImportError",
    "import_keras_model_and_weights",
    "import_keras_model_config",
    "import_keras_sequential_config",
    "import_keras_sequential_model_and_weights",
]
