"""Random-walk sequence generators (reference: graph/iterator/
RandomWalkIterator.java + WeightedWalkIterator — fixed-length walks
starting from every vertex, with a NoEdgeHandling policy for dead ends)."""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph


class NoEdgeHandling:
    SELF_LOOP = "self_loop"          # stay at the vertex
    EXCEPTION = "exception"
    CUTOFF = "cutoff"                # end the walk early


class RandomWalkIterator:
    """Yields one fixed-length walk per start vertex per epoch, in
    shuffled vertex order (reference semantics)."""

    def __init__(self, graph: Graph, walk_length: int,
                 weighted: bool = False, seed: int = 0,
                 no_edge_handling: str = NoEdgeHandling.SELF_LOOP):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.weighted = weighted
        self.no_edge = no_edge_handling
        self._rng = np.random.default_rng(seed)

    def walk_from(self, start: int) -> List[int]:
        walk = [start]
        v = start
        for _ in range(self.walk_length):
            nxt = self.graph.random_neighbor(v, self._rng, self.weighted)
            if nxt is None:
                if self.no_edge == NoEdgeHandling.EXCEPTION:
                    raise RuntimeError(f"vertex {v} has no outgoing edges")
                if self.no_edge == NoEdgeHandling.CUTOFF:
                    break
                nxt = v  # self loop
            walk.append(nxt)
            v = nxt
        return walk

    def __iter__(self) -> Iterator[List[int]]:
        order = self._rng.permutation(self.graph.num_vertices)
        for start in order:
            yield self.walk_from(int(start))
