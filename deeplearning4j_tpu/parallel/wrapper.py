"""ParallelWrapper — single-node multi-device data-parallel training.

Reference: deeplearning4j-scaleout/deeplearning4j-scaleout-parallelwrapper/
src/main/java/org/deeplearning4j/parallelism/ParallelWrapper.java — N worker
threads each holding a full model replica, barrier every
`averagingFrequency` iterations, then parameter + updater-state averaging
across replicas (:417-424, :231-262).

TPU-native design: there are no replicas and no averaging step. Parameters
and updater state are *replicated* arrays on a `Mesh`; each global batch is
*sharded* across the mesh's "data" axis; the jitted train step computes the
global-mean loss, and XLA GSPMD inserts a gradient `psum` over ICI where
the reference copied parameters between threads. Per-step gradient
allreduce is mathematically ⊇ parameter averaging with frequency=1 when
each "worker" contributes one shard of the global batch:

    averaged params = mean_i (θ - lr·g_i) = θ - lr·mean_i(g_i)

which is exactly the allreduced-gradient step (asserted by
tests/test_parallel.py::test_allreduce_equals_parameter_averaging). Higher
averaging frequencies trade accuracy for communication that ICI does not
need; they are intentionally not reproduced.

Training delegates to the model's own fit loop (epochs, listeners, TBPTT
dispatch, ETL timing all single-sourced in MultiLayerNetwork.fit) with a
batch-transform hook that shards each global batch onto the mesh; the
wrapped model's params/updater state are placed replicated at construction,
so after fit() the model is directly usable for inference/serialization.
"""

from __future__ import annotations

import logging

import jax
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator, StackedDataSetIterator
from deeplearning4j_tpu.parallel.mesh import (
    data_parallel_mesh,
    data_shards,
    pad_wrap,
    placement_for_batch,
    replicated,
)

logger = logging.getLogger("deeplearning4j_tpu")


class ParallelWrapper:
    """Data-parallel trainer over a device mesh.

    Args:
        model: an initialized (or initializable) MultiLayerNetwork or
            ComputationGraph.
        mesh: a `jax.sharding.Mesh` with a "data" axis; defaults to a 1-D
            mesh over all visible devices.
        workers: how many iterator minibatches form one global step
            (reference: each DefaultTrainer consumed one minibatch between
            barriers). Default 1 — the iterator's batches are already
            global.
        averaging_frequency: accepted for API parity; only 1 is meaningful
            here because allreduce happens every step (see module doc).
        prefetch_buffer: async host-side prefetch depth.
    """

    def __init__(
        self,
        model,
        mesh=None,
        workers: int = 1,
        averaging_frequency: int = 1,
        prefetch_buffer: int = 4,
    ):
        if averaging_frequency != 1:
            raise ValueError(
                "averaging_frequency > 1 is a CPU/PCIe-era tradeoff; the "
                "per-step ICI gradient allreduce used here is exact "
                "averaging with frequency=1 (see parallel/wrapper.py doc)"
            )
        self.model = model
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.workers = int(workers)
        self.prefetch_buffer = prefetch_buffer
        self.n_shards = data_shards(self.mesh)
        self._pad_target = 0  # largest shard-divisible batch seen
        model._require_init()
        self._place_replicated()

    # -- placement -----------------------------------------------------------

    def _place_replicated(self):
        """Commit params + updater state to the mesh, fully replicated —
        the analog of ParallelWrapper copying the source model into every
        worker replica (DefaultTrainer.java:193-221), done once instead of
        per averaging round."""
        rep = replicated(self.mesh)
        put = lambda t: jax.tree_util.tree_map(
            lambda a: jax.device_put(a, rep), t
        )
        self.model.params_list = put(self.model.params_list)
        self.model.upd_state = put(self.model.upd_state)

    def _shard_batch(self, ds):
        """Shard a global batch's dim 0 across the data axis (DataSet or
        MultiDataSet — ComputationGraph fit yields the latter).

        Pad-and-mask tail handling: a batch not divisible by the shard
        count is padded to the next multiple by WRAPPING examples (repeat
        from the batch start) and the pad rows are excluded from the loss
        via an all-zero labels-mask row (losses use masked_example_mean,
        so the padded step computes exactly the unpadded score/gradients).
        A labels mask of ones is supplied for full batches too, keeping
        one trace signature — the tail batch neither recompiles nor drops
        to replicated serial execution (round-2 weakness: a 255-example
        tail on 8 devices ran 8x redundant AND recompiled). Note: wrapped
        pad rows do still enter batch-norm batch statistics — a stochastic
        duplicate-sample effect on the tail step only."""
        n = ds.num_examples()
        # pad up to the largest (shard-divisible) batch seen so far, so a
        # short tail reuses the full batches' compiled executable instead
        # of introducing a second shape
        target = max(n + ((-n) % self.n_shards), self._pad_target)
        self._pad_target = target
        pad = target - n

        def wrap(a):
            return None if a is None else pad_wrap(np.asarray(a), target)

        def pad_lmask(lm):
            """Existing labels mask: pad rows of zeros. Absent: 0/1 vector."""
            if lm is not None:
                lm = np.asarray(lm)
                z = np.zeros((pad,) + lm.shape[1:], lm.dtype)
                return np.concatenate([lm, z]) if pad else lm
            m = np.ones((n + pad,), np.float32)
            if pad:
                m[n:] = 0.0
            return m

        sh = placement_for_batch(self.mesh, n + pad)
        put = lambda a: None if a is None else jax.device_put(a, sh)
        if isinstance(ds, MultiDataSet):
            lmasks = ds.labels_masks
            if lmasks is None:
                lmasks = [None] * len(ds.labels)
            out = MultiDataSet(
                [put(wrap(f)) for f in ds.features],
                [put(wrap(l)) for l in ds.labels],
                None if ds.features_masks is None
                else [put(wrap(m)) for m in ds.features_masks],
                [put(pad_lmask(m)) for m in lmasks],
            )
        else:
            out = DataSet(
                put(wrap(ds.features)),
                put(wrap(ds.labels)),
                put(wrap(ds.features_mask)),
                put(pad_lmask(ds.labels_mask)),
            )
        # listeners/counters must see the REAL example count, not the pad
        out.reported_examples = n
        return out

    # -- training ------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128, async_prefetch: bool = True):
        """Train data-parallel. Accepts the same inputs as
        MultiLayerNetwork.fit; `batch_size` is the GLOBAL batch (sharded
        across devices). With workers > 1 and an iterator input, each step
        consumes `workers` minibatches as one global batch.

        With async_prefetch, `_shard_batch` (pad + per-device
        `device_put`) runs inside the device-prefetch worker thread
        `prefetch_buffer`-deep ahead of the step (netbase's staged input
        pipeline), so the shard split overlaps the previous step's
        compute instead of sitting on the dispatch critical path."""
        net = self.model
        data_in = data
        if self.workers > 1:
            if not isinstance(data, DataSetIterator):
                raise ValueError("workers > 1 requires a DataSetIterator input")
            data_in = StackedDataSetIterator(data, self.workers)
        # the pad-up-to target is per-fit state: a later fit with a smaller
        # batch size must not keep padding to the old larger shape
        self._pad_target = 0
        prev_transform = net._batch_transform
        net._batch_transform = self._shard_batch
        try:
            net.fit(data_in, labels, epochs=epochs, batch_size=batch_size,
                    async_prefetch=async_prefetch,
                    prefetch_buffer=self.prefetch_buffer)
        finally:
            net._batch_transform = prev_transform
        return net

    # -- sharded inference ---------------------------------------------------

    def output(self, x):
        """Data-parallel forward pass: shards the batch, same replicated
        params. Non-divisible batches are padded by wrapping and the pad
        rows sliced off the result — sharded execution and a stable trace
        shape instead of the replicated fallback."""
        xx = np.asarray(x)
        n = xx.shape[0]
        pad = (-n) % self.n_shards
        if pad:
            xx = pad_wrap(xx, self.n_shards)
        sh = placement_for_batch(self.mesh, xx.shape[0])
        out = self.model.output(jax.device_put(xx, sh))
        return out[:n] if pad else out
