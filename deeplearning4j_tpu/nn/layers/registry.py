"""Layer implementation registry.

Maps a config dataclass type to its functional implementation:

- init_params(key, conf, dtype) -> dict[name, array]   (trainable)
- init_state(conf, dtype)       -> dict[name, array] | None  (non-trainable,
  e.g. batchnorm running stats — the analog of the reference's layer
  internal state that lives outside the flattened param view)
- forward(conf, params, x, ctx) -> (y, new_state)

ctx is a LayerContext carrying training flag, rng, masks and minibatch
metadata — the information the reference threads through Layer.activate
arguments and network fields.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple, Type

import jax

_INIT: Dict[Type, Callable] = {}
_STATE: Dict[Type, Callable] = {}
_FORWARD: Dict[Type, Callable] = {}
_ORDER: Dict[Type, Callable] = {}


@dataclasses.dataclass
class LayerContext:
    """Per-call context for a layer forward."""

    training: bool = False
    rng: Optional[jax.Array] = None
    mask: Optional[jax.Array] = None  # [batch, time] for RNN inputs
    timesteps: Optional[int] = None  # batch time length (for ff<->rnn reshape)
    state: Optional[dict] = None  # layer's mutable state going in


def register_layer(conf_cls, init_fn, forward_fn, order_fn=None, state_fn=None):
    _INIT[conf_cls] = init_fn
    _FORWARD[conf_cls] = forward_fn
    if order_fn is not None:
        _ORDER[conf_cls] = order_fn
    if state_fn is not None:
        _STATE[conf_cls] = state_fn


def _lookup(table, conf):
    for cls in type(conf).__mro__:
        if cls in table:
            return table[cls]
    return None


def init_layer_params(key, conf, dtype) -> Dict[str, Any]:
    fn = _lookup(_INIT, conf)
    if fn is None:
        raise NotImplementedError(f"no init for layer conf {type(conf).__name__}")
    return fn(key, conf, dtype)


def init_layer_state(conf, dtype) -> Optional[dict]:
    fn = _lookup(_STATE, conf)
    return None if fn is None else fn(conf, dtype)


def forward_layer(conf, params, x, ctx: LayerContext) -> Tuple[Any, Optional[dict]]:
    fn = _lookup(_FORWARD, conf)
    if fn is None:
        raise NotImplementedError(f"no forward for layer conf {type(conf).__name__}")
    return fn(conf, params, x, ctx)


def param_order(conf) -> Tuple[str, ...]:
    """Stable parameter-name order used for the flattened view
    (reference: each nn/params/*ParamInitializer defines the layout of its
    slice of flattenedParams)."""
    fn = _lookup(_ORDER, conf)
    if fn is not None:
        return fn(conf)
    return ("W", "b")
