"""Multi-host data parallelism over DCN (the reference's cluster story).

Reference transports (SURVEY §2.4): Spark parameter averaging
(ParameterAveragingTrainingMaster.java:429-621 — driver broadcasts params,
executors train splits, treeAggregate averages) and the Aeron parameter
server (ParameterServerTrainer.java:32-66 — async pushNDArray). Both exist
because the reference has no collective fabric.

TPU-native design: hosts form ONE jax.distributed job; all chips across
hosts join a single global Mesh. Gradients still allreduce every step —
XLA routes the reduction over ICI within a slice and DCN across slices;
there is no driver, no broadcast, no tree aggregation to reimplement. The
host-side contract is only about DATA: each process feeds its local shard
of the global batch, assembled into one global array
(host_local_array_to_global_array). The reference's "TrainingMaster"
becomes ~40 lines of process bootstrap + batch assembly.

Run one process per host:

    initialize_distributed(coordinator, num_processes, process_id)
    mesh = global_data_parallel_mesh()
    trainer = MultiHostDataParallel(net, mesh)
    trainer.fit_local_shards(local_iter, epochs=3)

Verified without real hosts by tests/test_multihost.py: two CPU processes
x 4 virtual devices each == one 8-device process, to float tolerance.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS
from deeplearning4j_tpu.parallel.sharded import MeshPlan
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


def initialize_distributed(coordinator_address: str, num_processes: int,
                           process_id: int,
                           local_device_ids: Optional[list] = None) -> None:
    """Join this process into the jax.distributed job (DCN bootstrap —
    the analog of the reference's Spark/Aeron cluster setup, minus the
    driver/worker asymmetry: every process is a peer)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def global_data_parallel_mesh() -> Mesh:
    """1-D "data" mesh over every device of every process."""
    return Mesh(np.array(jax.devices()), (DATA_AXIS,))


class MultiHostMeshPlan(MeshPlan):
    """MeshPlan over a global (cross-process) mesh.

    The single-host plan's batch split device_puts a host-local numpy
    batch; across processes each host only HAS its own shard, so staging
    instead assembles the global array from per-process locals
    (host_local_array_to_global_array). Every process must fit the same
    number of equally-shaped local batches per epoch (the SPMD
    contract)."""

    def place_net(self, net) -> "MultiHostMeshPlan":
        """Replicate params/updater state across ALL processes' devices.
        Every process holds an identical copy (same-seed init or a
        restored checkpoint) — its local copy becomes the local shards of
        one global fully-replicated array."""
        def rep(a):
            if a is None or self._on_this_mesh(a):
                return a
            return multihost_utils.host_local_array_to_global_array(
                np.asarray(a), self.mesh, PartitionSpec())

        put = lambda t: jax.tree_util.tree_map(rep, t)
        net.params_list = put(net.params_list)
        net.state_list = put(net.state_list)
        net.upd_state = put(net.upd_state)
        self._payload_bytes = None
        return self

    def shard_batch(self, ds):
        spec = PartitionSpec(DATA_AXIS)

        def to_global(a):
            if a is None:
                return None
            if self._on_this_mesh(a):
                return a  # already assembled upstream
            return multihost_utils.host_local_array_to_global_array(
                np.asarray(a), self.mesh, spec)

        local_shards = self.n_data_shards // jax.process_count()
        n_local = ds.num_examples()
        if n_local % local_shards != 0:
            raise ValueError(
                f"local batch of {n_local} examples does not divide this "
                f"process's {local_shards} shards; pad locally "
                "(multi-host pad-and-mask must be applied identically on "
                "every process)")
        return DataSet(
            to_global(ds.features), to_global(ds.labels),
            to_global(ds.features_mask), to_global(ds.labels_mask),
        )


class MultiHostDataParallel(ParallelWrapper):
    """The ParallelWrapper facade over a global (cross-process) mesh —
    NOT deprecated: it remains the multi-host bootstrap + data-assembly
    entry point; the train step itself is the same mainline sharded
    program (netbase.set_mesh with a MultiHostMeshPlan)."""

    def _make_plan(self, mesh):
        return MultiHostMeshPlan(mesh)

    def fit_local_shards(self, iterator, *, epochs: int = 1,
                         async_prefetch: bool = False):
        """Train where `iterator` yields THIS process's shard of each
        global batch (global batch = num_processes x local batch)."""
        return self.fit(iterator, epochs=epochs,
                        async_prefetch=async_prefetch)
