"""ResNet workload tests (tiny variants on CPU; the full ResNet-50 is the
bench workload — here we verify its construction and parameter count
against the canonical 25.5M)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.compgraph import ComputationGraph
from deeplearning4j_tpu.models.resnet import (
    resnet50_conf,
    tiny_resnet_conf,
)
from deeplearning4j_tpu.train.gradientcheck import check_gradients_graph


def _tiny_net():
    return ComputationGraph(tiny_resnet_conf()).init()


def _img_batch(n=8, size=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, size, size, 3)).astype(np.float32)
    y = np.zeros((n, classes), np.float32)
    y[np.arange(n), rng.integers(0, classes, n)] = 1.0
    return x, y


def test_resnet50_builds_with_canonical_param_count():
    conf = resnet50_conf(num_classes=1000, image_size=224)
    net = ComputationGraph(conf)
    # count params without materializing arrays: conv k*k*cin*cout, bn 2c,
    # dense (nin+1)*nout — init on CPU is fast enough to just do it
    net.init()
    total = net.num_params()
    # torchvision resnet50: 25,557,032 params (incl. BN). Ours counts W+b
    # for the head and gamma/beta for BN the same way.
    assert total == 25_557_032, f"got {total}"


def test_tiny_resnet_trains():
    net = _tiny_net()
    x, y = _img_batch(16)
    s0 = net.score(x, y)
    net.fit(x, y, epochs=25, batch_size=16, async_prefetch=False)
    s1 = net.score(x, y)
    assert s1 < s0, (s0, s1)


def test_tiny_resnet_gradcheck():
    """Gradient check through conv/BN/residual-add/global-pool DAG
    (reference: CNNGradientCheckTest + GradientCheckTestsComputationGraph)."""
    net = _tiny_net()
    x, y = _img_batch(4)
    assert check_gradients_graph(net, [x], [y], max_checks=80)


def test_tiny_resnet_inference_shapes():
    net = _tiny_net()
    x, _ = _img_batch(5)
    out = net.output(x)
    assert out.shape == (5, 3)
    probs = np.asarray(out)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)
