"""Subprocess half of tests/test_fault_tolerance.py.

Runs a small deterministic fit with checkpointing armed and prints one
flushed "STEP <iteration> <score>" line per training step, so the parent
test can kill the process (SIGKILL for the preemption-recovery tests,
SIGTERM for the signal-chain ordering tests) at a step of its choosing.
The network/data builders live here — the parent imports them too, so
the killed run, the resumed run, and the uninterrupted reference run are
the same model on the same batches by construction.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.data.dataset import DataSet  # noqa: E402
from deeplearning4j_tpu.data.iterators import ListDataSetIterator  # noqa: E402
from deeplearning4j_tpu.train.checkpoint import CheckpointListener  # noqa: E402
from deeplearning4j_tpu.train.listeners import IterationListener  # noqa: E402

N_EXAMPLES = 48
BATCH = 8
N_FEATURES = 5
N_CLASSES = 3
SHUFFLE_SEED = 11


def build_net(seed: int = 7):
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed).updater("adam")
            .learning_rate(0.02).list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=N_CLASSES, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_FEATURES)).build())
    return MultiLayerNetwork(conf).init()


def build_iterator(seed: int = 7):
    """Shuffling iterator: each epoch deals a DIFFERENT (epoch-seeded)
    permutation, so mid-epoch resume only reproduces the reference run if
    the iterator's epoch state is actually restored — a non-shuffling
    iterator would hide that bug."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((N_EXAMPLES, N_FEATURES)).astype(np.float32)
    y = np.zeros((N_EXAMPLES, N_CLASSES), np.float32)
    y[np.arange(N_EXAMPLES), rng.integers(0, N_CLASSES, N_EXAMPLES)] = 1.0
    return ListDataSetIterator(DataSet(x, y), BATCH, shuffle=True,
                               seed=SHUFFLE_SEED)


class StepPrinter(IterationListener):
    """One flushed line per step — the parent's kill trigger. The small
    sleep widens the window between steps so the parent's signal lands at
    (about) the step it chose instead of after the fit finished."""

    def __init__(self, sleep: float = 0.05):
        self.sleep = sleep

    def iteration_done(self, model, iteration, info):
        # .17g round-trips a float64 exactly: the parent compares these
        # against in-process reference scores with ==
        print(f"STEP {iteration} {float(info['score']()):.17g}", flush=True)
        if self.sleep:
            time.sleep(self.sleep)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["fit", "sigterm"], default="fit")
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--resume", action="store_true",
                    help="pass resume_from=<ckpt-dir> to fit (chaos loop)")
    ap.add_argument("--async-save", action="store_true")
    ap.add_argument("--sleep", type=float, default=0.05)
    ap.add_argument("--order", choices=["ckpt-first", "hooks-first"],
                    default="ckpt-first",
                    help="sigterm mode: which subsystem arms SIGTERM first")
    ap.add_argument("--dump", default=None,
                    help="sigterm mode: blackbox crash-dump path")
    args = ap.parse_args()

    def make_listener():
        # sigterm mode: NO periodic schedule — the only checkpoint that
        # can exist is the one the preemption hook saved, so its presence
        # proves the SIGTERM chain ran the save action
        sig = args.mode == "sigterm"
        return CheckpointListener(
            args.ckpt_dir,
            every_n_iterations=(None if sig else 1),
            every_n_epochs=(None if sig else 1),
            keep_last=3,
            save_on_preemption=sig,
            async_save=args.async_save)

    if args.mode == "sigterm":
        # the regression under test: installation ORDER between the
        # checkpoint preemption hook and the blackbox crash hooks must
        # not change the outcome (save first, then dump, then die)
        from deeplearning4j_tpu.utils.blackbox import install_crash_hooks

        if args.order == "hooks-first":
            install_crash_hooks(args.dump)
            listener = make_listener()
        else:
            listener = make_listener()
            install_crash_hooks(args.dump)
    else:
        listener = make_listener()

    net = build_net()
    net.set_listeners(listener, StepPrinter(args.sleep))
    net.fit(build_iterator(), epochs=args.epochs,
            resume_from=(args.ckpt_dir if args.resume else None))
    print("FIT DONE", flush=True)


if __name__ == "__main__":
    main()
