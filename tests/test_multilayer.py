"""MultiLayerNetwork training tests (reference:
deeplearning4j-core nn/multilayer/MultiLayerTest, BackPropMLPTest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    InputType,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.listeners import CollectScoresIterationListener


def blobs(n=256, seed=0):
    """Two gaussian blobs, linearly separable-ish."""
    rng = np.random.default_rng(seed)
    half = n // 2
    x0 = rng.normal(loc=-1.5, scale=1.0, size=(half, 2))
    x1 = rng.normal(loc=+1.5, scale=1.0, size=(half, 2))
    x = np.concatenate([x0, x1]).astype(np.float32)
    y = np.zeros((n, 2), np.float32)
    y[:half, 0] = 1
    y[half:, 1] = 1
    idx = rng.permutation(n)
    return x[idx], y[idx]


def mlp_conf(updater=Updater.SGD, lr=0.5, **kw):
    b = (
        NeuralNetConfiguration.builder()
        .seed(42)
        .updater(updater)
        .learning_rate(lr)
    )
    for k, v in kw.items():
        b = getattr(b, k)(v)
    return (
        b.list()
        .layer(DenseLayer(n_in=2, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=2, activation="softmax", loss="mcxent"))
        .build()
    )


def test_init_and_param_count():
    net = MultiLayerNetwork(mlp_conf()).init()
    # (2*16 + 16) + (16*2 + 2) = 48 + 34
    assert net.num_params() == 82
    assert net.params().shape == (82,)
    names = [r[0] for r in net.param_table()]
    assert names == ["0_W", "0_b", "1_W", "1_b"]


def test_params_roundtrip():
    net = MultiLayerNetwork(mlp_conf()).init()
    flat = net.params()
    net2 = MultiLayerNetwork(mlp_conf()).init()
    net2.set_params(flat)
    np.testing.assert_array_equal(np.asarray(net2.params()), np.asarray(flat))
    x = np.random.default_rng(0).normal(size=(4, 2)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(net.output(x)), np.asarray(net2.output(x)), rtol=1e-6
    )


def test_deterministic_init_by_seed():
    n1 = MultiLayerNetwork(mlp_conf()).init()
    n2 = MultiLayerNetwork(mlp_conf()).init()
    np.testing.assert_array_equal(np.asarray(n1.params()), np.asarray(n2.params()))


def test_training_reduces_score_and_learns():
    x, y = blobs()
    net = MultiLayerNetwork(mlp_conf()).init()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=20, batch_size=64, async_prefetch=False)
    s1 = net.score(x, y)
    assert s1 < s0 * 0.5
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.9
    assert ev.f1() > 0.9


@pytest.mark.parametrize("updater", ["sgd", "nesterovs", "adam", "adamax",
                                     "adadelta", "adagrad", "rmsprop"])
def test_all_updaters_learn(updater):
    x, y = blobs(128)
    lr = {"adadelta": 1.0, "adam": 0.05, "adamax": 0.05, "adagrad": 0.2,
          "rmsprop": 0.02}.get(updater, 0.5)
    net = MultiLayerNetwork(mlp_conf(updater=updater, lr=lr)).init()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=15, batch_size=64, async_prefetch=False)
    assert net.score(x, y) < s0


def test_fit_with_iterator_and_listener():
    x, y = blobs(128)
    it = ListDataSetIterator(DataSet(x, y), batch=32, shuffle=True)
    net = MultiLayerNetwork(mlp_conf()).init()
    collector = CollectScoresIterationListener()
    net.set_listeners(collector)
    net.fit(it, epochs=3, async_prefetch=True)
    assert len(collector.scores) == 12  # 4 batches x 3 epochs
    assert collector.scores[-1][1] < collector.scores[0][1]


def test_l2_regularization_changes_training():
    x, y = blobs(128)
    net_plain = MultiLayerNetwork(mlp_conf()).init()
    net_reg = MultiLayerNetwork(mlp_conf(l2=0.1)).init()
    net_plain.fit(x, y, epochs=10, batch_size=128, async_prefetch=False)
    net_reg.fit(x, y, epochs=10, batch_size=128, async_prefetch=False)
    wn_plain = float(jnp.linalg.norm(net_plain.params_list[0]["W"]))
    wn_reg = float(jnp.linalg.norm(net_reg.params_list[0]["W"]))
    assert wn_reg < wn_plain  # weight decay shrinks weights


def test_gradient_clipping_runs():
    x, y = blobs(64)
    conf = mlp_conf(
        gradient_normalization="clip_l2_per_layer",
        gradient_normalization_threshold=0.5,
    )
    net = MultiLayerNetwork(conf).init()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=10, batch_size=64, async_prefetch=False)
    assert net.score(x, y) < s0


def test_lr_schedule_applied():
    from deeplearning4j_tpu.train.updaters import schedule_lr

    conf = (
        NeuralNetConfiguration.builder()
        .learning_rate(0.1)
        .learning_rate_schedule({0: 0.1, 5: 0.01, 10: 0.001})
        .build()
    )
    assert schedule_lr(conf, 0) == 0.1
    assert schedule_lr(conf, 7) == 0.01
    assert schedule_lr(conf, 50) == 0.001


def test_output_probabilities_sum_to_one():
    x, y = blobs(32)
    net = MultiLayerNetwork(mlp_conf()).init()
    out = net.output(x)
    np.testing.assert_allclose(np.asarray(jnp.sum(out, -1)), np.ones(32), atol=1e-5)


def test_score_matches_manual_crossentropy():
    x, y = blobs(16)
    net = MultiLayerNetwork(mlp_conf()).init()
    out = np.asarray(net.output(x))
    manual = -np.mean(np.sum(y * np.log(np.clip(out, 1e-8, None)), axis=-1))
    assert abs(net.score(x, y) - manual) < 1e-4


# -- evaluation extensions (top-N, ROCBinary, prediction metadata) -----------

def test_topn_accuracy_and_prediction_meta():
    from deeplearning4j_tpu.train.evaluation import Evaluation

    ev = Evaluation(top_n=2)
    labels = np.eye(4, dtype=np.float32)[[0, 1, 2, 3]]
    # top-1 correct only for example 0; top-2 correct for 0,1,2
    preds = np.array([
        [0.9, 0.05, 0.03, 0.02],
        [0.5, 0.4, 0.05, 0.05],
        [0.1, 0.2, 0.3, 0.4],
        [0.4, 0.3, 0.2, 0.1],
    ], np.float32)
    ev.eval_batch(labels, preds, record_meta=["a", "b", "c", "d"])
    assert ev.accuracy() == 0.25
    assert ev.top_n_accuracy() == 0.75
    errs = ev.get_prediction_errors()
    assert [e.record_meta for e in errs] == ["b", "c", "d"]
    assert ev.get_predictions(1, 0)[0].record_meta == "b"
    # merge keeps the counters
    ev2 = Evaluation(top_n=2)
    ev2.eval_batch(labels, labels, record_meta=list("wxyz"))
    ev.merge(ev2)
    assert ev.top_n_accuracy() == (3 + 4) / 8


def test_roc_binary_per_column():
    from deeplearning4j_tpu.train.evaluation import ROCBinary

    rng = np.random.default_rng(0)
    n = 400
    labels = (rng.random((n, 2)) > 0.5).astype(np.float64)
    # column 0: informative scores; column 1: pure noise
    scores = np.stack([
        0.7 * labels[:, 0] + 0.3 * rng.random(n),
        rng.random(n),
    ], axis=1)
    roc = ROCBinary()
    # feed in two halves and also exercise merge
    roc.eval_batch(labels[:200], scores[:200])
    other = ROCBinary()
    other.eval_batch(labels[200:], scores[200:])
    roc.merge(other)
    assert roc.calculate_auc(0) > 0.9
    assert 0.4 < roc.calculate_auc(1) < 0.6
