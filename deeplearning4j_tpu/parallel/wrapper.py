"""ParallelWrapper — single-node multi-device data-parallel training.

Reference: deeplearning4j-scaleout/deeplearning4j-scaleout-parallelwrapper/
src/main/java/org/deeplearning4j/parallelism/ParallelWrapper.java — N worker
threads each holding a full model replica, barrier every
`averagingFrequency` iterations, then parameter + updater-state averaging
across replicas (:417-424, :231-262).

TPU-native design: there are no replicas and no averaging step. Parameters
and updater state are *replicated* arrays on a `Mesh`; each global batch is
*sharded* across the mesh's "data" axis; the jitted train step computes the
global-mean loss, and XLA GSPMD inserts a gradient `psum` over ICI where
the reference copied parameters between threads. Per-step gradient
allreduce is mathematically ⊇ parameter averaging with frequency=1 when
each "worker" contributes one shard of the global batch:

    averaged params = mean_i (θ - lr·g_i) = θ - lr·mean_i(g_i)

which is exactly the allreduced-gradient step (asserted by
tests/test_parallel.py::test_allreduce_equals_parameter_averaging). Higher
averaging frequencies trade accuracy for communication that ICI does not
need; they are intentionally not reproduced.

Training delegates to the model's own fit loop (epochs, listeners, TBPTT
dispatch, ETL timing all single-sourced in MultiLayerNetwork.fit) with a
batch-transform hook that shards each global batch onto the mesh; the
wrapped model's params/updater state are placed replicated at construction,
so after fit() the model is directly usable for inference/serialization.
"""

from __future__ import annotations

import logging

import jax
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator, StackedDataSetIterator
from deeplearning4j_tpu.parallel.mesh import (
    data_parallel_mesh,
    data_shards,
    placement_for_batch,
    replicated,
)

logger = logging.getLogger("deeplearning4j_tpu")


class ParallelWrapper:
    """Data-parallel trainer over a device mesh.

    Args:
        model: an initialized (or initializable) MultiLayerNetwork or
            ComputationGraph.
        mesh: a `jax.sharding.Mesh` with a "data" axis; defaults to a 1-D
            mesh over all visible devices.
        workers: how many iterator minibatches form one global step
            (reference: each DefaultTrainer consumed one minibatch between
            barriers). Default 1 — the iterator's batches are already
            global.
        averaging_frequency: accepted for API parity; only 1 is meaningful
            here because allreduce happens every step (see module doc).
        prefetch_buffer: async host-side prefetch depth.
    """

    def __init__(
        self,
        model,
        mesh=None,
        workers: int = 1,
        averaging_frequency: int = 1,
        prefetch_buffer: int = 4,
    ):
        if averaging_frequency != 1:
            raise ValueError(
                "averaging_frequency > 1 is a CPU/PCIe-era tradeoff; the "
                "per-step ICI gradient allreduce used here is exact "
                "averaging with frequency=1 (see parallel/wrapper.py doc)"
            )
        self.model = model
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.workers = int(workers)
        self.prefetch_buffer = prefetch_buffer
        self.n_shards = data_shards(self.mesh)
        model._require_init()
        self._place_replicated()

    # -- placement -----------------------------------------------------------

    def _place_replicated(self):
        """Commit params + updater state to the mesh, fully replicated —
        the analog of ParallelWrapper copying the source model into every
        worker replica (DefaultTrainer.java:193-221), done once instead of
        per averaging round."""
        rep = replicated(self.mesh)
        put = lambda t: jax.tree_util.tree_map(
            lambda a: jax.device_put(a, rep), t
        )
        self.model.params_list = put(self.model.params_list)
        self.model.upd_state = put(self.model.upd_state)

    def _shard_batch(self, ds):
        """Shard a global batch's dim 0 across the data axis (DataSet or
        MultiDataSet — ComputationGraph fit yields the latter)."""
        sh = placement_for_batch(self.mesh, ds.num_examples())
        put = lambda a: None if a is None else jax.device_put(np.asarray(a), sh)
        if isinstance(ds, MultiDataSet):
            put_list = lambda arrs: None if arrs is None else [put(a) for a in arrs]
            return MultiDataSet(
                [put(f) for f in ds.features],
                [put(l) for l in ds.labels],
                put_list(ds.features_masks),
                put_list(ds.labels_masks),
            )
        return DataSet(
            put(ds.features),
            put(ds.labels),
            put(ds.features_mask),
            put(ds.labels_mask),
        )

    # -- training ------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128, async_prefetch: bool = True):
        """Train data-parallel. Accepts the same inputs as
        MultiLayerNetwork.fit; `batch_size` is the GLOBAL batch (sharded
        across devices). With workers > 1 and an iterator input, each step
        consumes `workers` minibatches as one global batch."""
        net = self.model
        data_in = data
        if self.workers > 1:
            if not isinstance(data, DataSetIterator):
                raise ValueError("workers > 1 requires a DataSetIterator input")
            data_in = StackedDataSetIterator(data, self.workers)
        prev_transform = net._batch_transform
        net._batch_transform = self._shard_batch
        try:
            net.fit(data_in, labels, epochs=epochs, batch_size=batch_size,
                    async_prefetch=async_prefetch)
        finally:
            net._batch_transform = prev_transform
        return net

    # -- sharded inference ---------------------------------------------------

    def output(self, x):
        """Data-parallel forward pass: shards the batch, same replicated
        params."""
        xx = np.asarray(x)
        sh = placement_for_batch(self.mesh, xx.shape[0])
        return self.model.output(jax.device_put(xx, sh))
