"""StatsListener: per-iteration training telemetry -> storage router.

Reference: BaseStatsListener.java:51,103-124 — collects score,
param/gradient/update mean magnitudes, learning rate, memory and
throughput counters each iteration and routes them through a
StatsStorageRouter; cadence controlled by StatsUpdateConfiguration.

TPU-first: the mean-magnitude reductions are fused INTO the jitted train
step (net.set_collect_stats(True) — netbase exposes them via
info["stats"]) so collection adds tiny on-device reductions instead of
host-side parameter sweeps; the host readback happens only every
``frequency`` iterations.
"""

from __future__ import annotations

import time
import uuid
from typing import Optional

import numpy as np

from deeplearning4j_tpu.train.listeners import IterationListener
from deeplearning4j_tpu.ui.storage import StatsStorageRouter


def _device_memory_stats() -> dict:
    """Per-device memory counters when the backend exposes them (TPU/GPU
    runtimes do; CPU returns nothing). Reference reports JVM/off-heap
    memory per device (BaseStatsListener memory section)."""
    import jax

    out = {}
    try:
        for d in jax.local_devices():
            ms = d.memory_stats()
            if ms:
                out[f"device{d.id}"] = {
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "bytes_limit": int(ms.get("bytes_limit", 0)),
                }
    except Exception:
        pass
    return out


class StatsListener(IterationListener):
    """Routes per-iteration stats to a StatsStorageRouter.

    Usage::

        storage = InMemoryStatsStorage()
        net.set_collect_stats(True)
        net.set_listeners(StatsListener(storage))
        net.fit(...)
        UIServer(storage).start()
    """

    def __init__(self, router: StatsStorageRouter,
                 session_id: Optional[str] = None,
                 worker_id: str = "worker0",
                 frequency: int = 1,
                 report_memory: bool = True):
        self.router = router
        self.session_id = session_id or f"session-{uuid.uuid4().hex[:8]}"
        self.worker_id = worker_id
        self.frequency = max(1, int(frequency))
        self.report_memory = report_memory
        self._sent_static = False
        self._last_time: Optional[float] = None
        self._samples_since = 0

    # -- static info (once per session) --------------------------------------

    def _send_static(self, model):
        import jax

        confs = model._ordered_layer_confs()
        layers = [
            {"index": i, "type": type(c).__name__,
             "n_params": int(sum(np.prod(v.shape) for v in p.values()))}
            for i, (c, p) in enumerate(zip(confs, model.params_list))
        ]
        self.router.put_static_info(self.session_id, {
            "model_class": type(model).__name__,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0].device_kind),
            "n_devices": len(jax.devices()),
            "start_time": time.time(),
            "layers": layers,
            "total_params": int(sum(l["n_params"] for l in layers)),
        })
        self._sent_static = True

    # -- per iteration --------------------------------------------------------

    def iteration_done(self, model, iteration, info):
        if not self._sent_static:
            self._send_static(model)
        now = time.perf_counter()
        self._samples_since += info.get("batch_size", 0)
        if iteration % self.frequency != 0:
            return
        sps = 0.0
        if self._last_time is not None and now > self._last_time:
            sps = self._samples_since / (now - self._last_time)
        self._last_time = now
        self._samples_since = 0

        rec = {
            "iteration": int(iteration),
            "ts": time.time(),
            "epoch": int(model.epoch),
            "score": float(np.asarray(info["score"]())),
            "etl_ms": float(info.get("etl_ms", 0.0)),
            "samples_per_sec": float(sps),
            "worker": 0,
        }
        stats = info.get("stats", lambda: None)()
        if stats is not None:
            for group in ("grad_mm", "update_mm", "param_mm"):
                per_layer = {}
                for li, layer in enumerate(stats[group]):
                    for pname, v in layer.items():
                        per_layer[f"{li}_{pname}"] = float(np.asarray(v))
                rec[group] = per_layer
        if self.report_memory:
            mem = _device_memory_stats()
            if mem:
                rec["memory"] = mem
        self.router.put_update(self.session_id, rec)
