"""Gradient checks — the correctness backbone (reference:
deeplearning4j-core gradientcheck/ — GradientCheckTests,
CNNGradientCheckTest, LSTMGradientCheckTests, BNGradientCheckTest,
LossFunctionGradientCheck — run at eps=1e-6 in double precision)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    ActivationLayer,
    AutoEncoder,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    InputType,
    LSTM,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.gradientcheck import check_gradients

RNG = np.random.default_rng(12345)


def _labels(n, c):
    y = np.zeros((n, c))
    y[np.arange(n), RNG.integers(0, c, n)] = 1.0
    return y


def _rnn_labels(n, t, c):
    y = np.zeros((n, t, c))
    idx = RNG.integers(0, c, (n, t))
    for i in range(n):
        y[i, np.arange(t), idx[i]] = 1.0
    return y


# smooth activations only (reference whitelist GradientCheckUtil.java:48-59)
@pytest.mark.parametrize("act,loss,out_act", [
    ("tanh", "mcxent", "softmax"),
    ("sigmoid", "mse", "identity"),
    ("softplus", "mcxent", "softmax"),
    ("cube", "mse", "tanh"),
    ("softsign", "xent", "sigmoid"),
    ("elu", "mse", "identity"),
])
def test_mlp_gradients(act, loss, out_act):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .list()
        .layer(DenseLayer(n_in=4, n_out=5, activation=act))
        .layer(OutputLayer(n_out=3, activation=out_act, loss=loss))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.normal(size=(6, 4))
    if loss == "xent":
        y = RNG.uniform(0.1, 0.9, size=(6, 3))
    else:
        y = _labels(6, 3)
    assert check_gradients(net, x, y, verbose=True)


def test_mlp_with_l1_l2_gradients():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .l1(0.01)
        .l2(0.02)
        .list()
        .layer(DenseLayer(n_in=4, n_out=5, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    # keep weights away from 0 so |w| is differentiable at the check points
    x = RNG.normal(size=(5, 4))
    y = _labels(5, 3)
    assert check_gradients(net, x, y)


def test_cnn_gradients():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .list()
        .layer(ConvolutionLayer(kernel_size=(2, 2), n_out=3, activation="tanh"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), pooling_type="avg"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.convolutional(6, 6, 2))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.normal(size=(4, 6, 6, 2))
    y = _labels(4, 2)
    assert check_gradients(net, x, y, verbose=True)


def test_cnn_max_pool_gradients():
    # max pool is piecewise-linear; fine for gradient checks away from ties
    conf = (
        NeuralNetConfiguration.builder()
        .seed(99)
        .list()
        .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=2, activation="sigmoid"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.convolutional(7, 7, 1))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.normal(size=(3, 7, 7, 1))
    y = _labels(3, 2)
    assert check_gradients(net, x, y)


def test_batchnorm_gradients():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .list()
        .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
        .layer(BatchNormalization())
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.normal(size=(8, 4))
    y = _labels(8, 3)
    assert check_gradients(net, x, y, verbose=True)


def test_lstm_gradients():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .list()
        .layer(LSTM(n_out=4, activation="tanh"))
        .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(3))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.normal(size=(3, 5, 3))
    y = _rnn_labels(3, 5, 2)
    assert check_gradients(net, x, y, verbose=True)


def test_graves_lstm_gradients_with_mask():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .list()
        .layer(GravesLSTM(n_out=3, activation="tanh"))
        .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(2))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.normal(size=(2, 6, 2))
    y = _rnn_labels(2, 6, 2)
    mask = np.ones((2, 6))
    mask[0, 4:] = 0
    mask[1, 5:] = 0
    assert check_gradients(net, x, y, features_mask=mask, labels_mask=mask)


def test_bidirectional_lstm_gradients():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(5)
        .list()
        .layer(GravesBidirectionalLSTM(n_out=3, activation="tanh"))
        .layer(GlobalPoolingLayer(pooling_type="avg"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(2))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.normal(size=(2, 4, 2))
    y = _labels(2, 2)
    assert check_gradients(net, x, y)


def test_embedding_gradients():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .list()
        .layer(EmbeddingLayer(n_in=7, n_out=4, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.integers(0, 7, size=(6, 1)).astype(np.float64)
    y = _labels(6, 3)
    assert check_gradients(net, x, y)


def test_autoencoder_supervised_gradients():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(3)
        .list()
        .layer(AutoEncoder(n_in=5, n_out=4, activation="sigmoid"))
        .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.normal(size=(4, 5))
    y = _labels(4, 2)
    assert check_gradients(net, x, y)
