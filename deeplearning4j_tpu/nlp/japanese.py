"""Minimal Japanese morphological segmenter — lattice + Viterbi.

Reference: deeplearning4j-nlp-japanese bundles a full Kuromoji fork (76
files: mmap'd dictionaries, trained connection-cost matrices, POS
tagging). This framework's scope is EMBEDDING-QUALITY segmentation — the
tokens feed word2vec/GloVe/TF-IDF, not a tagger — so it implements the
same *mechanism* (a segmentation lattice over a lexicon, cheapest path
by Viterbi, character-class unknown-word handling) at a bundled-lexicon
scale, pluggable through the identical TokenizerFactory SPI as
CJKTokenizerFactory (which remains the dictionary-free fallback for
arbitrary CJK text). See README "CJK tokenization" for the scope
rationale.

Model (a deliberately simplified Kuromoji/MeCab):
- lattice nodes = dictionary matches starting at each position (longest
  lexicon entry is `max_len` chars) + one unknown-word node per
  same-character-class run prefix
- node cost = per-entry lexicon cost (frequent particles/affixes cheap,
  content words mid, unknown runs expensive per char) — no connection
  matrix (that is the trained-model part of Kuromoji; unigram costs
  already recover dictionary words and particle boundaries)
- cheapest full segmentation by Viterbi over positions.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from deeplearning4j_tpu.nlp.tokenization import (
    CJK_CHAR_RANGES,
    Tokenizer,
    TokenizerFactory,
)

# -- bundled mini-lexicon -----------------------------------------------------
# cost per entry: particles/copula bits ~1, verb/adjective inflections ~2,
# content words ~3 (beats unknown-run cost of 6/char so known words win,
# while unknown runs still beat absurd over-segmentation).

_PARTICLES = ["は", "が", "を", "に", "で", "と", "も", "の", "へ", "や",
              "から", "まで", "より", "ね", "よ", "か", "な", "わ", "ぞ",
              "こそ", "しか", "だけ", "ほど", "など", "って", "ば"]
_COPULA = ["です", "でした", "だ", "だった", "である", "ます", "ました",
           "ません", "ましょう", "たい", "ない", "なかった", "れる",
           "られる", "せる", "させる", "て", "た", "ている", "ていた",
           "う", "よう"]
_WORDS = [
    # pronouns / people
    "私", "僕", "君", "彼", "彼女", "あなた", "誰", "人", "皆", "友達",
    "先生", "学生", "子供", "家族", "男", "女",
    # time / place
    "今日", "明日", "昨日", "今", "時間", "年", "月", "日", "週", "朝",
    "夜", "午前", "午後", "東京", "日本", "京都", "大阪", "世界", "国",
    "家", "学校", "会社", "駅", "店", "道", "町", "部屋",
    # common nouns
    "猫", "犬", "水", "火", "山", "川", "海", "空", "雨", "雪", "花",
    "木", "本", "車", "電車", "電話", "映画", "音楽", "写真", "料理",
    "食べ物", "飲み物", "言葉", "名前", "仕事", "勉強", "問題", "質問",
    "答え", "お金", "気持ち", "心", "手", "目", "耳", "口", "足", "頭",
    # verbs (stems + common forms)
    "行き", "行く", "行った", "来る", "来た", "来ます", "見る", "見た",
    "見え", "食べ", "食べる", "食べた", "飲む", "飲んだ", "する", "した",
    "します", "言う", "言った", "思う", "思った", "書く", "書いた",
    "読む", "読んだ", "読んで", "飲んで", "聞く", "聞いた", "話す",
    "話した", "分かる",
    "分かった", "知る", "知って", "作る", "作った", "使う", "使った",
    "買う", "買った", "働く", "歩く", "走る", "泳ぐ", "遊ぶ", "住む",
    "住んで", "待つ", "持つ", "持って", "帰る", "帰った", "出る",
    "入る", "会う", "会った", "始まる", "終わる", "ある", "あった",
    "いる", "いた", "なる", "なった", "できる", "できた",
    # adjectives / adverbs
    "大きい", "小さい", "新しい", "古い", "高い", "安い", "良い", "悪い",
    "早い", "遅い", "近い", "遠い", "暑い", "寒い", "楽しい", "嬉しい",
    "悲しい", "難しい", "簡単", "綺麗", "静か", "元気", "大切", "大変",
    "好き", "嫌い", "上手", "下手",
    "とても", "少し", "たくさん", "もう", "まだ", "いつも", "時々",
    "一緒", "全部", "本当", "多分",
    # numbers / counters
    "一", "二", "三", "四", "五", "六", "七", "八", "九", "十", "百",
    "千", "万", "円", "時", "分", "歳", "個", "人",
]


def _default_lexicon() -> Dict[str, float]:
    lex: Dict[str, float] = {}
    for w in _WORDS:
        lex[w] = 3.0
    for w in _COPULA:
        lex[w] = 2.0
    for w in _PARTICLES:
        lex[w] = 1.0
    return lex


_CLASS_PATTERNS: List[Tuple[str, re.Pattern]] = [
    (name, re.compile(f"[{body}]")) for name, body in CJK_CHAR_RANGES
]


def _char_class(ch: str) -> str:
    for name, pat in _CLASS_PATTERNS:
        if pat.match(ch):
            return name
    return "other"


def segment(text: str, lexicon: Dict[str, float] = None,
            unknown_cost: float = 6.0) -> List[str]:
    """Cheapest segmentation of `text` (whitespace and punctuation are
    hard boundaries; each non-space span runs its own lattice)."""
    lex = lexicon if lexicon is not None else _DEFAULT_LEX
    max_len = max((len(w) for w in lex), default=1)
    out: List[str] = []
    for span in re.split(r"[\s、。,．.!?！？「」()（）]+", text):
        if span:
            out.extend(_segment_span(span, lex, max_len, unknown_cost))
    return out


def _segment_span(s: str, lex, max_len: int,
                  unknown_cost: float) -> List[str]:
    n = len(s)
    INF = float("inf")
    best = [INF] * (n + 1)
    back: List[Tuple[int, str]] = [(-1, "")] * (n + 1)
    best[0] = 0.0
    for i in range(n):
        if best[i] == INF:
            continue
        # dictionary edges
        for L in range(1, min(max_len, n - i) + 1):
            w = s[i:i + L]
            c = lex.get(w)
            if c is not None and best[i] + c < best[i + L]:
                best[i + L] = best[i] + c
                back[i + L] = (i, w)
        # unknown edges: every PREFIX of the same-class run from i. The
        # per-char cost decreases with length, so whole runs win (katakana
        # loanwords, unknown kanji compounds, latin words stay intact)
        # UNLESS splitting exposes a cheaper dictionary edge — which is
        # exactly how a particle after an out-of-lexicon word (of any
        # script) gets its boundary back
        cls = _char_class(s[i])
        j = i + 1
        while j < n and _char_class(s[j]) == cls:
            j += 1
        for L in range(1, j - i + 1):
            c = unknown_cost * (1.0 + 0.3 * (L - 1))
            if best[i] + c < best[i + L]:
                best[i + L] = best[i] + c
                back[i + L] = (i, s[i:i + L])
    toks: List[str] = []
    i = n
    while i > 0:
        prev, w = back[i]
        toks.append(w)
        i = prev
    toks.reverse()
    return toks


_DEFAULT_LEX = _default_lexicon()


class JapaneseTokenizerFactory(TokenizerFactory):
    """Lattice/Viterbi Japanese tokenizer on the TokenizerFactory SPI
    (the deeplearning4j-nlp-japanese slot). `lexicon` extends/overrides
    the bundled mini-lexicon ({word: cost}); unknown text falls back to
    character-class runs, so any input segments."""

    def __init__(self, lexicon: Dict[str, float] = None,
                 unknown_cost: float = 6.0):
        super().__init__()
        self.lexicon = dict(_DEFAULT_LEX)
        if lexicon:
            self.lexicon.update(lexicon)
        self.unknown_cost = float(unknown_cost)

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(self._apply_pre(
            segment(text, self.lexicon, self.unknown_cost)))
