"""Continuous-batching decode engine (serving/decode.py).

The load-bearing claims, each pinned:
- ENGINE OUTPUT IS BIT-IDENTICAL to the same requests run one-at-a-time
  through the sequential `rnn_time_step` reference — padding/masking
  cannot bleed across slots, including a mid-flight admission between
  two other requests' steps.
- COMPILE COUNT IS CONSTANT after warmup: admissions, weight swaps and
  traffic mix never retrace (the O(1)-compile contract).
- ZERO-DOWNTIME WEIGHT SWAP: v+1 flips atomically between steps,
  compile-free, with post-swap output equal to a fresh reference run on
  the new params.
- MULTI-TENANT BOOKS: weighted-fair slot allocation and per-tenant
  conservation (admitted == completed + shed + failed).
- DEADLINES: expired work is shed at admission / queued / decode stages,
  never served late.
- REPLAY: a seeded fault plan drives the engine to the same event log
  and books twice (the PR 8 determinism harness).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.charlstm import char_lstm_network
from deeplearning4j_tpu.parallel.inference import (
    DeadlineExceeded,
    RequestRejected,
    RequestValidationError,
)
from deeplearning4j_tpu.serving.decode import DecodeEngine
from deeplearning4j_tpu.utils import faultpoints as fp

VOCAB = 13


def tiny_net(layers=1, hidden=16, seed=12345):
    return char_lstm_network(vocab_size=VOCAB, hidden=hidden,
                             layers=layers, tbptt_length=8, seed=seed)


@pytest.fixture
def net():
    return tiny_net()


def reference_decode(net, prompt, max_new, eos=None):
    """The naive sequential loop: one request at a time, one token per
    rnn_time_step call at batch 1 — the semantics the engine must match
    bit for bit."""
    net.clear_rnn_state()
    out = None
    for t in prompt:
        oh = np.zeros((1, VOCAB), np.float32)
        oh[0, t] = 1.0
        out = np.asarray(net.rnn_time_step(oh))
    toks = []
    while len(toks) < max_new:
        g = int(np.argmax(out[0]))
        toks.append(g)
        if eos is not None and g == eos:
            break
        oh = np.zeros((1, VOCAB), np.float32)
        oh[0, g] = 1.0
        out = np.asarray(net.rnn_time_step(oh))
    net.clear_rnn_state()
    return toks


def test_continuous_batching_bit_identical_to_sequential_reference(net):
    """9 mixed-length requests through 3 slots == each run alone through
    rnn_time_step. Slots turn over mid-flight (requests finish at
    different steps and free slots are re-admitted), so any cross-slot
    bleed or padding artifact would break the equality."""
    rng = np.random.default_rng(42)
    reqs = [(rng.integers(0, VOCAB, size=1 + i % 5).tolist(), 4 + i % 5)
            for i in range(9)]
    refs = [reference_decode(net, p, m) for p, m in reqs]
    eng = DecodeEngine(net, n_slots=3, default_max_tokens=16,
                       component_prefix="t_eq")
    try:
        futs = [eng.generate(p, max_new_tokens=m) for p, m in reqs]
        outs = [f.result(timeout=120) for f in futs]
    finally:
        eng.shutdown()
    assert outs == refs


def test_mid_flight_admission_between_other_requests_steps(net):
    """A request admitted BETWEEN two other requests' decode steps (via
    an on_token trigger, so admission is guaranteed mid-flight) decodes
    bit-identically, and so do the requests it joined."""
    reqs = [([1, 2, 3], 8), ([5, 4], 8)]
    late = ([7, 1], 6)
    refs = [reference_decode(net, p, m) for p, m in reqs]
    ref_late = reference_decode(net, *late)
    eng = DecodeEngine(net, n_slots=3, default_max_tokens=16,
                       component_prefix="t_mid")
    late_fut = []
    fired = threading.Event()

    def on_token(_tok):
        # runs on the engine thread after request 0's FIRST emitted
        # token — both initial requests are mid-decode right now
        if not fired.is_set():
            fired.set()
            late_fut.append(eng.generate(late[0], max_new_tokens=late[1]))

    try:
        f0 = eng.generate(reqs[0][0], max_new_tokens=reqs[0][1],
                          on_token=on_token)
        f1 = eng.generate(reqs[1][0], max_new_tokens=reqs[1][1])
        outs = [f0.result(timeout=120), f1.result(timeout=120)]
        assert fired.wait(timeout=60)
        out_late = late_fut[0].result(timeout=120)
    finally:
        eng.shutdown()
    assert outs == refs
    assert out_late == ref_late


def test_compile_count_constant_after_warmup(net):
    """Admissions at every traffic mix reuse the two warmup programs
    (step + slot reset) — no per-admission retrace. compile_total{kind}
    in the shared registry carries the same evidence."""
    eng = DecodeEngine(net, n_slots=4, default_max_tokens=8,
                       component_prefix="t_cc")
    try:
        eng.generate([1], max_new_tokens=2).result(timeout=60)
        warm = eng.program_cache_size()
        assert warm == 2  # one step program + one reset program
        rng = np.random.default_rng(0)
        futs = [eng.generate(rng.integers(0, VOCAB, size=1 + i % 6).tolist(),
                             max_new_tokens=1 + i % 7)
                for i in range(20)]
        for f in futs:
            f.result(timeout=120)
        assert eng.program_cache_size() == warm
    finally:
        eng.shutdown()


def test_weight_swap_compile_free_mid_traffic(net):
    """load_version mid-traffic: zero failures, zero retraces, version
    bumps, and post-swap requests decode exactly as a fresh sequential
    reference over the NEW params — v+1 really is serving."""
    new_net = tiny_net(seed=999)  # genuinely different weights
    eng = DecodeEngine(net, n_slots=2, default_max_tokens=8,
                       component_prefix="t_swap")
    try:
        eng.generate([1, 2], max_new_tokens=2).result(timeout=60)
        warm = eng.program_cache_size()
        pre = eng.generate([3, 1], max_new_tokens=5)
        v = eng.load_version(new_net.params_list)
        assert pre.result(timeout=120)  # in-flight request still lands
        # drain so the flip (applied between steps) is visible
        eng.generate([1], max_new_tokens=1).result(timeout=60)
        assert eng.version == v == 1
        post = eng.generate([3, 1], max_new_tokens=5).result(timeout=120)
        m = eng.metrics()
    finally:
        eng.shutdown()
    assert post == reference_decode(new_net, [3, 1], 5)
    assert m["failed"] == 0 and m["swaps"] == 1
    assert eng.program_cache_size() == warm


def test_weight_swap_rejects_changed_shapes(net):
    other = char_lstm_network(vocab_size=VOCAB, hidden=24, layers=1,
                              tbptt_length=8)
    eng = DecodeEngine(net, n_slots=2, component_prefix="t_swapbad")
    try:
        with pytest.raises((ValueError, TypeError)):
            eng.load_version(other.params_list)
        assert eng.version == 0
    finally:
        eng.shutdown()


def test_weighted_fair_admission_and_per_tenant_books(net):
    """One slot, all requests queued up front: stride scheduling must
    admit the weight-3 tenant ~3x as often as the weight-1 tenant, and
    the books must conserve per tenant."""
    eng = DecodeEngine(net, n_slots=1, default_max_tokens=2,
                       tenant_weights={"gold": 3.0, "std": 1.0},
                       component_prefix="t_fair")
    order = []
    order_lock = threading.Lock()
    try:
        # warm up, then pause admission pressure by queuing everything
        # while the single slot is held by a long request
        eng.generate([1], max_new_tokens=1, tenant="gold").result(60)
        blocker = eng.generate([1, 2, 3, 4, 5, 6, 7, 8], max_new_tokens=8,
                               tenant="std")
        futs = []
        for i in range(12):
            tenant = "gold" if i < 6 else "std"

            def cb(_tok, _t=tenant, _i=i):
                with order_lock:
                    if not order or order[-1] != (_t, _i):
                        order.append((_t, _i))

            futs.append(eng.generate([2 + i % 3], max_new_tokens=1,
                                     tenant=tenant, on_token=cb))
        blocker.result(timeout=120)
        for f in futs:
            f.result(timeout=120)
        m = eng.metrics()
    finally:
        eng.shutdown()
    # first 4 completions after the blocker: weight-3 tenant gets ~3
    first = [t for t, _ in order[:4]]
    assert first.count("gold") >= 3, order
    tb = m["tenants"]
    assert tb["gold"]["conservation_ok"] and tb["std"]["conservation_ok"]
    assert tb["gold"]["completed"] == 7  # warmup + 6
    assert tb["std"]["completed"] == 7   # blocker + 6
    assert m["conservation_ok"]


def test_deadline_sheds_at_every_stage(net):
    eng = DecodeEngine(net, n_slots=1, default_max_tokens=8,
                       queue_capacity=2, component_prefix="t_dl")
    try:
        eng.generate([1], max_new_tokens=1).result(timeout=60)  # warm
        # admission: already expired -> DeadlineExceeded, booked rejected
        with pytest.raises(DeadlineExceeded):
            eng.generate([1, 2], deadline_ms=0.0)
        # queue_full -> RequestRejected (outside the law)
        slow = fp.FaultPlan(seed=1).add("decode_step", "latency",
                                        p=1.0, latency_ms=40.0)
        with fp.active(slow):
            blocker = eng.generate([1, 2, 3], max_new_tokens=6)
            # wait for the blocker's ADMISSION (into the one slot) so
            # the queue really holds only what we queue next
            deadline = time.monotonic() + 30
            while eng.metrics()["queue_depth"] > 0:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            q1 = eng.generate([1], max_new_tokens=1)
            q2 = eng.generate([2], max_new_tokens=1)
            with pytest.raises(RequestRejected) as ei:
                eng.generate([3], max_new_tokens=1)
            assert ei.value.reason == "queue_full"
            # drain the queue so the next submit is ADMITTED, then shed
            # mid-generation: the deadline expires under the injected
            # per-step latency long before 50 tokens land
            blocker.result(timeout=120)
            q1.result(timeout=120)
            q2.result(timeout=120)
            with pytest.raises(DeadlineExceeded) as dd:
                eng.generate_sync([1, 2, 3, 4], max_new_tokens=50,
                                  deadline_ms=120.0)
            assert dd.value.stage in ("decode", "wait", "queued")
        m = eng.metrics()
    finally:
        eng.shutdown()
    assert m["rejected"] == 2  # expired-at-admission + queue_full
    assert m["shed"] >= 1
    assert m["conservation_ok"]
    assert any(k.split("/")[0] in ("decode", "wait", "queued")
               for k in m["shed_by"])


def test_waiter_shed_while_queued_not_double_booked(net):
    """Regression: a request shed by the generate_sync wait-stage
    backstop WHILE STILL QUEUED must not be booked a second time when
    admission later pops it (one request, one shed — conservation)."""
    plan = fp.FaultPlan(seed=5).add("decode_step", "latency",
                                    p=1.0, latency_ms=120.0)
    eng = DecodeEngine(net, n_slots=1, default_max_tokens=4,
                       component_prefix="t_dbl")
    try:
        eng.generate([1], max_new_tokens=1).result(timeout=60)  # warm
        with fp.active(plan):
            blocker = eng.generate([1, 2, 3], max_new_tokens=4)
            deadline = time.monotonic() + 30
            while eng.metrics()["queue_depth"] > 0:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            # two queued requests whose waiters give up long before the
            # slot frees (blocker holds it ~0.7s; deadline 100ms)
            waiters = []
            for i in range(2):
                def run(_i=i):
                    with pytest.raises(DeadlineExceeded):
                        eng.generate_sync([1 + _i], max_new_tokens=1,
                                          deadline_ms=100.0)
                t = threading.Thread(target=run, daemon=True,
                                     name=f"dl4j-t-dbl-{i}")
                t.start()
                waiters.append(t)
            for t in waiters:
                t.join(timeout=60)
                assert not t.is_alive()
            blocker.result(timeout=120)
        # drain: the engine has popped (and must have skipped) the
        # already-shed queued requests
        eng.generate([2], max_new_tokens=1).result(timeout=60)
        m = eng.metrics()
    finally:
        eng.shutdown()
    assert m["conservation_ok"], m["tenants"]
    assert m["admitted"] == 5  # warm + blocker + 2 queued + drain
    assert m["shed"] == 2      # the two waiter-shed queued requests, ONCE
    assert m["completed"] == 3 and m["failed"] == 0


def test_returning_idle_tenant_cannot_monopolize(net):
    """Stride-scheduling regression: a tenant that idled while another
    decoded must re-enter at the scheduler's current virtual position,
    not its stale-low vtime — equal weights must interleave, not let
    the returner drain its whole backlog first."""
    eng = DecodeEngine(net, n_slots=1, default_max_tokens=1,
                       tenant_weights={"a": 1.0, "b": 1.0},
                       component_prefix="t_mono")
    order = []
    lock = threading.Lock()
    try:
        # tenant a: one early request, then idle
        eng.generate([1], max_new_tokens=1, tenant="a").result(60)
        # tenant b: builds up virtual time across 6 admissions
        for _ in range(6):
            eng.generate([2], max_new_tokens=1, tenant="b").result(60)
        # both tenants queue a backlog behind a blocker
        blocker = eng.generate([1, 2, 3, 4, 5, 6], max_new_tokens=6,
                               tenant="b")
        deadline = time.monotonic() + 30
        while eng.metrics()["queue_depth"] > 0:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        futs = []
        for i in range(8):
            tenant = "a" if i < 4 else "b"

            def cb(_tok, _t=tenant, _i=i):
                with lock:
                    order.append(_t)

            futs.append(eng.generate([3], max_new_tokens=1, tenant=tenant,
                                     on_token=cb))
        blocker.result(timeout=120)
        for f in futs:
            f.result(timeout=120)
    finally:
        eng.shutdown()
    # equal weights: the first 4 admissions must interleave (2 each),
    # not be a's stale-vtime monopoly (pre-fix order: a a a a b b b b)
    assert order[:4].count("b") >= 1, order
    assert order[:6].count("b") >= 2, order


def test_validation_errors(net):
    eng = DecodeEngine(net, n_slots=1, component_prefix="t_val")
    try:
        with pytest.raises(RequestValidationError):
            eng.generate([])
        with pytest.raises(RequestValidationError):
            eng.generate([VOCAB + 3])
        with pytest.raises(RequestValidationError):
            eng.generate([1], max_new_tokens=0)
        with pytest.raises(RequestValidationError):
            eng.generate([1], deadline_ms=float("nan"))
        m = eng.metrics()
        assert m["admitted"] == 0 and m["requests"] == 0
    finally:
        eng.shutdown()


def test_eos_token_frees_slot_early(net):
    """With every token declared EOS, each request emits exactly one
    token (EOS included in the output) and the slot turns over."""
    ref = reference_decode(net, [2, 5], 8)
    eng = DecodeEngine(net, n_slots=1, eos_token=ref[0],
                       default_max_tokens=8, component_prefix="t_eos")
    try:
        out = eng.generate([2, 5]).result(timeout=60)
        m = eng.metrics()
    finally:
        eng.shutdown()
    assert out == [ref[0]]
    assert m["completed"] == 1 and m["slots_in_use"] == 0


def _replay_run(seed):
    """One deterministic engine run under a seeded plan: requests are
    served strictly one at a time (submit -> wait -> submit), so the
    decode_step invocation sequence is a pure function of the request
    list and the plan — the replay contract."""
    net = tiny_net()
    plan = (fp.FaultPlan(seed=seed)
            .add("decode_step", "error", every_nth=9, max_fires=2)
            .add("decode_step", "latency", every_nth=5, latency_ms=1.0))
    eng = DecodeEngine(net, n_slots=2, default_max_tokens=16,
                       component_prefix=f"t_replay{seed}")
    outcomes = []
    try:
        eng.generate([1], max_new_tokens=1).result(timeout=60)  # warm
        with fp.active(plan):
            for i in range(6):
                try:
                    toks = eng.generate([1 + i % 4, 2],
                                        max_new_tokens=3 + i % 3
                                        ).result(timeout=60)
                    outcomes.append(("ok", toks))
                except Exception as e:
                    outcomes.append(("err", type(e).__name__))
        m = eng.metrics()
    finally:
        eng.shutdown()
    return plan.event_log(), outcomes, {
        k: m[k] for k in ("admitted", "completed", "failed", "shed")}


def test_chaos_replay_bit_identical():
    log1, out1, books1 = _replay_run(7)
    log2, out2, books2 = _replay_run(7)
    assert log1 == log2
    assert out1 == out2
    assert books1 == books2
    # the plan actually fired (non-vacuous) and the books conserved
    assert any(e["kind"] == "error" for e in log1)
    assert books1["failed"] >= 1
    assert books1["admitted"] == (books1["completed"] + books1["failed"]
                                  + books1["shed"])


def test_step_failure_is_contained(net):
    """An injected decode_step error fails the ACTIVE sequences and
    nothing else: queued work and later traffic keep serving, the
    engine stays healthy, books conserve."""
    plan = fp.FaultPlan(seed=3).add("decode_step", "error",
                                    every_nth=2, max_fires=1)
    eng = DecodeEngine(net, n_slots=2, default_max_tokens=4,
                       component_prefix="t_err")
    try:
        eng.generate([1], max_new_tokens=1).result(timeout=60)
        with fp.active(plan):
            with pytest.raises(RuntimeError):
                eng.generate([1, 2], max_new_tokens=6).result(timeout=60)
        # after the plan: life goes on, bit-identically
        out = eng.generate([2, 5], max_new_tokens=3).result(timeout=60)
        m = eng.metrics()
    finally:
        eng.shutdown()
    assert out == reference_decode(net, [2, 5], 3)
    assert m["failed"] == 1 and m["conservation_ok"]


def test_shutdown_refuses_new_and_drains(net):
    eng = DecodeEngine(net, n_slots=2, default_max_tokens=3,
                       component_prefix="t_shut")
    fut = eng.generate([1, 2], max_new_tokens=3)
    eng.shutdown()
    assert fut.result(timeout=60) == reference_decode(net, [1, 2], 3)
    from deeplearning4j_tpu.parallel.inference import ReplicaUnavailable

    with pytest.raises(ReplicaUnavailable):
        eng.generate([1])


# -- REST integration ---------------------------------------------------------


@pytest.fixture
def server(net):
    from deeplearning4j_tpu.serving.inference_server import InferenceServer

    srv = InferenceServer(net, decode_slots=3, decode_max_tokens=8)
    srv.start()
    yield srv
    srv.stop()


def _post(port, route, payload, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{route}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=60)


def test_rest_generate_matches_reference(net, server):
    ref = reference_decode(net, [1, 2, 3], 5)
    out = json.loads(_post(server.port, "/generate",
                           {"prompt": [1, 2, 3], "max_tokens": 5}).read())
    assert out["tokens"] == ref
    assert out["version"] == 0


def test_rest_generate_streams_chunked_tokens(net, server):
    ref = reference_decode(net, [2, 5], 4)
    resp = _post(server.port, "/generate",
                 {"prompt": [2, 5], "max_tokens": 4, "stream": True})
    assert resp.headers.get("Content-Type") == "application/x-ndjson"
    lines = [json.loads(l) for l in resp.read().decode().splitlines()]
    assert [l["token"] for l in lines[:-1]] == ref
    assert lines[-1]["done"] is True and lines[-1]["tokens"] == ref


def test_rest_generate_deadline_contract(server):
    # expired -> 429 + Retry-After, the same shed contract as /predict
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, "/generate", {"prompt": [1], "deadline_ms": 0})
    assert ei.value.code == 429
    assert int(ei.value.headers["Retry-After"]) >= 1
    assert json.loads(ei.value.read())["shed"] is True
    # the header route works too (case-insensitive)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, "/generate", {"prompt": [1]},
              headers={"x-deadline-ms": "0"})
    assert ei.value.code == 429
    # malformed -> 400, including prompts numpy cannot even coerce
    # (string/ragged/null must be a client fault, never a 500)
    for bad in ({"prompt": []}, {"prompt": [1], "deadline_ms": "x"},
                {"no_prompt": 1}, {"prompt": "abc"},
                {"prompt": [[1, 2], [3]]}, {"prompt": None}):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, "/generate", bad)
        assert ei.value.code == 400, bad


def test_rest_stream_sheds_on_wedged_engine(net, server):
    """A deadline-carrying STREAM must terminate with a shed line a
    grace past its deadline even when the engine is wedged inside a
    hung step — not pin the handler thread until the hang clears."""
    plan = fp.FaultPlan(seed=9).add("decode_step", "hang",
                                    every_nth=1, max_fires=1,
                                    hang_seconds=5.0)
    t0 = time.monotonic()
    with fp.active(plan):
        resp = _post(server.port, "/generate",
                     {"prompt": [1, 2], "max_tokens": 4, "stream": True,
                      "deadline_ms": 300})
        lines = [json.loads(l) for l in resp.read().decode().splitlines()]
    elapsed = time.monotonic() - t0
    assert elapsed < 4.0, "stream outlived the deadline backstop"
    assert lines[-1].get("shed") is True, lines
    m = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics", timeout=30).read())
    assert m["decode"]["conservation_ok"]


def test_rest_metrics_carry_decode_books(server):
    _post(server.port, "/generate", {"prompt": [1], "max_tokens": 2}).read()
    m = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics", timeout=30).read())
    d = m["decode"]
    assert d["completed"] >= 1 and d["conservation_ok"]
    assert d["slots"] == 3
    assert "tenants" in d


def test_decode_requires_recurrent_model():
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_in=4, n_out=4, activation="tanh"))
            .layer(OutputLayer(n_in=4, n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    with pytest.raises(ValueError, match="recurrent"):
        DecodeEngine(MultiLayerNetwork(conf).init(), n_slots=2)


def test_smoke_entrypoint_runs():
    """The scripts/t1.sh gate body, in-process (small)."""
    from deeplearning4j_tpu.serving import decode as dec

    v = dec.smoke(n_slots=3, vocab=7, hidden=8, requests=6)
    assert v["ok"] and v["zero_retraces"]


# -- fused decode steps (PR 16) -----------------------------------------------


@pytest.mark.parametrize("k", [2, 3, 4])
def test_fused_steps_bit_identical_to_per_step(net, k):
    """set_fused_steps(K) scans K decode steps into one jitted dispatch;
    the in-graph argmax feedback must reproduce the per-step host
    feedback EXACTLY — mixed prefill/decode positions, slot turnover
    mid-window, the lot."""
    rng = np.random.default_rng(7)
    reqs = [(rng.integers(0, VOCAB, size=1 + i % 5).tolist(), 3 + i % 6)
            for i in range(9)]
    refs = [reference_decode(net, p, m) for p, m in reqs]
    eng = DecodeEngine(net, n_slots=3, default_max_tokens=16,
                       component_prefix=f"t_fused{k}")
    eng.set_fused_steps(k)
    try:
        futs = [eng.generate(p, max_new_tokens=m) for p, m in reqs]
        outs = [f.result(timeout=120) for f in futs]
    finally:
        eng.shutdown()
    assert outs == refs


def test_fused_steps_eos_mid_window_discards_tail(net):
    """EOS landing mid-window: the tail tokens the fused dispatch
    computed past it are discarded host-side — output and books match
    the per-step engine."""
    ref = reference_decode(net, [2, 5], 8)
    eng = DecodeEngine(net, n_slots=1, eos_token=ref[0],
                       default_max_tokens=8, component_prefix="t_feos")
    eng.set_fused_steps(4)
    try:
        out = eng.generate([2, 5]).result(timeout=60)
        m = eng.metrics()
    finally:
        eng.shutdown()
    assert out == [ref[0]]
    assert m["completed"] == 1 and m["slots_in_use"] == 0
    assert m["tokens"] == 1  # tail window tokens never hit the books
