"""On-device batch transforms — the heavy per-pixel ETL tail, jitted.

The DataVec analog kept normalization and augmentation in host code
(ImagePreProcessingScaler, transform pipelines run by the ETL threads);
here the same math runs as ONE jitted program on the already-staged
device batch, composed by DevicePrefetchIterator after placement — so the
accelerator does the per-pixel work and host numpy never touches it.

`DeviceBatchTransform` is shape-keyed: one compile per distinct
(features shape, dtype), counted under `compile_total{kind=
"input_transform"}`. Randomness is deterministic: a per-transform step
counter is folded into the seed key (`fold_in(key, step)`), and the step
rides into the jitted function as a traced scalar — step 7 augments the
same way whether the pipeline is on, off, or replayed, which is what
makes prefetch-on vs prefetch-off training byte-identical when no
augmentation is configured and bit-reproducible across runs when it is.

Augmentation layout contract: flip/crop require NHWC image batches
(ndim == 4); `normalize` works on any feature layout.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.utils import metrics as _metrics


class DeviceBatchTransform:
    """Jitted feature-batch transform: normalize -> random flip ->
    random crop, any subset.

    Args:
        normalize: (mean, std) — arrays/scalars broadcastable against the
            feature batch; computes (x - mean) / std.
        random_flip: horizontal flip with p=0.5 per example (NHWC).
        random_crop: pad each spatial edge by `random_crop` pixels
            (zeros), then take a per-example random HxW crop back to the
            original size — the standard CIFAR-style augmentation.
        seed: RNG seed; per-batch keys derive via fold_in(key, step).
    """

    def __init__(self, normalize: Optional[Tuple] = None,
                 random_flip: bool = False,
                 random_crop: Optional[int] = None, seed: int = 0):
        self.normalize = normalize
        self.random_flip = bool(random_flip)
        self.random_crop = None if not random_crop else int(random_crop)
        self.seed = int(seed)
        self._fns: dict = {}
        self._lock = threading.Lock()
        self._step = 0

    @property
    def randomized(self) -> bool:
        return self.random_flip or self.random_crop is not None

    def _build(self, shape, dtype):
        import jax
        import jax.numpy as jnp

        if self.randomized and len(shape) != 4:
            raise ValueError(
                f"random flip/crop need NHWC image batches, got shape "
                f"{shape}; use normalize-only for non-image features")
        mean = std = None
        if self.normalize is not None:
            m, s = self.normalize
            mean = jnp.asarray(np.asarray(m, np.float32))
            std = jnp.asarray(np.asarray(s, np.float32))
        pad = self.random_crop

        def fn(x, step):
            if mean is not None:
                x = (x - mean.astype(x.dtype)) / std.astype(x.dtype)
            if not self.randomized:
                return x
            key = jax.random.fold_in(jax.random.PRNGKey(self.seed), step)
            n, h, w = x.shape[0], x.shape[1], x.shape[2]
            if self.random_flip:
                key, k = jax.random.split(key)
                flip = jax.random.bernoulli(k, 0.5, (n,))
                x = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
            if pad is not None:
                key, k = jax.random.split(key)
                xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
                offs = jax.random.randint(k, (n, 2), 0, 2 * pad + 1)

                def crop_one(img, off):
                    return jax.lax.dynamic_slice(
                        img, (off[0], off[1], 0), (h, w, x.shape[3]))

                x = jax.vmap(crop_one)(xp, offs)
            return x

        return jax.jit(fn)

    def _fn_for(self, x):
        key = (tuple(x.shape), str(getattr(x, "dtype", None)))
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                fn = self._fns[key] = self._build(x.shape, x.dtype)
                _metrics.get_registry().counter(
                    "compile_total", "jit cache insertions (fresh traces)",
                    ("kind",)).labels("input_transform").inc()
        return fn

    def __call__(self, ds):
        """Transform a DataSet/MultiDataSet's features (labels and masks
        pass through). One step value per call, shared by every features
        array of a MultiDataSet — deterministic regardless of pipeline
        staging."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.data.prefetch import _carry_metadata

        with self._lock:
            step = self._step
            self._step += 1
        step_arr = jnp.asarray(step, jnp.uint32)
        apply = lambda x: self._fn_for(x)(x, step_arr)
        if isinstance(ds, MultiDataSet):
            out = MultiDataSet([apply(f) for f in ds.features], ds.labels,
                               ds.features_masks, ds.labels_masks)
        else:
            out = DataSet(apply(ds.features), ds.labels,
                          ds.features_mask, ds.labels_mask)
        return _carry_metadata(ds, out)

    def reset_steps(self):
        """Rewind the per-batch step counter (replaying an identical run)."""
        with self._lock:
            self._step = 0

    @property
    def compile_count(self) -> int:
        with self._lock:
            return len(self._fns)
