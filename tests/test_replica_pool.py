"""Self-healing serving (ISSUE 7): ReplicaPool eviction + respawn.

The contract under test: kill (wedge) one replica of a pool under
traffic and no caller sees an error beyond the requests that were
in-flight inside that replica's device forward — queued work re-routes
to a healthy sibling, the unhealthy replica is evicted when the PR 6
watchdog flips its component, a fresh replica respawns into the slot,
and the whole cycle is visible in `component_health` transitions and
`serving_replica_*` counters on the same /metrics scrape as the traffic
series."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.inference import ReplicaPool
from deeplearning4j_tpu.serving import InferenceServer
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics

N_IN = 6


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Updater.SGD).learning_rate(0.05).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


class WedgeableModel:
    """Delegates to a real net, but can be wedged: while `wedged` is set,
    `output` blocks on `release` — the dispatcher-stuck-in-a-device-
    forward failure the PR 6 watchdog exists to catch."""

    def __init__(self, net):
        self._net = net
        self.wedged = threading.Event()
        self.release = threading.Event()

    def _require_init(self):
        self._net._require_init()

    @property
    def params_list(self):
        return self._net.params_list

    @params_list.setter
    def params_list(self, v):
        self._net.params_list = v

    @property
    def output_compile_count(self):
        return getattr(self._net, "output_compile_count", 0)

    def output(self, x):
        if self.wedged.is_set():
            self.release.wait(timeout=30.0)
        return self._net.output(x)


def _wait_until(pred, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _wedgeable_pool(net, n=2, **kw):
    """Pool whose factory hands out fresh WedgeableModel wrappers over
    one shared net (so respawns get a working replacement); returns
    (pool, made) where made[i] is the i-th wrapper spawned."""
    made = []

    def factory():
        m = WedgeableModel(net)
        made.append(m)
        return m

    kw.setdefault("max_batch_size", 8)
    kw.setdefault("batch_timeout_ms", 1.0)
    return ReplicaPool(model_factory=factory, n_replicas=n, **kw), made


def test_pool_serves_and_aggregates(tmp_path):
    net = _net()
    pool = ReplicaPool(net, n_replicas=2, max_batch_size=8,
                       batch_timeout_ms=1.0, component_prefix="tp_basic")
    try:
        pool.warmup((N_IN,))
        x = np.random.default_rng(0).standard_normal((3, N_IN)).astype(
            np.float32)
        outs = [pool.output(x) for _ in range(6)]
        for o in outs:
            assert np.asarray(o).shape == (3, 3)
        m = pool.metrics()
        assert m["requests"] == 6 and m["in_rotation"] == 2
        assert m["evictions"] == 0
        # round-robin spread the traffic over both replicas
        served = [r["requests"] for r in m["replicas"]]
        assert all(s > 0 for s in served) and sum(served) == 6
        comps = _health.get_health().status()["components"]
        assert "tp_basic_r0_dispatcher" in comps
        assert "tp_basic_r1_dispatcher" in comps
    finally:
        pool.shutdown()
    # shutdown unregisters every replica's heartbeats
    comps = _health.get_health().status()["components"]
    assert not any(c.startswith("tp_basic_") for c in comps)


def test_explicit_evict_respawns_and_keeps_serving():
    net = _net()
    pool = ReplicaPool(net, n_replicas=2, max_batch_size=8,
                       batch_timeout_ms=1.0, component_prefix="tp_evict")
    try:
        x = np.ones((2, N_IN), np.float32)
        pool.output(x)
        gen0 = pool.metrics()["replicas"][0]["generation"]
        pool.evict(0, "test eviction")
        assert _wait_until(lambda: pool.metrics()["in_rotation"] == 2)
        m = pool.metrics()
        assert m["evictions"] == 1 and m["respawns"] == 1
        assert m["replicas"][0]["generation"] == gen0 + 1
        for _ in range(4):
            assert np.asarray(pool.output(x)).shape == (2, 3)
    finally:
        pool.shutdown()


def test_wedged_replica_evicted_by_watchdog_only_inflight_fails():
    """The acceptance criterion: wedge one replica's device forward
    under traffic. The request inside that forward fails; every other
    request (queued on the wedged replica or arriving during the
    eviction) is served by a sibling; the watchdog->eviction->respawn
    cycle shows up in component_health transitions and the
    serving_replica_* counters."""
    net = _net()
    evict_before = _metrics.get_registry().get(
        "serving_replica_evictions_total")
    seq0 = _health.get_health().last_seq()
    pool, models = _wedgeable_pool(net, component_prefix="tp_wedge",
                                   health_stall_after=0.15)
    x = np.ones((1, N_IN), np.float32)
    results, errors = [], []
    try:
        pool.warmup((N_IN,))
        # wedge replica 0's model, then throw traffic at the pool from
        # many threads — some requests land on replica 0 and queue
        # behind (or inside) the wedged forward
        models[0].wedged.set()

        def call(i):
            try:
                results.append((i, np.asarray(pool.output(x))))
            except Exception as e:
                errors.append((i, e))

        threads = [threading.Thread(target=call, args=(i,),
                                    name=f"dl4j-test-client-{i}")
                   for i in range(12)]
        for t in threads:
            t.start()
            time.sleep(0.01)
        # the watchdog flips tp_wedge_r0_* unhealthy (0.6s at stall 0.15),
        # the pool evicts and respawns
        assert _wait_until(
            lambda: pool.metrics()["evictions"] >= 1, timeout=15.0), \
            "watchdog never triggered an eviction"
        models[0].wedged.clear()
        models[0].release.set()  # let the wedged daemon thread die
        for t in threads:
            t.join(timeout=30)
        assert _wait_until(lambda: pool.metrics()["in_rotation"] == 2,
                           timeout=15.0)
        # ONLY in-flight requests may fail — at most one fused group was
        # inside the wedged forward (batch_timeout fuses aggressively,
        # but the remaining 11+ went to the sibling or were re-routed)
        assert len(results) >= 8, (
            f"{len(errors)} failures: {[repr(e) for _, e in errors]}")
        for _, e in errors:
            assert "in flight" in str(e) or "evicted" in str(e), repr(e)
        # post-respawn: the pool serves cleanly again
        for _ in range(4):
            assert np.asarray(pool.output(x)).shape == (1, 3)
        # observability: the counter moved and the transition history
        # shows replica 0's component degrading
        assert pool.metrics()["respawns"] >= 1
        trs = _health.get_health().transitions_since(seq0)
        assert any(t["component"].startswith("tp_wedge_r0_")
                   and t["to"] == _health.UNHEALTHY for t in trs)
        assert evict_before.labels("0").value >= 1
    finally:
        models[0].release.set()
        pool.shutdown()


def test_server_with_replicas_no_5xx_across_eviction():
    """REST-level: an InferenceServer backed by a ReplicaPool keeps
    serving 200s while a replica is evicted and respawned, and the
    /metrics scrape carries the pool's lifecycle numbers."""
    net = _net()
    server = InferenceServer(net, max_batch_size=8, batch_timeout_ms=1.0,
                             n_replicas=2, warmup_shape=(N_IN,))
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    try:
        body = json.dumps(
            {"features": np.ones((2, N_IN)).tolist()}).encode()

        def predict():
            req = urllib.request.Request(
                f"{url}/predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status

        assert predict() == 200
        server.inference.evict(0, "operator kill")  # no in-flight work
        statuses = [predict() for _ in range(8)]
        assert statuses == [200] * 8, statuses
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as resp:
            m = json.loads(resp.read())
        assert m["evictions"] >= 1 and m["n_replicas"] == 2
        with urllib.request.urlopen(
                f"{url}/metrics?format=prometheus", timeout=10) as resp:
            text = resp.read().decode()
        assert "serving_replica_evictions_total" in text
        assert "serving_replicas_in_rotation" in text
        assert "component_health" in text
    finally:
        server.stop()


def test_pool_validation_errors_propagate():
    net = _net()
    pool = ReplicaPool(net, n_replicas=2, max_batch_size=8,
                       component_prefix="tp_val", retry_window=1.0)
    try:
        from deeplearning4j_tpu.parallel.inference import (
            RequestValidationError,
        )

        with pytest.raises(RequestValidationError):
            pool.output(np.ones((0, N_IN), np.float32))
    finally:
        pool.shutdown()


def test_pool_shutdown_rejects_new_work():
    net = _net()
    pool = ReplicaPool(net, n_replicas=1, max_batch_size=8,
                       component_prefix="tp_down", retry_window=0.5)
    pool.shutdown()
    with pytest.raises(RuntimeError):
        pool.output(np.ones((1, N_IN), np.float32))
