"""Activation function tests (reference behavior: org.nd4j activations)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.activations import (
    Activation,
    activation_fn,
    apply_activation,
    register_activation,
)

ALL_SIMPLE = [
    "identity", "sigmoid", "tanh", "relu", "leakyrelu", "elu", "selu",
    "softplus", "softsign", "hardtanh", "hardsigmoid", "cube",
    "rationaltanh", "rectifiedtanh", "swish", "gelu", "mish", "softmax",
    "logsoftmax", "relu6", "thresholdedrelu",
]


@pytest.mark.parametrize("name", ALL_SIMPLE)
def test_shapes_and_finiteness(name):
    x = jnp.linspace(-3.0, 3.0, 24).reshape(4, 6)
    y = apply_activation(name, x)
    assert y.shape == x.shape
    assert bool(jnp.all(jnp.isfinite(y)))


def test_known_values():
    x = jnp.array([[-1.0, 0.0, 2.0]])
    np.testing.assert_allclose(apply_activation("relu", x), [[0.0, 0.0, 2.0]])
    np.testing.assert_allclose(apply_activation("cube", x), [[-1.0, 0.0, 8.0]])
    np.testing.assert_allclose(apply_activation("hardtanh", x), [[-1.0, 0.0, 1.0]])
    np.testing.assert_allclose(
        apply_activation("hardsigmoid", x), [[0.3, 0.5, 0.9]], atol=1e-6
    )
    np.testing.assert_allclose(
        apply_activation("identity", x), x
    )


def test_softmax_rows_sum_to_one():
    x = jax.random.normal(jax.random.PRNGKey(0), (5, 7))
    y = apply_activation("softmax", x)
    np.testing.assert_allclose(jnp.sum(y, axis=-1), np.ones(5), atol=1e-6)


def test_rrelu_train_vs_inference():
    x = jnp.array([[-2.0, 3.0]])
    fn = activation_fn("rrelu")
    # Inference: deterministic slope (l+u)/2 = (1/8 + 1/3)/2
    y = fn(x, training=False)
    slope = (1.0 / 8.0 + 1.0 / 3.0) / 2.0
    np.testing.assert_allclose(y, [[-2.0 * slope, 3.0]], rtol=1e-6)
    # Training: random slope in [1/8, 1/3], positive side unchanged
    yt = fn(x, key=jax.random.PRNGKey(1), training=True)
    assert float(yt[0, 1]) == 3.0
    assert -2.0 / 3.0 - 1e-6 <= float(yt[0, 0]) <= -2.0 / 8.0 + 1e-6


def test_rationaltanh_bounded():
    x = jnp.linspace(-10, 10, 101)
    y = apply_activation("rationaltanh", x)
    assert bool(jnp.all(jnp.abs(y) <= 1.7159 + 1e-5))
    # odd function
    np.testing.assert_allclose(y, -y[::-1], atol=1e-5)


def test_custom_activation_spi():
    register_activation("doubler", lambda x, key=None, training=False: 2 * x)
    np.testing.assert_allclose(
        apply_activation("doubler", jnp.array([1.0, 2.0])), [2.0, 4.0]
    )


def test_unknown_raises():
    with pytest.raises(ValueError):
        activation_fn("nope")


def test_enum_names_resolve():
    for name in vars(Activation):
        if not name.startswith("_"):
            activation_fn(getattr(Activation, name))
