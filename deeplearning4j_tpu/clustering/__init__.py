"""Clustering + neighbor-search algorithms (TPU-first).

Capability parity with the reference's deeplearning4j-core clustering
package (clustering/kmeans, clustering/vptree, clustering/kdtree,
plot/BarnesHutTsne) — redesigned so the distance work rides the MXU as
batched matmuls instead of per-point Java loops.
"""

from deeplearning4j_tpu.clustering.kmeans import ClusterSet, KMeansClustering
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.tsne import Tsne
from deeplearning4j_tpu.clustering.vptree import VPTree

__all__ = [
    "KMeansClustering",
    "ClusterSet",
    "VPTree",
    "KDTree",
    "Tsne",
]
