"""Asynchronous parameter server for embedding training (DCN path).

Design (the written PS/embedding-async plan; reference:
ParameterServerTrainer.java:32-66 pushNDArray over Aeron,
SparkSequenceVectors.java:292-294 VoidParameterServer):

Why a PS at all, when gradient allreduce covers dense training? Embedding
workloads touch a SPARSE, tiny slice of an enormous table each step;
allreducing a dense table-sized gradient per step is absurd, and the
hot-word rows tolerate stale updates (async SGD is the reference's own
semantics — it documents the nondeterminism, DeepWalk.java:223). So:

  server:  row-sharded tables (syn0/syn1/syn1neg) in host memory, one
           process per DCN endpoint; applies row DELTAS in arrival order
           (Hogwild-style), serves row PULLS. HTTP here; the transport is
           the pluggable part (the reference swapped Aeron in the same
           slot) — gRPC/DCN drops into _Transport without touching
           trainer logic.
  client:  per-batch: PULL the rows the batch touches, run the jitted
           device skip-gram/CBOW step (nlp/learning.py — the
           AggregateSkipGram analog) on those rows only, PUSH back the
           row deltas fire-and-forget on a bounded queue.
  sharding: row id -> shard by modulo over server endpoints; each
           endpoint owns rows i with i % n_servers == k, so pushes from
           all workers for one row serialize at one owner (no
           cross-server coordination).

Staleness bound: one in-flight push window per worker (the queue), i.e.
a worker's pulls lag its own pushes by <= queue depth; convergence for
embedding objectives is unaffected in practice (the reference ships the
same tradeoff).
"""

from __future__ import annotations

import json
import logging
import queue
import struct
import threading
import time
import urllib.request
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils.concurrency import (
    QueueAborted,
    get_abortable,
    put_abortable,
)
from deeplearning4j_tpu.utils.jsonhttp import JsonHttpServer, json_response

logger = logging.getLogger("deeplearning4j_tpu")


# -- binary wire format -------------------------------------------------------
# A real [vocab, dim] f32 table pushed as JSON lists is ~10x the bytes and
# far more CPU than raw rows; the hot routes (/pull.bin, /push.bin) move
# raw little-endian buffers instead. JSON routes remain for debugging and
# as the "transport is the pluggable part" demonstration.
#
#   request  := u16 name_len | name utf8 | u32 n_rows | u32 dim
#               | i64 * n_rows row ids | f32 * n_rows * dim deltas
#               (dim == 0 for pulls: no payload follows the ids)
#   pull rsp := u32 n_rows | u32 dim | f32 * n_rows * dim raw rows

def _pack_request(table: str, rows: np.ndarray,
                  deltas: Optional[np.ndarray] = None) -> bytes:
    name = table.encode()
    rows = np.ascontiguousarray(rows, dtype="<i8")
    if deltas is None:
        head = struct.pack("<H", len(name)) + name + struct.pack(
            "<II", rows.size, 0)
        return head + rows.tobytes()
    deltas = np.ascontiguousarray(deltas, dtype="<f4")
    if deltas.ndim != 2 or deltas.shape[0] != rows.size:
        raise ValueError(f"deltas must be [n_rows, dim], got {deltas.shape} "
                         f"for {rows.size} rows")
    head = struct.pack("<H", len(name)) + name + struct.pack(
        "<II", rows.size, deltas.shape[1])
    return head + rows.tobytes() + deltas.tobytes()


def _unpack_request(body: bytes):
    (name_len,) = struct.unpack_from("<H", body, 0)
    name = body[2:2 + name_len].decode()
    n, dim = struct.unpack_from("<II", body, 2 + name_len)
    off = 2 + name_len + 8
    rows = np.frombuffer(body, "<i8", count=n, offset=off)
    off += 8 * n
    deltas = None
    if dim:
        deltas = np.frombuffer(body, "<f4", count=n * dim,
                               offset=off).reshape(n, dim)
    return name, rows, deltas


def _pack_rows(rows: np.ndarray) -> bytes:
    rows = np.ascontiguousarray(rows, dtype="<f4")
    n, dim = rows.shape
    return struct.pack("<II", n, dim) + rows.tobytes()


def _unpack_rows(body: bytes) -> np.ndarray:
    n, dim = struct.unpack_from("<II", body, 0)
    return np.frombuffer(body, "<f4", count=n * dim, offset=8).reshape(n, dim)


class EmbeddingParameterServer:
    """One shard-owner process. Tables are {name: [rows, dim]} float32."""

    def __init__(self, tables: Dict[str, np.ndarray], port: int = 0):
        self.tables = {k: np.asarray(v, np.float32) for k, v in tables.items()}
        self._locks = {k: threading.Lock() for k in self.tables}
        self._server = JsonHttpServer(post=self._post, port=port)
        self.pushes_applied = 0
        # RPC counters + latency histograms in the shared registry, by
        # route — the PS hot path (pull.bin/push.bin) becomes a series an
        # operator can alert on instead of a private attribute
        reg = _metrics.get_registry()
        self._m_rpc = reg.counter(
            "paramserver_rpc_total", "parameter-server RPCs served",
            ("route",))
        self._m_rpc_sec = reg.histogram(
            "paramserver_rpc_seconds", "parameter-server RPC service time",
            ("route",))

    @property
    def port(self) -> int:
        return self._server.port

    # -- core ops ------------------------------------------------------------

    def pull(self, name: str, rows: List[int]) -> np.ndarray:
        with self._locks[name]:
            return self.tables[name][rows].copy()

    def push(self, name: str, rows: List[int], deltas: np.ndarray) -> None:
        """Apply row deltas in arrival order (async SGD)."""
        with self._locks[name]:
            np.add.at(self.tables[name], rows, deltas)
            self.pushes_applied += 1

    # -- http transport ------------------------------------------------------

    def _post(self, path, body, headers):
        if path in ("/pull.bin", "/push.bin", "/pull", "/push"):
            route = path.lstrip("/")
            t0 = time.perf_counter()
            try:
                return self._post_timed(path, body)
            finally:
                self._m_rpc.labels(route).inc()
                self._m_rpc_sec.labels(route).observe(
                    time.perf_counter() - t0)
        if path == "/meta":
            return json_response({
                "tables": {k: list(v.shape) for k, v in self.tables.items()},
                "pushes_applied": self.pushes_applied,
            })
        return None

    def _post_timed(self, path, body):
        if path == "/pull.bin":
            name, rows, _ = _unpack_request(body)
            return 200, "application/octet-stream", _pack_rows(
                self.pull(name, rows.tolist()))
        if path == "/push.bin":
            name, rows, deltas = _unpack_request(body)
            self.push(name, rows.tolist(), deltas)
            return 200, "application/octet-stream", b"ok"
        req = json.loads(body)
        name = req["table"]
        rows = req["rows"]
        if path == "/pull":
            return json_response({"data": self.pull(name, rows).tolist()})
        self.push(name, rows, np.asarray(req["deltas"], np.float32))
        return json_response({"status": "ok"})

    def start(self) -> int:
        return self._server.start()

    def stop(self):
        self._server.stop()


class EmbeddingPSClient:
    """Worker-side pull/push. Pushes ride a bounded background queue
    (fire-and-forget, the Aeron pushNDArray analog); pulls are
    synchronous (the step needs the rows). The wire format is raw
    little-endian rows (see _pack_request) — JSON would be ~10x the bytes
    for real [vocab, dim] tables.

    `dropped_pushes` counts push batches lost to dead/misbehaving
    endpoints — training degrades (loses some async gradient mass)
    rather than hanging, and the loss is observable instead of silent."""

    def __init__(self, urls: List[str], queue_size: int = 64,
                 timeout: float = 10.0):
        self.urls = [u.rstrip("/") for u in urls]
        self.timeout = timeout
        self.dropped_pushes = 0
        self._dims: Dict[str, int] = {}
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        reg = _metrics.get_registry()
        self._m_rpc = reg.counter(
            "paramserver_client_rpc_total",
            "parameter-server client RPCs issued", ("route",))
        self._m_rpc_sec = reg.histogram(
            "paramserver_client_rpc_seconds",
            "parameter-server client RPC round-trip time", ("route",))
        self._m_dropped = reg.counter(
            "paramserver_client_push_dropped_total",
            "push batches lost to dead/misbehaving endpoints").labels()
        self._stop = threading.Event()
        # liveness: the drain holds a busy slot only while delivering a
        # push batch — a wedged endpoint (socket past its timeout, DNS
        # hang) flips `component_health{component=paramserver_push}`
        self._hb = _health.get_health().register(
            "paramserver_push", stall_after=max(60.0, 4.0 * timeout))
        self._worker = threading.Thread(target=self._drain, daemon=True,
                                        name="dl4j-paramserver-push")
        self._worker.start()

    def _owner(self, row: int) -> int:
        return row % len(self.urls)

    def _post_bin(self, url: str, route: str, payload: bytes) -> bytes:
        req = urllib.request.Request(
            f"{url}{route}", data=payload,
            headers={"Content-Type": "application/octet-stream"})
        label = route.lstrip("/")
        t0 = time.perf_counter()
        try:  # count failures too (server side does the same): an outage
            # must show up in the RPC series, not just the drop counter
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.read()
        finally:
            self._m_rpc.labels(label).inc()
            self._m_rpc_sec.labels(label).observe(time.perf_counter() - t0)

    def _dim(self, table: str) -> int:
        """Table dim, cached from the first shard's /meta (needed to shape
        empty pulls)."""
        if table not in self._dims:
            req = urllib.request.Request(self.urls[0] + "/meta", data=b"{}")
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                meta = json.loads(r.read())
            for k, shape in meta["tables"].items():
                self._dims[k] = int(shape[1])
        return self._dims[table]

    def pull(self, table: str, rows: np.ndarray) -> np.ndarray:
        """Fetch rows (grouped per owning shard, order restored). Empty
        row sets return a well-formed [0, dim] array."""
        rows = np.asarray(rows, np.int64)
        if rows.size == 0:
            return np.zeros((0, self._dim(table)), np.float32)
        out: Optional[np.ndarray] = None
        for s, url in enumerate(self.urls):
            sel = np.nonzero(rows % len(self.urls) == s)[0]
            if sel.size == 0:
                continue
            got = _unpack_rows(self._post_bin(
                url, "/pull.bin", _pack_request(table, rows[sel])))
            if out is None:
                out = np.zeros((rows.size, got.shape[1]), np.float32)
            out[sel] = got
        self._dims.setdefault(table, int(out.shape[1]))
        return out

    def push_async(self, table: str, rows: np.ndarray,
                   deltas: np.ndarray) -> None:
        deltas = np.asarray(deltas, np.float32)
        if deltas.ndim != 2 or deltas.shape[0] != np.asarray(rows).size:
            raise ValueError(  # fail at the call site, not in the drain
                f"deltas must be [n_rows, dim], got {deltas.shape}")
        item = (table, np.asarray(rows, np.int64),
                np.asarray(deltas, np.float32))
        if self._stop.is_set() or not self._worker.is_alive():
            # the drain is gone: an enqueue would never be serviced —
            # count the drop instead of losing gradient mass silently
            self.dropped_pushes += 1
            self._m_dropped.inc()
            logger.warning("PS push dropped (%d total): drain thread gone",
                           self.dropped_pushes)
            return
        try:
            self._q.put_nowait(item)
        except queue.Full:
            # backpressure: block — dropping would lose gradient mass.
            # Abortable: if the drain thread died (or close() ran), a
            # blocked producer counts a drop instead of wedging forever
            try:
                put_abortable(self._q, item,
                              abort=lambda: (self._stop.is_set()
                                             or not self._worker.is_alive()))
            except QueueAborted:
                self.dropped_pushes += 1
                self._m_dropped.inc()
                logger.warning(
                    "PS push dropped (%d total): drain thread gone",
                    self.dropped_pushes)

    def close(self):
        """Stop accepting pushes and retire the drain thread. Pushes
        already queued are still delivered (get_abortable drains the
        queue before honoring the stop), so close() waits up to ~10s;
        against a dead endpoint delivery can outlast the join timeout —
        the daemon thread then finishes (or dies) on its own."""
        self._stop.set()
        self._worker.join(timeout=10)
        _health.get_health().unregister(self._hb)

    def _drain(self):
        while True:
            try:
                table, rows, deltas = get_abortable(self._q, self._stop)
            except QueueAborted:
                return
            try:
                with self._hb.busy():
                    for s, url in enumerate(self.urls):
                        sel = np.nonzero(rows % len(self.urls) == s)[0]
                        if sel.size == 0:
                            continue
                        self._post_bin(url, "/push.bin",
                                       _pack_request(table, rows[sel],
                                                     deltas[sel]))
            except Exception as e:
                # endpoint down or reply malformed: drop THIS push and keep
                # the drain thread alive — a dead thread would silently
                # wedge push_async once the bounded queue fills
                self.dropped_pushes += 1
                self._m_dropped.inc()
                logger.warning("PS push dropped (%d total): %s",
                               self.dropped_pushes, e)
            finally:
                self._q.task_done()

    def flush(self, timeout: float = 30.0):
        import time

        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.02)
        self._q.join()
