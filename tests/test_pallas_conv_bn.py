"""Pallas conv+BN-stats epilogue fusion (ops/pallas_conv_bn.py) vs the XLA
path.

Runs the kernels in interpreter mode on the CPU test backend (the real
lowering is exercised on TPU by bench.py resnet50's A/B); correctness =
forward AND hand-written-backward equality against the built-in lowerings
on ResNet-stage shape patterns, an f64 finite-difference check through
train/gradientcheck.py, and fallback proofs: unsupported shapes/platforms
take the built-in path, and a helper fn that raises is disabled with the
layer still producing the built-in result (the SPI bugfix).
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops import pallas_conv_bn as pcb
from deeplearning4j_tpu.ops.helpers import (
    HelperError,
    get_helper,
    helper_names,
    register_helper,
    set_helper_enabled,
)

_DIMS2D = ("NHWC", "HWIO", "NHWC")


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = pcb._INTERPRET
    pcb._INTERPRET = True
    pcb._STATS_STASH.clear()
    pcb._RELU_STASH.clear()
    yield
    pcb._INTERPRET = old
    pcb._STATS_STASH.clear()
    pcb._RELU_STASH.clear()


def _ref_conv(x, w, strides):
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding="SAME",
        dimension_numbers=_DIMS2D)


# -- kernel numerics ---------------------------------------------------------

@pytest.mark.parametrize(
    "kernel,strides,cin,cout,hw",
    [
        ((1, 1), (1, 1), 8, 32, 6),   # bottleneck expand (1x1 w -> 4w)
        ((1, 1), (2, 2), 16, 8, 6),   # projection shortcut, even spatial
        ((1, 1), (2, 2), 8, 16, 7),   # SAME/odd spatial: ceil(7/2)=4 rows
        ((3, 3), (1, 1), 8, 8, 5),    # bottleneck middle conv
        ((3, 3), (2, 2), 8, 8, 8),    # stage-entry 3x3/s2, even spatial
        ((3, 3), (2, 2), 8, 8, 7),    # 3x3/s2 odd spatial: stride-2 halo
        ((7, 7), (2, 2), 3, 8, 16),   # stem 7x7/s2, even spatial
        ((7, 7), (2, 2), 3, 8, 9),    # stem 7x7/s2, odd: asymmetric SAME pad
    ],
)
def test_conv_stats_matches_xla_forward_and_grad(kernel, strides, cin, cout, hw):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, hw, hw, cin)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((*kernel, cin, cout)) * 0.2,
                    jnp.float32)

    y, s1, s2 = pcb.conv2d_bn_stats(x, w, strides)
    yr = _ref_conv(x, w, strides)
    assert y.shape == yr.shape
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=2e-5, atol=2e-5)
    # the epilogue's raw moments == reductions of the conv output
    yf = np.asarray(yr, np.float64).reshape(-1, cout)
    np.testing.assert_allclose(np.asarray(s1), yf.sum(0), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s2), (yf * yf).sum(0),
                               rtol=1e-4, atol=1e-4)

    # hand-written backward (transposed-conv pullback) == autodiff of XLA
    gf = jax.grad(lambda a, b: jnp.sum(
        jnp.sin(pcb.conv2d_bn_stats(a, b, strides)[0])), argnums=(0, 1))
    gr = jax.grad(lambda a, b: jnp.sum(
        jnp.sin(_ref_conv(a, b, strides))), argnums=(0, 1))
    for a, b, name in zip(gf(x, w), gr(x, w), ("dx", "dW")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=name)


@pytest.mark.parametrize("relu", [False, True])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_bn_apply_matches_builtin_bn(relu, dtype):
    """bn_apply from precomputed raw moments == norm.py's fused _bn_train
    (+ ReLU), forward and the reused fused-VJP backward, within the
    dtype's tolerance."""
    from deeplearning4j_tpu.nn.layers.norm import _bn_train

    rng = np.random.default_rng(1)
    c = 8
    x = jnp.asarray(rng.standard_normal((4, 5, 5, c)) * 1.3 + 0.4, dtype)
    gamma = jnp.asarray(rng.standard_normal(c) * 0.2 + 1.0, jnp.float32)
    beta = jnp.asarray(rng.standard_normal(c) * 0.1, jnp.float32)
    n = x.size // c
    # bf16: the epilogue's raw-moment variance and norm.py's centered
    # variance legitimately differ by ~0.2%, which moves a handful of
    # outputs across a bf16 rounding boundary — gradients of those
    # elements then differ by an ulp of the output scale. Structure is
    # pinned by the f32 case (3e-4) and the f64 finite-difference check.
    tol = 1e-1 if dtype == jnp.bfloat16 else 3e-4

    def moments(a):
        a2 = lax.stop_gradient(a).astype(jnp.float32).reshape(n, c)
        return jnp.sum(a2, 0), jnp.sum(a2 * a2, 0)

    s1, s2 = moments(x)
    y, mean, var = pcb.bn_apply(x, s1, s2, gamma, beta, 1e-5, n, relu)
    yr, mean_r, var_r = _bn_train(x, gamma, beta, 1e-5)
    if relu:
        yr = jnp.maximum(yr, jnp.zeros_like(yr))
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(yr, np.float32),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(mean_r),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(var), np.asarray(var_r),
                               rtol=tol, atol=tol)

    def loss_fused(a, g_, b_):
        m1, m2 = moments(a)
        out, _, _ = pcb.bn_apply(a, m1, m2, g_, b_, 1e-5, n, relu)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(a, g_, b_):
        out, _, _ = _bn_train(a, g_, b_, 1e-5)
        if relu:
            out = jnp.maximum(out, jnp.zeros_like(out))
        return jnp.sum(out.astype(jnp.float32) ** 2)

    ga = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    gb = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b, name in zip(ga, gb, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=tol, atol=tol, err_msg=name)


def test_fused_op_f64_gradient_check():
    """f64 finite-difference check of the COMPOSED fused op (conv with
    stats epilogue -> stop_gradient'ed moments -> bn_apply normalize+ReLU)
    through train/gradientcheck.py — validates the hand-written VJP pair
    end to end, including the total-derivative treatment of the stats."""
    from deeplearning4j_tpu.train.gradientcheck import check_gradients_fn

    rng = np.random.default_rng(2)
    cin, cout, hw, b = 4, 8, 3, 2
    x = rng.standard_normal((b, hw, hw, cin))
    sizes = [cin * cout, cout, cout]

    def loss_of_flat(flat):
        wf, gamma, beta = jnp.split(flat, np.cumsum(sizes)[:-1])
        w = wf.reshape(1, 1, cin, cout)
        xj = jnp.asarray(x, flat.dtype)
        y, s1, s2 = pcb.conv2d_bn_stats(xj, w, (1, 1))
        s1 = lax.stop_gradient(s1)
        s2 = lax.stop_gradient(s2)
        n = y.size // cout
        out, _, _ = pcb.bn_apply(y, s1, s2, gamma, beta, 1e-5, n, True)
        return jnp.sum(out * jnp.cos(out))

    flat0 = np.concatenate([
        rng.standard_normal(sizes[0]) * 0.3,
        rng.standard_normal(sizes[1]) * 0.1 + 1.0,
        rng.standard_normal(sizes[2]) * 0.1,
    ])
    assert check_gradients_fn(loss_of_flat, flat0, epsilon=1e-6,
                              max_rel_error=1e-5, verbose=True)


@pytest.mark.parametrize("kernel,strides,hw", [
    ((3, 3), (2, 2), 5),   # stage-entry stride, odd spatial halo
    ((7, 7), (2, 2), 6),   # stem kernel: pad wider than the input edge
])
def test_strided_kernels_f64_gradient_check(kernel, strides, hw):
    """f64 finite differences through the NEW strided kernels' VJP (the
    transposed-conv pullback is stride-agnostic by construction — this
    pins that claim numerically, per-tap slice plan included)."""
    from deeplearning4j_tpu.train.gradientcheck import check_gradients_fn

    rng = np.random.default_rng(7)
    cin, cout, b = 2, 4, 2
    x = rng.standard_normal((b, hw, hw, cin))
    nw = kernel[0] * kernel[1] * cin * cout

    def loss_of_flat(flat):
        w = flat.reshape(*kernel, cin, cout)
        xj = jnp.asarray(x, flat.dtype)
        y, _, _ = pcb.conv2d_bn_stats(xj, w, strides)
        return jnp.sum(y * jnp.cos(y))

    flat0 = rng.standard_normal(nw) * 0.3
    assert check_gradients_fn(loss_of_flat, flat0, epsilon=1e-6,
                              max_rel_error=1e-5, verbose=True)


# -- SPI integration ---------------------------------------------------------

def _build_conv_bn_net(seed=5):
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import (
        ActivationLayer,
        BatchNormalization,
        ConvolutionLayer,
        GlobalPoolingLayer,
        InputType,
        NeuralNetConfiguration,
        OutputLayer,
    )

    gb = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
          .weight_init("relu").graph_builder().add_inputs("input")
          .set_input_types(InputType.convolutional(6, 6, 4)))
    gb.add_layer("c1", ConvolutionLayer(
        kernel_size=(3, 3), stride=(1, 1), n_out=8, convolution_mode="same",
        has_bias=False, activation="identity"), "input")
    gb.add_layer("bn1", BatchNormalization(), "c1")
    gb.add_layer("r1", ActivationLayer(activation="relu"), "bn1")
    gb.add_layer("c2", ConvolutionLayer(
        kernel_size=(1, 1), stride=(2, 2), n_out=16, convolution_mode="same",
        has_bias=False, activation="identity"), "r1")
    gb.add_layer("bn2", BatchNormalization(), "c2")
    gb.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), "bn2")
    gb.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                    loss="mcxent"), "pool")
    gb.set_outputs("out")
    return ComputationGraph(gb.build()).init()


def _train_data():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((8, 6, 6, 4)).astype(np.float32)
    y = np.zeros((8, 3), np.float32)
    y[np.arange(8), rng.integers(0, 3, 8)] = 1.0
    return x, y


def test_network_uses_helpers_and_matches_builtin():
    """End to end through the SPI: a conv->BN->ReLU->conv/s2->BN graph
    trained with the fused helpers equals the built-in XLA path — outputs,
    params AND the BN running statistics (the EMA consumes the epilogue's
    mean/var)."""
    x, y = _train_data()

    net_h = _build_conv_bn_net()
    net_h.fit(x, y, batch_size=8, epochs=2, async_prefetch=False)
    out_h = np.asarray(net_h.output(x))

    for op in ("conv2d", "batch_norm", "bn_backward"):
        set_helper_enabled(op, False)
    try:
        net_b = _build_conv_bn_net()
        net_b.fit(x, y, batch_size=8, epochs=2, async_prefetch=False)
        out_b = np.asarray(net_b.output(x))
    finally:
        for op in ("conv2d", "batch_norm", "bn_backward"):
            set_helper_enabled(op, True)

    np.testing.assert_allclose(out_h, out_b, rtol=3e-4, atol=3e-5)
    for p1, p2 in zip(net_h.params_list, net_b.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=3e-4, atol=3e-5,
                err_msg=f"param {k}")
    for s1, s2 in zip(net_h.state_list, net_b.state_list):
        if s1 is not None:
            for k in s1:
                np.testing.assert_allclose(
                    np.asarray(s1[k]), np.asarray(s2[k]), rtol=3e-4,
                    atol=3e-5, err_msg=f"state {k}")


def _build_stem_net(seed=11):
    """A ResNet-stem-shaped graph: 7x7/s2 conv -> BN -> ReLU -> 3x3/s2
    conv -> BN -> pool -> out, on odd 9x9 input so both strided kernels
    exercise the asymmetric-SAME halo path end to end."""
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import (
        ActivationLayer,
        BatchNormalization,
        ConvolutionLayer,
        GlobalPoolingLayer,
        InputType,
        NeuralNetConfiguration,
        OutputLayer,
    )

    gb = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
          .weight_init("relu").graph_builder().add_inputs("input")
          .set_input_types(InputType.convolutional(9, 9, 3)))
    gb.add_layer("stem", ConvolutionLayer(
        kernel_size=(7, 7), stride=(2, 2), n_out=8, convolution_mode="same",
        has_bias=False, activation="identity"), "input")
    gb.add_layer("bn1", BatchNormalization(), "stem")
    gb.add_layer("r1", ActivationLayer(activation="relu"), "bn1")
    gb.add_layer("entry", ConvolutionLayer(
        kernel_size=(3, 3), stride=(2, 2), n_out=16, convolution_mode="same",
        has_bias=False, activation="identity"), "r1")
    gb.add_layer("bn2", BatchNormalization(), "entry")
    gb.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), "bn2")
    gb.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                    loss="mcxent"), "pool")
    gb.set_outputs("out")
    return ComputationGraph(gb.build()).init()


def test_stem_network_helpers_match_builtin():
    """End to end with the NEW kernels (7x7/s2 stem + 3x3/s2 stage entry):
    helpers-on training equals builtin-XLA training — outputs, params and
    the BN running statistics."""
    rng = np.random.default_rng(13)
    x = rng.standard_normal((8, 9, 9, 3)).astype(np.float32)
    y = np.zeros((8, 3), np.float32)
    y[np.arange(8), rng.integers(0, 3, 8)] = 1.0

    net_h = _build_stem_net()
    net_h.fit(x, y, batch_size=8, epochs=2, async_prefetch=False)
    out_h = np.asarray(net_h.output(x))

    for op in ("conv2d", "batch_norm", "bn_backward"):
        set_helper_enabled(op, False)
    try:
        net_b = _build_stem_net()
        net_b.fit(x, y, batch_size=8, epochs=2, async_prefetch=False)
        out_b = np.asarray(net_b.output(x))
    finally:
        for op in ("conv2d", "batch_norm", "bn_backward"):
            set_helper_enabled(op, True)

    np.testing.assert_allclose(out_h, out_b, rtol=3e-4, atol=3e-5)
    for p1, p2 in zip(net_h.params_list, net_b.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=3e-4, atol=3e-5,
                err_msg=f"param {k}")
    for s1, s2 in zip(net_h.state_list, net_b.state_list):
        if s1 is not None:
            for k in s1:
                np.testing.assert_allclose(
                    np.asarray(s1[k]), np.asarray(s2[k]), rtol=3e-4,
                    atol=3e-5, err_msg=f"state {k}")


def test_helpers_registered_and_probed():
    names = helper_names()
    assert names.get("conv2d") == "pallas_conv_bn_stats"
    assert names.get("batch_norm") == "pallas_fused_bn_apply"

    base = dict(kernel=(1, 1), stride=(1, 1), dilation=(1, 1), same=True,
                has_bias=False, activation="identity", dtype=jnp.float32,
                n_in=8, n_out=16, x_shape=(2, 6, 6, 8), training=True)
    assert get_helper("conv2d", **base) is not None
    # the full covered family, stem + stage-entry strided shapes included
    for good in (dict(kernel=(1, 1), stride=(2, 2)),
                 dict(kernel=(3, 3), stride=(1, 1)),
                 dict(kernel=(3, 3), stride=(2, 2)),  # stage-entry 3x3/s2
                 dict(kernel=(7, 7), stride=(2, 2), n_in=3,
                      x_shape=(2, 6, 6, 3))):         # stem
        ctx = dict(base)
        ctx.update(good)
        assert get_helper("conv2d", **ctx) is not None, good
    # fallback whitelist: everything a ResNet trunk conv is NOT
    for bad in (dict(kernel=(5, 5)),
                dict(kernel=(7, 7), stride=(1, 1)),
                dict(has_bias=True),
                dict(activation="relu"),
                dict(dilation=(2, 2)),
                dict(same=False),
                dict(training=False)):
        ctx = dict(base)
        ctx.update(bad)
        assert get_helper("conv2d", **ctx) is None, bad


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_bn_backward_fused_matches_builtin_reductions(dtype):
    """The fused bn_backward helper (one Pallas pass over g and x for
    dgamma/dbeta + one for dx) == the builtin jnp reductions it replaces,
    for both the f32 (raw x, center=mean) and bf16 (centered x,
    center=delta) recenterings of `_bn_backward_pieces`."""
    rng = np.random.default_rng(17)
    c, n_shape = 8, (4, 5, 5, 8)
    x = jnp.asarray(rng.standard_normal(n_shape) * 1.2 + 0.3, dtype)
    g = jnp.asarray(rng.standard_normal(n_shape), dtype)
    gamma = jnp.asarray(rng.standard_normal(c) * 0.2 + 1.0, jnp.float32)
    n = x.size // c
    xf = np.asarray(x, np.float64).reshape(-1, c)
    mean = jnp.asarray(xf.mean(0), jnp.float32)
    var = jnp.asarray(xf.var(0), jnp.float32)
    inv = lax.rsqrt(var + 1e-5)

    dx_h, dg_h, db_h = pcb._bn_backward_pieces(g, x, mean, inv, gamma, n)
    set_helper_enabled("bn_backward", False)
    try:
        dx_b, dg_b, db_b = pcb._bn_backward_pieces(g, x, mean, inv, gamma, n)
    finally:
        set_helper_enabled("bn_backward", True)

    # bf16: the kernel casts g and x to f32 BEFORE the product; the
    # builtin reduction multiplies in bf16 first (`_col_sums(g2 * x2)`).
    # The kernel is the more accurate of the two — the comparison
    # tolerance is the bf16 product-rounding bound, not a kernel defect.
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(dx_h, np.float32),
                               np.asarray(dx_b, np.float32),
                               rtol=tol, atol=tol, err_msg="dx")
    for a, b, name in ((dg_h, dg_b, "dgamma"), (db_h, db_b, "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=tol, atol=tol, err_msg=name)


def test_roofline_declines_compute_bound_conv():
    """The economic stage of `conv_decision`: a stage-3-like 3x3 conv is
    compute-bound on the modeled roofline (intensity above the ridge) and
    must be DECLINED — the stats epilogue saves an HBM read worth nothing
    there, so a compute-bound shape can never regress through the helper.
    The same kernel family on a memory-bound instance stays covered."""
    big = dict(kernel=(3, 3), stride=(1, 1), dilation=(1, 1), same=True,
               has_bias=False, activation="identity", dtype=jnp.bfloat16,
               n_in=256, n_out=256, x_shape=(8, 14, 14, 256), training=True)
    d = pcb.conv_decision(**big)
    assert d["status"] == "declined"
    assert d["reason"] == "compute_bound"
    assert d["roofline"]["intensity"] > d["roofline"]["ridge_intensity"]
    assert d["family"] == "conv3x3"
    assert get_helper("conv2d", **big) is None

    small = dict(big, dtype=jnp.float32, n_in=8, n_out=8,
                 x_shape=(2, 6, 6, 8))
    ds = pcb.conv_decision(**small)
    assert ds["status"] == "covered"
    assert ds["reason"] == "memory_bound"


def test_resnet50_kernel_coverage_complete():
    """The 53/53 contract: every ResNet-50 conv instance resolves to
    covered or declined-with-roofline-verdict — zero silently-unsupported
    shapes (the gap this kernel family closes)."""
    from deeplearning4j_tpu.analysis.kernelcoverage import (
        coverage_summary,
        coverage_table,
    )
    from deeplearning4j_tpu.models.resnet import resnet50_conf

    rows = coverage_table(resnet50_conf(), batch=128)
    s = coverage_summary(rows)
    assert s["total"] == 53
    assert s["unsupported"] == 0
    assert s["covered"] + s["declined"] == 53
    assert s["covered"] > 0 and s["declined"] > 0
    by = {r["layer"]: r for r in rows}
    assert by["stem_conv"]["status"] == "covered"
    assert by["stem_conv"]["family"] == "conv7x7s2"
    assert by["s1b0_b_conv"]["status"] == "covered"   # 3x3/s2 stage entry
    assert by["s1b0_b_conv"]["family"] == "conv3x3s2"
    for r in rows:
        if r["status"] == "declined":
            assert r["reason"] == "compute_bound"
            assert r["intensity"] > r["ridge"]


def test_fallback_on_cpu_without_interpret():
    """Tier-1/CPU safety: with interpret mode off (the library default),
    the probes refuse the CPU backend outright — the TPU kernel path can
    never run in a CPU process."""
    pcb._INTERPRET = False
    assert get_helper(
        "conv2d", kernel=(1, 1), stride=(1, 1), dilation=(1, 1), same=True,
        has_bias=False, activation="identity", dtype=jnp.bfloat16,
        n_in=64, n_out=256, x_shape=(8, 56, 56, 64), training=True) is None
    x = jnp.zeros((2, 4, 4, 8), jnp.bfloat16)
    assert get_helper("batch_norm", x=x, training=True) is None


def test_stash_match_with_mixed_shapes_pending():
    """Regression: taking a stashed entry that is NOT first in the deque,
    while entries of DIFFERENT shapes are pending (a ResNet block's main
    path + projection shortcut), used to raise — deque.remove compares
    entries with ==, which broadcasts traced arrays. Removal must be by
    identity/index."""
    xa = jnp.zeros((2, 4, 4, 8), jnp.float32)
    xb = jnp.zeros((2, 4, 4, 4), jnp.float32)
    wa = jnp.zeros((1, 1, 8, 16), jnp.float32)
    wb = jnp.zeros((1, 1, 4, 8), jnp.float32)
    ya = pcb._conv2d_helper(xa, wa, strides=(1, 1))   # shape (2,4,4,16)
    yb = pcb._conv2d_helper(xb, wb, strides=(1, 1))   # shape (2,4,4,8)
    assert pcb.take_stats(yb) is not None   # second entry, first still pending
    assert pcb.take_stats(ya) is not None
    assert pcb.take_stats(ya) is None       # consumed; miss answers None
    # same for the deferred-ReLU stash: different-shaped entries pending
    g = jnp.ones((16,), jnp.float32)
    b = jnp.zeros((16,), jnp.float32)
    za = pcb._conv2d_helper(xa, wa, strides=(1, 1))
    ra, _, _ = pcb._bn_helper(za, g, b, 1e-5)
    zb = pcb._conv2d_helper(xb, wb, strides=(1, 1))
    rb, _, _ = pcb._bn_helper(zb, g[:8], b[:8], 1e-5)
    fused_b = pcb.take_fused_relu(rb)       # second entry, first pending
    assert fused_b is not None and fused_b.shape == rb.shape
    assert pcb.take_fused_relu(ra) is not None


def test_bn_probe_requires_stashed_stats():
    """The batch_norm helper only engages for the exact tensor a conv
    epilogue produced — any intervening op breaks identity and falls back."""
    x = jnp.zeros((2, 4, 4, 8), jnp.float32)
    assert get_helper("batch_norm", x=x, training=True) is None
    w = jnp.zeros((1, 1, 8, 8), jnp.float32)
    y = pcb._conv2d_helper(x, w, strides=(1, 1))
    assert get_helper("batch_norm", x=y, training=True) is not None
    assert pcb.take_stats(y) is not None   # consumed...
    assert get_helper("batch_norm", x=y, training=True) is None  # ...once


# -- the SPI raising-fn bugfix ----------------------------------------------

def test_raising_helper_fn_disables_and_falls_back(caplog):
    """Regression (ops/helpers.py): a helper `fn` that raises at trace
    time used to kill the layer with no fallback even though its probe
    passed. Now the SPI catches, logs, disables the helper, and the layer
    retries its built-in path — the network must train identically to the
    builtin-only run, and the helper must be off afterwards."""

    def exploding(*a, **k):
        raise ValueError("synthetic kernel lowering failure")

    x, y = _train_data()
    register_helper("conv2d", exploding, lambda **ctx: True,
                    name="exploding_conv")
    try:
        with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
            net = _build_conv_bn_net()
            net.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
        assert any("exploding_conv" in r.message and "disabled" in r.message
                   for r in caplog.records)
        assert helper_names()["conv2d"] == "exploding_conv"
        # disabled => probe-level refusal now, without calling fn
        assert get_helper("conv2d", anything=1) is None

        set_helper_enabled("conv2d", False)
        set_helper_enabled("batch_norm", False)
        try:
            net_b = _build_conv_bn_net()
            net_b.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
        finally:
            set_helper_enabled("batch_norm", True)
        for p1, p2 in zip(net.params_list, net_b.params_list):
            for k in p1:
                np.testing.assert_allclose(
                    np.asarray(p1[k]), np.asarray(p2[k]),
                    rtol=1e-5, atol=1e-6, err_msg=f"param {k}")
    finally:
        pcb.register()  # restore the real kernels (fresh enabled Helper)
    assert helper_names()["conv2d"] == "pallas_conv_bn_stats"


def test_raising_bn_backward_helper_disables_and_falls_back(caplog):
    """The SPI auto-disable contract for the NEW "bn_backward" slot: a
    fused-backward fn that raises at trace time is caught, logged and
    disabled, and both consumers (`norm.py _bn_train_bwd` and the pallas
    `_bn_bwd`) retry their builtin reductions — the network trains to the
    same parameters as the fully-builtin run."""

    def exploding(*a, **k):
        raise ValueError("synthetic bn-backward lowering failure")

    x, y = _train_data()
    register_helper("bn_backward", exploding, lambda **ctx: True,
                    name="exploding_bn_bwd")
    try:
        with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
            net = _build_conv_bn_net()
            net.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
        assert any("exploding_bn_bwd" in r.message and "disabled" in r.message
                   for r in caplog.records)
        assert helper_names()["bn_backward"] == "exploding_bn_bwd"
        # disabled => probe-level refusal now, without calling fn
        assert get_helper("bn_backward", anything=1) is None

        for op in ("conv2d", "batch_norm", "bn_backward"):
            set_helper_enabled(op, False)
        try:
            net_b = _build_conv_bn_net()
            net_b.fit(x, y, batch_size=8, epochs=1, async_prefetch=False)
        finally:
            for op in ("conv2d", "batch_norm"):
                set_helper_enabled(op, True)
        for p1, p2 in zip(net.params_list, net_b.params_list):
            for k in p1:
                np.testing.assert_allclose(
                    np.asarray(p1[k]), np.asarray(p2[k]),
                    rtol=3e-4, atol=3e-5, err_msg=f"param {k}")
    finally:
        pcb.register()  # restore the real kernels (fresh enabled Helper)
    assert helper_names()["bn_backward"] == "pallas_fused_bn_bwd"


def test_guarded_helper_raises_helper_error_directly():
    register_helper("_t1_scratch", lambda: (_ for _ in ()).throw(
        RuntimeError("boom")), name="scratch")
    try:
        fn = get_helper("_t1_scratch")
        assert fn is not None
        with pytest.raises(HelperError):
            fn()
        assert get_helper("_t1_scratch") is None  # disabled after the raise
    finally:
        from deeplearning4j_tpu.ops.helpers import _HELPERS

        _HELPERS.pop("_t1_scratch", None)
