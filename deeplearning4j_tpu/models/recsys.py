"""Recsys preset: an embedding table + dense tower classifier.

The model half of the `bench.py recsys` workload and the JX008
host-residency regression tests: one (optionally huge) EmbeddingLayer
whose table can be declared `host_resident=True` — row-sharded across
paramserver endpoints and trained through the sparse pipeline
(parallel/sparse) — followed by a small dense tower that runs as a
normal jitted device step. With `host_resident=False` the same conf is
the control: the residency audit must then count the table against HBM
and fail when it does not fit.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    EmbeddingLayer,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def recsys_conf(vocab: int = 100_000, dim: int = 64, hidden: int = 128,
                classes: int = 2, host_resident: bool = True,
                seed: int = 7, learning_rate: float = 0.05):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.SGD)
        .learning_rate(learning_rate)
        .weight_init("xavier")
        .list()
        .layer(EmbeddingLayer(n_in=vocab, n_out=dim, has_bias=False,
                              activation="identity",
                              host_resident=host_resident))
        .layer(DenseLayer(n_out=hidden, activation="relu"))
        .layer(DenseLayer(n_out=hidden, activation="relu"))
        .layer(OutputLayer(n_out=classes, activation="softmax",
                           loss="mcxent"))
        .set_input_type(InputType.feed_forward(1))
        .build()
    )


def recsys_network(vocab: int = 100_000, dim: int = 64, hidden: int = 128,
                   classes: int = 2, host_resident: bool = True,
                   **kw) -> MultiLayerNetwork:
    return MultiLayerNetwork(
        recsys_conf(vocab, dim, hidden, classes, host_resident, **kw)
    ).init()
