"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A from-scratch reimplementation of the *capabilities* of Deeplearning4j
(reference surveyed in SURVEY.md) designed idiomatically for TPUs:

- declarative layer/graph configuration DSL with JSON round-trip
  (reference: deeplearning4j-nn/.../nn/conf/NeuralNetConfiguration.java)
- pure-functional layer forward passes compiled by XLA; gradients via
  autodiff instead of hand-written backprop
  (reference: deeplearning4j-nn/.../nn/layers/*)
- one jitted train step = forward + loss + grad + normalization + fused
  updater, with buffer donation
  (reference: Solver/StochasticGradientDescent + BaseMultiLayerUpdater)
- data parallelism via jax.sharding Mesh + per-step gradient psum over ICI
  (reference: deeplearning4j-scaleout ParallelWrapper / Spark averaging)
- Pallas kernels where XLA's defaults need help
  (reference: deeplearning4j-cuda cuDNN helper plugins)

The public API deliberately mirrors the reference's concept names
(MultiLayerConfiguration, ComputationGraph, Updater, Evaluation, ...) so a
DL4J user can find everything they know, while the execution model is
TPU-first throughout.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.common.dtypes import PrecisionPolicy, default_policy
