"""Serving entry points: k-NN REST server (reference:
deeplearning4j-nearestneighbor-server) and ParallelInference (parallel/)."""

from deeplearning4j_tpu.serving.knnserver import NearestNeighborsServer

__all__ = ["NearestNeighborsServer"]
