"""Dataset fetchers beyond MNIST: CIFAR-10, Iris, LFW (reference:
deeplearning4j-core datasets/iterator/impl/ CifarDataSetIterator,
IrisDataSetIterator, LFWDataSetIterator + fetchers in datasets/fetchers/).

Same contract as data/mnist.py: cached download when egress exists,
DETERMINISTIC synthetic fallback otherwise, honestly labeled via
``source`` on the iterator."""

from __future__ import annotations

import os
import pickle
import tarfile
import urllib.request
from pathlib import Path
from typing import Tuple

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator

_CIFAR_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"


def _cache_dir(name: str) -> Path:
    root = os.environ.get("DL4J_TPU_DATA",
                          os.path.expanduser("~/.deeplearning4j_tpu"))
    d = Path(root) / name
    d.mkdir(parents=True, exist_ok=True)
    return d


def _onehot(idx: np.ndarray, k: int) -> np.ndarray:
    y = np.zeros((idx.size, k), np.float32)
    y[np.arange(idx.size), idx] = 1.0
    return y


# -- CIFAR-10 ----------------------------------------------------------------

def synthetic_cifar(n: int, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Procedural 32x32x3 class-conditional textures: each class is a
    distinct (orientation, color, frequency) sinusoid grating + noise —
    linearly inseparable in pixel space but conv-learnable, the role the
    real CIFAR plays in pipeline tests."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    x = np.empty((n, 32, 32, 3), np.float32)
    for i, c in enumerate(labels):
        angle = c * np.pi / 10.0
        freq = 3.0 + (c % 5)
        phase = rng.uniform(0, 2 * np.pi)
        wave = np.sin(
            2 * np.pi * freq * (np.cos(angle) * xx + np.sin(angle) * yy)
            + phase)
        color = np.array([
            0.5 + 0.5 * np.cos(c), 0.5 + 0.5 * np.sin(1.7 * c),
            0.5 + 0.5 * np.cos(2.3 * c)], np.float32)
        img = 0.5 + 0.35 * wave[..., None] * color[None, None, :]
        img += rng.normal(0, 0.05, img.shape)
        x[i] = np.clip(img, 0, 1)
    return x, _onehot(labels, 10)


class CifarDataFetcher:
    """CIFAR-10 with cache/download/synthetic fallback."""

    def __init__(self, allow_download: bool = True,
                 synthetic_fallback: bool = True, synthetic_n: int = 2000):
        self.allow_download = allow_download
        self.synthetic_fallback = synthetic_fallback
        self.synthetic_n = synthetic_n
        self.source = None

    def _load_real(self, train: bool):
        d = _cache_dir("cifar10")
        tar = d / "cifar-10-python.tar.gz"
        if not tar.exists():
            if not self.allow_download:
                return None
            tmp = tar.with_suffix(".tmp")
            try:
                with urllib.request.urlopen(_CIFAR_URL, timeout=30) as r, \
                        open(tmp, "wb") as f:
                    f.write(r.read())
                os.replace(tmp, tar)  # atomic: no truncated cache entries
            except OSError:
                tmp.unlink(missing_ok=True)
                return None
        try:
            xs, ys = [], []
            names = ([f"data_batch_{i}" for i in range(1, 6)]
                     if train else ["test_batch"])
            with tarfile.open(tar, "r:gz") as tf:
                for m in tf.getmembers():
                    base = os.path.basename(m.name)
                    if base in names:
                        batch = pickle.load(tf.extractfile(m),
                                            encoding="bytes")
                        xs.append(np.asarray(batch[b"data"], np.float32))
                        ys.append(np.asarray(batch[b"labels"]))
            x = (np.concatenate(xs).reshape(-1, 3, 32, 32)
                 .transpose(0, 2, 3, 1) / 255.0).astype(np.float32)
            y = _onehot(np.concatenate(ys), 10)
            return x, y
        except (OSError, KeyError, EOFError, tarfile.TarError,
                pickle.UnpicklingError):
            # corrupt cache: drop it so the next run can re-download
            tar.unlink(missing_ok=True)
            return None

    def load(self, train: bool):
        real = self._load_real(train)
        if real is not None:
            self.source = "cifar10"
            return real
        if not self.synthetic_fallback:
            raise RuntimeError("CIFAR-10 unavailable and fallback disabled")
        self.source = "synthetic"
        return synthetic_cifar(self.synthetic_n, seed=1 if train else 2)


class CifarDataSetIterator(ListDataSetIterator):
    def __init__(self, batch: int, train: bool = True,
                 num_examples: int = None, fetcher: CifarDataFetcher = None):
        fetcher = fetcher or CifarDataFetcher()
        x, y = fetcher.load(train)
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        self.source = fetcher.source
        super().__init__(DataSet(x, y), batch)


# -- Iris --------------------------------------------------------------------

# Fisher's data is tiny and public domain: ship the generation-free subset
# inline (reference bundles it as a resource in IrisDataFetcher).
_IRIS_MEANS = np.array([
    [5.006, 3.428, 1.462, 0.246],   # setosa
    [5.936, 2.770, 4.260, 1.326],   # versicolor
    [6.588, 2.974, 5.552, 2.026],   # virginica
], np.float32)
_IRIS_STDS = np.array([
    [0.352, 0.379, 0.174, 0.105],
    [0.516, 0.314, 0.470, 0.198],
    [0.636, 0.322, 0.552, 0.275],
], np.float32)


def iris_data(seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """150 examples drawn from the class-conditional Gaussian fit of
    Fisher's iris (deterministic per seed) — same shape/statistics/task
    difficulty as the bundled CSV the reference ships."""
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for c in range(3):
        xs.append(rng.normal(_IRIS_MEANS[c], _IRIS_STDS[c], (50, 4)))
        ys.append(np.full(50, c))
    x = np.concatenate(xs).astype(np.float32)
    y = _onehot(np.concatenate(ys), 3)
    perm = rng.permutation(150)
    return x[perm], y[perm]


class IrisDataSetIterator(ListDataSetIterator):
    """reference: IrisDataSetIterator(batch, numExamples)."""

    def __init__(self, batch: int, num_examples: int = 150, seed: int = 0):
        x, y = iris_data(seed)
        super().__init__(DataSet(x[:num_examples], y[:num_examples]), batch)


# -- LFW (Labeled Faces in the Wild) -----------------------------------------

_LFW_URL = "http://vis-www.cs.umass.edu/lfw/lfw.tgz"


def synthetic_lfw(n: int, num_labels: int = 10, image_size: int = 64,
                  seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Procedural class-conditional "faces": each identity is a fixed
    face-geometry (skin tone, eye spacing/height, mouth curve) with
    per-example jitter — same role the real LFW identities play in
    pipeline tests (class-consistent structure, conv-learnable)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_labels, n)
    s = image_size
    yy, xx = np.mgrid[0:s, 0:s].astype(np.float32) / s
    x = np.empty((n, s, s, 3), np.float32)
    id_rng = np.random.default_rng(12345)  # identity geometry is fixed
    geom = [{
        "skin": 0.45 + 0.4 * id_rng.random(3),
        "eye_dx": 0.12 + 0.08 * id_rng.random(),
        "eye_y": 0.34 + 0.10 * id_rng.random(),
        "mouth_y": 0.68 + 0.08 * id_rng.random(),
        "mouth_w": 0.10 + 0.10 * id_rng.random(),
        "brow": id_rng.random(),
    } for _ in range(num_labels)]
    for i, c in enumerate(labels):
        g = geom[c]
        jitter = rng.normal(0, 0.01, 4)
        img = np.ones((s, s, 3), np.float32) * 0.08
        # head: filled ellipse in the identity's skin tone
        head = (((xx - 0.5) / 0.32) ** 2 + ((yy - 0.5) / 0.42) ** 2) < 1.0
        img[head] = g["skin"]
        # eyes: dark discs, identity-specific spacing/height
        for sx in (-1, 1):
            ex = 0.5 + sx * (g["eye_dx"] + jitter[0])
            ey = g["eye_y"] + jitter[1]
            eye = ((xx - ex) ** 2 + (yy - ey) ** 2) < 0.0012
            img[eye] = 0.05 + 0.1 * g["brow"]
        # mouth: dark horizontal bar of identity-specific width
        my, mw = g["mouth_y"] + jitter[2], g["mouth_w"] + jitter[3]
        mouth = (np.abs(yy - my) < 0.02) & (np.abs(xx - 0.5) < mw)
        img[mouth] = 0.15
        img += rng.normal(0, 0.03, img.shape).astype(np.float32)
        x[i] = np.clip(img, 0, 1)
    return x, _onehot(labels, num_labels)


class LFWDataFetcher:
    """LFW with cache/download/synthetic fallback (reference:
    datasets/fetchers/LFWDataFetcher.java + iterator/impl/
    LFWDataSetIterator.java). `num_labels` keeps the most-photographed
    identities, the reference's lfwNumLabels subsetting."""

    def __init__(self, allow_download: bool = True,
                 synthetic_fallback: bool = True, synthetic_n: int = 1000,
                 num_labels: int = 10, image_size: int = 64):
        self.allow_download = allow_download
        self.synthetic_fallback = synthetic_fallback
        self.synthetic_n = synthetic_n
        self.num_labels = int(num_labels)
        self.image_size = int(image_size)
        self.source = None

    def _decode_ppm_like(self, data: bytes):
        """LFW ships JPEGs; decode via PIL when available (not a core
        dependency), else signal no-real-data."""
        try:
            from io import BytesIO

            from PIL import Image  # optional; baked into many images

            img = Image.open(BytesIO(data)).convert("RGB")
            img = img.resize((self.image_size, self.image_size))
            return np.asarray(img, np.float32) / 255.0
        except Exception:
            return None

    def _load_real(self, train: bool):
        d = _cache_dir("lfw")
        tar = d / "lfw.tgz"
        if not tar.exists():
            if not self.allow_download:
                return None
            tmp = tar.with_suffix(".tmp")
            try:
                with urllib.request.urlopen(_LFW_URL, timeout=60) as r, \
                        open(tmp, "wb") as f:
                    f.write(r.read())
                os.replace(tmp, tar)
            except OSError:
                tmp.unlink(missing_ok=True)
                return None
        try:
            by_person = {}
            with tarfile.open(tar, "r:gz") as tf:
                for m in tf.getmembers():
                    # person dirs only: lfw/<Person_Name>/<img>.jpg
                    if not (m.isfile() and m.name.endswith(".jpg")
                            and "/" in m.name):
                        continue
                    person = m.name.split("/")[-2]
                    by_person.setdefault(person, []).append(m)
                top = sorted(by_person, key=lambda p: -len(by_person[p]))
                top = top[: self.num_labels]
                xs, ys = [], []
                for li, person in enumerate(top):
                    for m in by_person[person]:
                        f = tf.extractfile(m)
                        img = self._decode_ppm_like(f.read()) if f else None
                        if img is None:
                            return None  # no decoder: fall back
                        xs.append(img)
                        ys.append(li)
            if not xs:
                return None
            x = np.stack(xs)
            y = _onehot(np.asarray(ys), len(top))
            # the tar groups examples by identity; shuffle deterministically
            # so truncation (num_examples) and the train/eval split both see
            # every class
            perm = np.random.default_rng(777).permutation(len(xs))
            x, y = x[perm], y[perm]
            idx = np.arange(len(xs))
            sel = idx[idx % 5 != 0] if train else idx[idx % 5 == 0]
            return x[sel], y[sel]
        except (OSError, KeyError, EOFError, IndexError, tarfile.TarError):
            tar.unlink(missing_ok=True)
            return None

    def load(self, train: bool):
        real = self._load_real(train)
        if real is not None:
            self.source = "lfw"
            return real
        if not self.synthetic_fallback:
            raise RuntimeError("LFW unavailable and fallback disabled")
        self.source = "synthetic"
        return synthetic_lfw(self.synthetic_n, self.num_labels,
                             self.image_size, seed=3 if train else 4)


class LFWDataSetIterator(ListDataSetIterator):
    def __init__(self, batch: int, train: bool = True,
                 num_examples: int = None, fetcher: LFWDataFetcher = None):
        fetcher = fetcher or LFWDataFetcher()
        x, y = fetcher.load(train)
        if num_examples:
            x, y = x[:num_examples], y[:num_examples]
        self.source = fetcher.source
        super().__init__(DataSet(x, y), batch)
