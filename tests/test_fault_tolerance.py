"""Elastic fault tolerance (ISSUE 7): async checkpointing, byte-identical
mid-epoch resume, SIGTERM chain ordering, torn-metadata recovery, and the
`cli resume` operator surface.

The recovery contract under test: a process killed -9 mid-fit, resumed
via `fit(resume_from=...)`, continues to EXACTLY the loss curve of an
uninterrupted run (per-step score equality on CPU) — the checkpoint
carries params/updater AND the TrainState (epoch, batches consumed,
iterator epoch state), and the resumed fit replays the consumed batches
through the pipeline without dispatching them.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.train.checkpoint import (
    CheckpointListener,
    describe_latest,
    latest_checkpoint,
    scan_checkpoints,
)
from deeplearning4j_tpu.train.listeners import CollectScoresIterationListener

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "fault_tolerance_child.py")

sys.path.insert(0, os.path.join(REPO, "tests"))

from fault_tolerance_child import build_iterator, build_net  # noqa: E402


def _child_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("T1_BLACKBOX_ARTIFACT", None)  # the child arms its own hooks
    return env


def _run_child_until_step(argv, kill_step, sig, wait_for_ckpt_in=None):
    """Start the child, read STEP lines until `kill_step`, deliver `sig`.
    Returns (proc, steps_seen: {iteration: score}).

    `wait_for_ckpt_in`: under async_save the writer thread can be
    starved by a loaded CPU — killing the instant the step line appears
    can catch a run with every save still queued, which is legal
    async-checkpoint behavior (you lose up to the in-flight interval)
    but not what the resume test wants to exercise. When set, the signal
    is held until a finished checkpoint zip exists in that directory, so
    the kill is still mid-fit but never outruns the first write."""
    proc = subprocess.Popen(
        [sys.executable, CHILD] + argv, env=_child_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    steps = {}
    try:
        for line in proc.stdout:
            if line.startswith("STEP "):
                _, it, score = line.split()
                steps[int(it)] = float(score)
                if int(it) >= kill_step:
                    if (wait_for_ckpt_in is not None
                            and not glob.glob(os.path.join(
                                wait_for_ckpt_in, "checkpoint_iter*.zip"))):
                        continue  # writer hasn't published yet: hold fire
                    proc.send_signal(sig)
                    break
            elif line.startswith("FIT DONE"):
                break
    finally:
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    return proc, steps


def _reference_scores(epochs=3):
    net = build_net()
    rec = CollectScoresIterationListener()
    net.set_listeners(rec)
    net.fit(build_iterator(), epochs=epochs)
    return dict(rec.scores)


def _clean_tmp_orphans(ckdir):
    # a SIGKILLed async writer leaves its in-flight *.tmp behind by
    # design (the atomic rename never happened); sweep it so the
    # session-level tmp-orphan guard stays a signal for REAL leaks
    for f in glob.glob(os.path.join(ckdir, "*.tmp*")):
        os.remove(f)


# -- kill -9 mid-fit, resume, same loss curve --------------------------------


def test_sigkill_mid_fit_resume_matches_reference(tmp_path):
    """The acceptance criterion: SIGKILL a fit at a (seeded-random)
    step, `fit(resume_from=...)` from the survivors, and every step the
    resumed run executes scores EXACTLY what the uninterrupted reference
    run scored at the same iteration."""
    ckdir = str(tmp_path / "ckpts")
    epochs = 3  # 6 batches/epoch -> 18 iterations
    kill_step = int(np.random.default_rng(int(time.time())).integers(4, 14))

    proc, steps = _run_child_until_step(
        ["--mode", "fit", "--ckpt-dir", ckdir, "--epochs", str(epochs),
         "--async-save"],
        kill_step, signal.SIGKILL, wait_for_ckpt_in=ckdir)
    assert proc.returncode == -signal.SIGKILL, proc.stderr.read()
    assert steps, "child never reported a step"
    _clean_tmp_orphans(ckdir)

    found = latest_checkpoint(ckdir)
    assert found is not None, "no checkpoint survived the kill"
    _, meta = found
    assert meta["iteration"] <= max(steps) + 1

    ref = _reference_scores(epochs)
    # the killed child's own steps already matched the reference (same
    # seeds): a cheap sanity check that the two runs are comparable
    for it, sc in steps.items():
        assert sc == pytest.approx(ref[it], abs=0.0), (
            f"child diverged from reference at step {it} BEFORE the kill")

    net = build_net()
    rec = CollectScoresIterationListener()
    net.set_listeners(rec)
    net.fit(build_iterator(), epochs=epochs, resume_from=ckdir)
    resumed = dict(rec.scores)

    assert resumed, "resumed fit dispatched no steps"
    assert net.iteration == epochs * 6
    for it, sc in resumed.items():
        assert sc == ref[it], (
            f"resumed run diverged at iteration {it}: {sc!r} != {ref[it]!r}")
    # the resumed run picks up where the newest checkpoint left off —
    # nothing before it is re-dispatched
    assert min(resumed) == meta["iteration"]


@pytest.mark.slow
def test_chaos_kill_loop_resumes_to_reference(tmp_path):
    """Chaos variant: kill the run N times at random steps, resuming
    from the same directory each time; the final completed run's curve
    still equals the uninterrupted reference everywhere it ran."""
    ckdir = str(tmp_path / "chaos")
    epochs = 4  # 24 iterations
    ref = _reference_scores(epochs)
    rng = np.random.default_rng(99)
    all_steps = {}
    resume = False
    for round_no in range(3):
        kill_step = int(rng.integers(3, 20))
        argv = ["--mode", "fit", "--ckpt-dir", ckdir,
                "--epochs", str(epochs), "--async-save"]
        if resume:
            argv.append("--resume")
        proc, steps = _run_child_until_step(argv, kill_step, signal.SIGKILL,
                                            wait_for_ckpt_in=ckdir)
        _clean_tmp_orphans(ckdir)
        all_steps.update(steps)
        resume = True
        if proc.returncode == 0:
            break  # outran the killer — the run completed
    # final uninterrupted pass from wherever the last kill left things
    net = build_net()
    rec = CollectScoresIterationListener()
    net.set_listeners(rec)
    net.fit(build_iterator(), epochs=epochs, resume_from=ckdir)
    all_steps.update(dict(rec.scores))
    assert net.iteration == epochs * 6
    for it, sc in all_steps.items():
        assert sc == ref[it], f"diverged at iteration {it}"


# -- SIGTERM chain: save before dump, both installation orders ---------------


@pytest.mark.parametrize("order", ["ckpt-first", "hooks-first"])
def test_sigterm_chain_order_independent(tmp_path, order):
    """Regression for the handler-stacking bug: whichever subsystem arms
    SIGTERM first, a preemption delivers (1) the checkpoint save, then
    (2) the blackbox dump — which therefore records the checkpoint_saved
    event — then (3) death by SIGTERM so parents see the real cause."""
    ckdir = str(tmp_path / f"pre-{order}")
    dump = str(tmp_path / f"dump-{order}.json")
    proc, steps = _run_child_until_step(
        ["--mode", "sigterm", "--ckpt-dir", ckdir, "--epochs", "50",
         "--order", order, "--dump", dump],
        3, signal.SIGTERM)
    stderr = proc.stderr.read()
    assert proc.returncode == -signal.SIGTERM, (
        f"child must die WITH SIGTERM (rc={proc.returncode}): {stderr}")
    # (1) the preemption save ran (it is the only save configured)
    found = latest_checkpoint(ckdir)
    assert found is not None, f"no preemption checkpoint: {stderr}"
    _, meta = found
    assert meta["reason"] == "preemption"
    # (2) the dump exists and already knows about the save -> save ran first
    assert os.path.exists(dump), f"no blackbox dump: {stderr}"
    with open(dump) as f:
        doc = json.load(f)
    kinds = [e.get("kind") for e in doc.get("events", [])]
    assert "checkpoint_saved" in kinds, (
        f"dump written before the preemption save (order={order}); "
        f"events: {kinds}")


# -- torn metadata ------------------------------------------------------------


def _save_two(ckdir):
    net = build_net()
    listener = CheckpointListener(ckdir, keep_last=0)
    p1 = listener.save(net, reason="manual")
    net.iteration += 5
    p2 = listener.save(net, reason="manual")
    return net, p1, p2


def test_torn_latest_json_falls_back_to_scan(tmp_path):
    ckdir = str(tmp_path / "torn")
    net, _, p2 = _save_two(ckdir)
    with open(os.path.join(ckdir, "latest.json"), "w") as f:
        f.write('{"iteration": 5, "file": "checkpoint_')  # crash mid-write
    path, meta = latest_checkpoint(ckdir)
    assert path == p2
    assert meta["iteration"] == net.iteration
    assert meta["reason"] == "scan"
    restored, meta2 = CheckpointListener.restore_latest(ckdir)
    assert restored.iteration == net.iteration
    info = describe_latest(ckdir)
    assert info["path"] == p2 and info["age_seconds"] >= 0.0


def test_missing_metadata_and_dangling_pointer(tmp_path):
    ckdir = str(tmp_path / "meta")
    net, p1, p2 = _save_two(ckdir)
    os.remove(os.path.join(ckdir, "latest.json"))
    path, _ = latest_checkpoint(ckdir)
    assert path == p2  # no metadata at all: scan wins
    # dangling pointer: metadata names a file that is gone
    with open(os.path.join(ckdir, "latest.json"), "w") as f:
        json.dump({"iteration": 1, "file": "checkpoint_iter999999999.zip"},
                  f)
    path, meta = latest_checkpoint(ckdir)
    assert path == p2 and meta["reason"] == "scan"
    # an unreadable newest zip is skipped, not fatal
    with open(p2, "wb") as f:
        f.write(b"not a zip")
    os.remove(os.path.join(ckdir, "latest.json"))
    path, _ = latest_checkpoint(ckdir)
    assert path == p1


def test_latest_json_written_atomically_and_monotonic(tmp_path):
    ckdir = str(tmp_path / "mono")
    net = build_net()
    listener = CheckpointListener(ckdir, keep_last=0)
    net.iteration = 10
    listener.save(net, reason="manual")
    # an async writer finishing an OLDER snapshot must not roll back the
    # pointer (the preemption-save-vs-writer race)
    net.iteration = 4
    listener.save(net, reason="manual")
    with open(os.path.join(ckdir, "latest.json")) as f:
        assert json.load(f)["iteration"] == 10
    assert len(scan_checkpoints(ckdir)) == 2


def test_empty_dir_is_fresh_start(tmp_path):
    ckdir = str(tmp_path / "fresh")
    os.makedirs(ckdir)
    assert latest_checkpoint(ckdir) is None
    assert describe_latest(ckdir) is None
    net = build_net()
    rec = CollectScoresIterationListener()
    net.set_listeners(rec)
    net.fit(build_iterator(), epochs=1, resume_from=ckdir)  # must not raise
    assert net.iteration == 6


# -- async checkpointing ------------------------------------------------------


def test_async_save_same_bytes_and_snapshot_isolation(tmp_path):
    """The async writer publishes the SAME checkpoint a sync save would
    have, and the snapshot is immune to the fit thread mutating the net
    after capture (reference grabs of immutable jax trees)."""
    from deeplearning4j_tpu.utils.model_serializer import load_model

    net = build_net()
    sync_dir, async_dir = str(tmp_path / "sync"), str(tmp_path / "async")
    CheckpointListener(sync_dir).save(net, reason="manual")
    with CheckpointListener(async_dir, async_save=True) as lst:
        path = lst.save(net, reason="manual")
        # mutate immediately after capture — the published zip must hold
        # the OLD params
        old_params = np.asarray(net.params())
        net.set_params(np.zeros_like(old_params))
        lst.flush()
    assert os.path.exists(path)
    a = load_model(path)
    s = load_model(os.path.join(sync_dir, os.path.basename(path)))
    np.testing.assert_array_equal(np.asarray(a.params()),
                                  np.asarray(s.params()))
    np.testing.assert_array_equal(np.asarray(a.params()), old_params)


def test_async_save_phases_split_and_snapshot_cheap(tmp_path):
    """The checkpoint_save_seconds histogram is phase-split, and the
    fit-thread-blocking `snapshot` phase is far cheaper than the
    background `write` phase — the step-stall-~0 claim."""
    from deeplearning4j_tpu.utils import metrics as _metrics

    reg = _metrics.get_registry()
    h = reg.histogram(
        "checkpoint_save_seconds", "checkpoint save duration by phase: "
        "`snapshot` is the fit-thread blocking part (capture + enqueue "
        "under async_save), `write` the serialize + atomic rename",
        ("phase",))
    snap0, write0 = h.labels("snapshot").count, h.labels("write").count
    snap_sum0 = h.labels("snapshot").sum
    write_sum0 = h.labels("write").sum

    net = build_net()
    with CheckpointListener(str(tmp_path / "ph"), async_save=True,
                            keep_last=0) as lst:
        for i in range(5):
            net.iteration += 1
            lst.save(net, reason="manual")
            lst.flush()
    snap_n = h.labels("snapshot").count - snap0
    write_n = h.labels("write").count - write0
    assert snap_n == 5 and write_n == 5
    snap_mean = (h.labels("snapshot").sum - snap_sum0) / snap_n
    write_mean = (h.labels("write").sum - write_sum0) / write_n
    # capture = reference grabs + conf JSON; write = device pull +
    # flatten + deflate + rename. Factor 2 is deliberately loose (CI
    # noise); in practice it is 10x+.
    assert snap_mean < write_mean / 2, (
        f"blocking snapshot phase ({snap_mean * 1e3:.3f} ms) not clearly "
        f"below background write phase ({write_mean * 1e3:.3f} ms)")


def test_async_writer_coalesces_backlog(tmp_path):
    """When the writer falls behind, the OLDEST queued snapshot is
    displaced (newest state wins) and the displacement is counted."""
    import queue as _queue

    from deeplearning4j_tpu.utils import metrics as _metrics
    from deeplearning4j_tpu.utils.model_serializer import ModelSnapshot

    net = build_net()
    lst = CheckpointListener(str(tmp_path / "co"), async_save=True,
                             queue_depth=1)
    before = _metrics.get_registry().get(
        "checkpoint_coalesced_total").labels().value
    # no writer running: the queue fills and _enqueue must displace
    lst._writer_q = _queue.Queue(maxsize=1)
    s1 = ModelSnapshot.capture(net, True)
    net.iteration += 1
    s2 = ModelSnapshot.capture(net, True)
    lst._enqueue(s1, "manual")
    lst._enqueue(s2, "manual")
    after = _metrics.get_registry().get(
        "checkpoint_coalesced_total").labels().value
    assert after == before + 1
    queued, _ = lst._writer_q.get_nowait()
    assert queued.iteration == s2.iteration  # the newest one survived


def test_on_fit_end_flushes_async_writer(tmp_path):
    ckdir = str(tmp_path / "eof")
    net = build_net()
    lst = CheckpointListener(ckdir, every_n_iterations=1, async_save=True,
                             keep_last=0)
    net.set_listeners(lst)
    net.fit(build_iterator(), epochs=1)
    # fit returned -> nothing is still in flight (on_fit_end flushed the
    # writer) and the NEWEST state is durable. Intermediate snapshots may
    # legitimately have been coalesced away while the writer lagged.
    assert lst._writer_q is None or lst._writer_q.unfinished_tasks == 0
    zips = scan_checkpoints(ckdir)
    assert zips and zips[-1][0] == net.iteration
    lst.close()


def test_ckpt_writer_heartbeat_unregisters_on_close(tmp_path):
    from deeplearning4j_tpu.utils import health as _health

    net = build_net()
    lst = CheckpointListener(str(tmp_path / "hb"), async_save=True)
    lst.save(net, reason="manual")
    assert "ckpt_writer" in _health.get_health().status()["components"]
    lst.close()
    assert "ckpt_writer" not in _health.get_health().status()["components"]


# -- the iterator resume protocol --------------------------------------------


def test_list_iterator_state_roundtrip_restores_permutation():
    it1 = build_iterator()
    [list(it1) for _ in range(2)]  # consume two epochs
    state = it1.state()
    assert state == {"epoch": 2}
    it2 = build_iterator()
    it2.restore_state(state)
    b1 = [np.asarray(d.features) for d in it1]
    b2 = [np.asarray(d.features) for d in it2]
    assert len(b1) == len(b2)
    for a, b in zip(b1, b2):
        np.testing.assert_array_equal(a, b)


def test_pipeline_wrappers_delegate_state():
    from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator
    from deeplearning4j_tpu.data.prefetch import ParallelDataSetIterator

    base = build_iterator()
    list(base)  # epoch 1
    wrapped = AsyncDataSetIterator(base, queue_size=2)
    assert wrapped.state() == {"epoch": 1}
    wrapped.restore_state({"epoch": 5})
    assert base._epoch == 5
    wrapped.close()
    par = ParallelDataSetIterator(build_iterator(), workers=2)
    assert par.state() == {"epoch": 0}
    par.restore_state({"epoch": 3})
    assert par.base._epoch == 3
    par.close()


def test_resume_from_mismatched_conf_raises(tmp_path):
    from deeplearning4j_tpu.utils.model_serializer import restore_fit_state

    ckdir = str(tmp_path / "mm")
    net = build_net()
    CheckpointListener(ckdir).save(net, reason="manual")
    other = build_net(seed=8)  # different seed -> different conf JSON
    path, _ = latest_checkpoint(ckdir)
    with pytest.raises(ValueError, match="different configuration"):
        restore_fit_state(other, path)


# -- cli resume ---------------------------------------------------------------


def test_cli_resume_happy_path(tmp_path, capsys):
    from deeplearning4j_tpu.cli import main as cli_main

    ckdir = str(tmp_path / "cli")
    net = build_net()
    rec = CollectScoresIterationListener()
    lst = CheckpointListener(ckdir, every_n_iterations=1)
    net.set_listeners(lst, rec)
    net.fit(build_iterator(), epochs=1)
    rc = cli_main(["resume", ckdir])
    out = capsys.readouterr().out
    assert rc == 0
    assert "iteration: 6" in out and "MultiLayerNetwork" in out
    rc = cli_main(["resume", ckdir, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["iteration"] == 6
    assert doc["train_state"]["epoch"] == 0
    assert doc["train_state"]["batch_in_epoch"] == 6


def test_cli_resume_empty_and_torn(tmp_path, capsys):
    from deeplearning4j_tpu.cli import main as cli_main

    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert cli_main(["resume", empty]) == 1
    capsys.readouterr()
    # a directory whose only "checkpoint" is garbage: describe falls back
    # to the scan, the scan finds nothing loadable -> exit 1
    torn = str(tmp_path / "torn")
    os.makedirs(torn)
    with open(os.path.join(torn, "checkpoint_iter000000001.zip"), "wb") as f:
        f.write(b"garbage")
    assert cli_main(["resume", torn]) == 1
    capsys.readouterr()
    # torn zip named by intact metadata: validation catches it
    ckdir = str(tmp_path / "tornzip")
    net = build_net()
    lst = CheckpointListener(ckdir)
    path = lst.save(net, reason="manual")
    with zipfile.ZipFile(path) as zf:
        names = zf.namelist()
    assert names
    with open(path, "r+b") as f:
        f.seek(0)
        f.write(b"\x00" * 64)  # corrupt the zip in place
    assert cli_main(["resume", ckdir]) == 1
    capsys.readouterr()
    # metadata-only mode does not open the payload -> passes
    assert cli_main(["resume", ckdir, "--no-validate"]) == 0
    capsys.readouterr()
