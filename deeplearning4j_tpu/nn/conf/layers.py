"""Layer configuration dataclasses.

One config class per layer type, mirroring the reference's nn/conf/layers/
catalog (28 classes — SURVEY.md §2.1 "Layer configs"). Configs are pure
data: JSON-serializable dataclasses with two responsibilities the reference
splits between InputTypeUtil and each Layer conf:

- output_type(input_type): shape inference through the network
- infer_n_in(input_type): fill in n_in/channels when the user set an
  InputType instead of wiring sizes by hand (reference: setNIn overrides)

Fields defaulting to None inherit the network-level default from
NeuralNetConfiguration (reference: Builder.layer(...) cloning global
hyperparameters into each layer's conf).

Convolutional layers use NHWC and "same"/"truncate" border modes
(reference ConvolutionMode.Same/Truncate, nn/conf/ConvolutionMode.java).
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalFlatInput,
    ConvolutionalInput,
    FeedForwardInput,
    RecurrentInput,
)
from deeplearning4j_tpu.nn.conf.serde import register_config


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


class ConvolutionMode:
    SAME = "same"
    TRUNCATE = "truncate"


def _conv_out(size: int, k: int, s: int, p: int, mode: str) -> int:
    if mode == ConvolutionMode.SAME:
        return int(math.ceil(size / s))
    return (size + 2 * p - k) // s + 1


@dataclasses.dataclass(kw_only=True)
class LayerConf:
    """Base fields shared by every layer (reference: nn/conf/layers/Layer.java
    + BaseLayer hyperparameters)."""

    name: Optional[str] = None
    dropout: Optional[float] = None  # keep DL4J semantics: retain probability

    def output_type(self, it):
        return it

    def infer_n_in(self, it) -> None:
        pass

    def has_params(self) -> bool:
        return True


@dataclasses.dataclass(kw_only=True)
class BaseLayerConf(LayerConf):
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[dict] = None
    bias_init: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    learning_rate: Optional[float] = None
    bias_learning_rate: Optional[float] = None


@dataclasses.dataclass(kw_only=True)
class FeedForwardLayerConf(BaseLayerConf):
    n_in: Optional[int] = None
    n_out: int = 0

    def output_type(self, it):
        return FeedForwardInput(self.n_out)

    def infer_n_in(self, it) -> None:
        if self.n_in is None:
            self.n_in = it.arity()


@register_config("layer.dense")
@dataclasses.dataclass(kw_only=True)
class DenseLayer(FeedForwardLayerConf):
    """Fully connected layer (reference: nn/conf/layers/DenseLayer.java)."""


@register_config("layer.output")
@dataclasses.dataclass(kw_only=True)
class OutputLayer(FeedForwardLayerConf):
    """Dense + loss head (reference: nn/conf/layers/OutputLayer.java)."""

    loss: str = "mcxent"


@register_config("layer.rnn_output")
@dataclasses.dataclass(kw_only=True)
class RnnOutputLayer(FeedForwardLayerConf):
    """Time-distributed output layer (reference: RnnOutputLayer.java).
    Input [batch, time, nIn] -> [batch, time, nOut], loss summed over time."""

    loss: str = "mcxent"

    def output_type(self, it):
        ts = it.timesteps if isinstance(it, RecurrentInput) else None
        return RecurrentInput(self.n_out, ts)


@register_config("layer.center_loss_output")
@dataclasses.dataclass(kw_only=True)
class CenterLossOutputLayer(FeedForwardLayerConf):
    """Output layer with center-loss auxiliary term
    (reference: CenterLossOutputLayer.java: intra-class center pull)."""

    loss: str = "mcxent"
    alpha: float = 0.05
    lambda_: float = 2e-4

    def output_type(self, it):
        return FeedForwardInput(self.n_out)


@register_config("layer.loss")
@dataclasses.dataclass(kw_only=True)
class LossLayer(BaseLayerConf):
    """Parameterless loss head (reference: LossLayer.java)."""

    loss: str = "mcxent"

    def has_params(self):
        return False


@register_config("layer.activation")
@dataclasses.dataclass(kw_only=True)
class ActivationLayer(BaseLayerConf):
    """Standalone activation (reference: ActivationLayer.java)."""

    def has_params(self):
        return False


@register_config("layer.dropout")
@dataclasses.dataclass(kw_only=True)
class DropoutLayer(BaseLayerConf):
    """Standalone dropout (reference: DropoutLayer.java)."""

    def has_params(self):
        return False


@register_config("layer.embedding")
@dataclasses.dataclass(kw_only=True)
class EmbeddingLayer(FeedForwardLayerConf):
    """Index lookup layer (reference: EmbeddingLayer.java). Input: integer
    indices [batch] or [batch, 1]. On TPU the lookup compiles to a gather;
    a one-hot-matmul path is used under jit where gather scatter-grads are
    slow (see ops/embedding_ops).

    `host_resident=True` declares the table lives on the HOST (sharded
    across paramserver endpoints, rows pulled/pushed through
    parallel/sparse.SparseEmbeddingPipeline) rather than in device HBM —
    the residency audit (JX008) and dead-weight liveness (JX005) then
    exempt its weights from the per-chip memory picture."""

    has_bias: bool = True
    host_resident: bool = False


@register_config("layer.convolution")
@dataclasses.dataclass(kw_only=True)
class ConvolutionLayer(FeedForwardLayerConf):
    """2D convolution, NHWC (reference: nn/conf/layers/ConvolutionLayer.java;
    runtime im2col+gemm at nn/layers/convolution/ConvolutionLayer.java:177-201
    — here it lowers to XLA conv_general_dilated which tiles directly onto
    the MXU, no explicit im2col)."""

    kernel_size: Sequence[int] = (5, 5)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = ConvolutionMode.TRUNCATE
    dilation: Sequence[int] = (1, 1)
    has_bias: bool = True

    def output_type(self, it):
        if not isinstance(it, ConvolutionalInput):
            raise ValueError(f"ConvolutionLayer needs convolutional input, got {it}")
        h = _conv_out(it.height, self.kernel_size[0], self.stride[0], self.padding[0], self.convolution_mode)
        w = _conv_out(it.width, self.kernel_size[1], self.stride[1], self.padding[1], self.convolution_mode)
        return ConvolutionalInput(h, w, self.n_out)

    def infer_n_in(self, it) -> None:
        if self.n_in is None and isinstance(it, ConvolutionalInput):
            self.n_in = it.channels


@register_config("layer.convolution1d")
@dataclasses.dataclass(kw_only=True)
class Convolution1DLayer(FeedForwardLayerConf):
    """1D convolution over time (reference: Convolution1DLayer.java).
    Input [batch, time, nIn] -> [batch, time', nOut]."""

    kernel_size: int = 5
    stride: int = 1
    padding: int = 0
    convolution_mode: str = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def output_type(self, it):
        if not isinstance(it, RecurrentInput):
            raise ValueError(f"Convolution1DLayer needs recurrent input, got {it}")
        ts = it.timesteps
        if ts is not None:
            ts = _conv_out(ts, self.kernel_size, self.stride, self.padding, self.convolution_mode)
        return RecurrentInput(self.n_out, ts)

    def infer_n_in(self, it) -> None:
        if self.n_in is None:
            self.n_in = it.size


@register_config("layer.subsampling")
@dataclasses.dataclass(kw_only=True)
class SubsamplingLayer(LayerConf):
    """2D pooling (reference: SubsamplingLayer.java; XLA reduce_window)."""

    pooling_type: str = PoolingType.MAX
    kernel_size: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (0, 0)
    convolution_mode: str = ConvolutionMode.TRUNCATE
    pnorm: int = 2

    def has_params(self):
        return False

    def output_type(self, it):
        if not isinstance(it, ConvolutionalInput):
            raise ValueError(f"SubsamplingLayer needs convolutional input, got {it}")
        h = _conv_out(it.height, self.kernel_size[0], self.stride[0], self.padding[0], self.convolution_mode)
        w = _conv_out(it.width, self.kernel_size[1], self.stride[1], self.padding[1], self.convolution_mode)
        return ConvolutionalInput(h, w, it.channels)


@register_config("layer.subsampling1d")
@dataclasses.dataclass(kw_only=True)
class Subsampling1DLayer(LayerConf):
    """1D pooling over time (reference: Subsampling1DLayer.java)."""

    pooling_type: str = PoolingType.MAX
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = ConvolutionMode.TRUNCATE
    pnorm: int = 2

    def has_params(self):
        return False

    def output_type(self, it):
        ts = it.timesteps
        if ts is not None:
            ts = _conv_out(ts, self.kernel_size, self.stride, self.padding, self.convolution_mode)
        return RecurrentInput(it.size, ts)


@register_config("layer.batch_norm")
@dataclasses.dataclass(kw_only=True)
class BatchNormalization(BaseLayerConf):
    """Batch normalization (reference: nn/conf/layers/BatchNormalization.java;
    cuDNN helper in deeplearning4j-cuda — here a fused XLA computation).
    Normalizes over all axes except the last (channels/features)."""

    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0  # init value
    beta: float = 0.0
    lock_gamma_beta: bool = False
    n_in: Optional[int] = None

    def infer_n_in(self, it) -> None:
        if self.n_in is None:
            self.n_in = it.channels if isinstance(it, ConvolutionalInput) else it.arity()


@register_config("layer.lrn")
@dataclasses.dataclass(kw_only=True)
class LocalResponseNormalization(LayerConf):
    """Cross-channel LRN (reference: LocalResponseNormalization.java,
    CudnnLocalResponseNormalizationHelper — here jnp window sum over the
    channel axis)."""

    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75

    def has_params(self):
        return False


@register_config("layer.zero_padding")
@dataclasses.dataclass(kw_only=True)
class ZeroPaddingLayer(LayerConf):
    """Spatial zero padding (reference: ZeroPaddingLayer.java).
    padding = (top, bottom, left, right)."""

    padding: Sequence[int] = (1, 1, 1, 1)

    def has_params(self):
        return False

    def output_type(self, it):
        pt, pb, pl, pr = self.padding
        return ConvolutionalInput(it.height + pt + pb, it.width + pl + pr, it.channels)


@register_config("layer.global_pooling")
@dataclasses.dataclass(kw_only=True)
class GlobalPoolingLayer(LayerConf):
    """Global pooling over spatial or time dims
    (reference: GlobalPoolingLayer.java). CNN input -> pool H,W;
    RNN input -> pool time (mask-aware)."""

    pooling_type: str = PoolingType.MAX
    pnorm: int = 2
    collapse_dimensions: bool = True

    def has_params(self):
        return False

    def output_type(self, it):
        if isinstance(it, ConvolutionalInput):
            return FeedForwardInput(it.channels)
        if isinstance(it, RecurrentInput):
            return FeedForwardInput(it.size)
        return it


@dataclasses.dataclass(kw_only=True)
class BaseRecurrentLayerConf(FeedForwardLayerConf):
    def output_type(self, it):
        ts = it.timesteps if isinstance(it, RecurrentInput) else None
        return RecurrentInput(self.n_out, ts)

    def infer_n_in(self, it) -> None:
        if self.n_in is None:
            self.n_in = it.size if isinstance(it, RecurrentInput) else it.arity()


@register_config("layer.lstm")
@dataclasses.dataclass(kw_only=True)
class LSTM(BaseRecurrentLayerConf):
    """LSTM without peepholes (reference: nn/conf/layers/LSTM.java;
    runtime LSTMHelpers.java — here a lax.scan over a fused gate matmul,
    with an optional Pallas kernel for the cell)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"


@register_config("layer.self_attention")
@dataclasses.dataclass(kw_only=True)
class SelfAttentionLayer(BaseRecurrentLayerConf):
    """Multi-head self-attention over the time axis — capability BEYOND
    the reference (DL4J 0.8 predates attention; SURVEY §5 lists
    long-context as greenfield). [b, t, nIn] -> [b, t, nOut]; nOut must
    be divisible by n_heads. ``causal`` masks future positions. The
    sequence-parallel execution of the same math is
    parallel/sequence.ring_self_attention."""

    n_heads: int = 4
    causal: bool = False
    projection_bias: bool = True

    def output_type(self, it):
        ts = it.timesteps if isinstance(it, RecurrentInput) else None
        return RecurrentInput(self.n_out, ts)


@register_config("layer.graves_lstm")
@dataclasses.dataclass(kw_only=True)
class GravesLSTM(BaseRecurrentLayerConf):
    """LSTM with peephole connections, Graves (2013) formulation
    (reference: GravesLSTM.java + LSTMHelpers.java:62,291)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"


@register_config("layer.graves_bidirectional_lstm")
@dataclasses.dataclass(kw_only=True)
class GravesBidirectionalLSTM(BaseRecurrentLayerConf):
    """Bidirectional peephole LSTM. Separate forward/backward parameter sets;
    the two directions' outputs are element-wise ADDED, so n_out stays n_out
    (reference: nn/layers/recurrent/GravesBidirectionalLSTM.java:205
    `fwdOutput.addi(backOutput)`)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"


@register_config("layer.autoencoder")
@dataclasses.dataclass(kw_only=True)
class AutoEncoder(FeedForwardLayerConf):
    """Denoising autoencoder (reference: nn/conf/layers/AutoEncoder.java,
    runtime nn/layers/feedforward/autoencoder/AutoEncoder.java). Supervised
    path behaves like a dense layer; unsupervised pretraining reconstructs
    corrupted input."""

    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"


@register_config("layer.rbm")
@dataclasses.dataclass(kw_only=True)
class RBM(FeedForwardLayerConf):
    """Restricted Boltzmann machine (reference: nn/conf/layers/RBM.java +
    nn/layers/feedforward/rbm/RBM.java — CD-k contrastive divergence with
    HiddenUnit/VisibleUnit types, :102,223-279). Supervised path behaves
    like a dense layer (propUp); unsupervised pretraining runs CD-k."""

    hidden_unit: str = "binary"  # binary | gaussian | rectified
    visible_unit: str = "binary"  # binary | gaussian
    k: int = 1  # CD-k Gibbs steps
    sparsity: float = 0.0


@register_config("layer.vae")
@dataclasses.dataclass(kw_only=True)
class VariationalAutoencoder(FeedForwardLayerConf):
    """VAE as a layer (reference: nn/conf/layers/variational/
    VariationalAutoencoder.java:40-54 — encoder/decoder MLP sizes, pluggable
    reconstruction distribution, ELBO objective; runtime impl 1,120 LoC)."""

    encoder_layer_sizes: List[int] = dataclasses.field(default_factory=lambda: [100])
    decoder_layer_sizes: List[int] = dataclasses.field(default_factory=lambda: [100])
    pzx_activation: str = "identity"
    reconstruction_distribution: Optional[dict] = None  # {"type": "gaussian"|"bernoulli", "activation": ...}
    num_samples: int = 1


@register_config("layer.frozen")
@dataclasses.dataclass(kw_only=True)
class FrozenLayer(LayerConf):
    """Wrapper marking an inner layer's params as non-trainable
    (reference: nn/layers/FrozenLayer.java, used by TransferLearning)."""

    inner: Optional[LayerConf] = None

    def output_type(self, it):
        return self.inner.output_type(it)

    def infer_n_in(self, it) -> None:
        self.inner.infer_n_in(it)

    def has_params(self):
        return self.inner.has_params()
