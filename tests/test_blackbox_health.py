"""Flight recorder + hang watchdog + component health (utils/blackbox,
utils/health) — the crash-forensics layer.

Acceptance coverage (ISSUE 6): a subprocess killed via SIGTERM mid-fit
leaves a dump that `cli blackbox` renders with the last recorded step
index and the dl4j-* thread stacks; an injected stall (blocked serving
dispatcher, stalled prefetch worker) flips `component_health` to
degraded within the watchdog interval over `GET /health` and recovers
when unblocked; the flight recorder's hot-path cost stays within noise
of the tracing-off fit baseline.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import metrics as metrics_mod
from deeplearning4j_tpu.utils.blackbox import (
    FlightRecorder,
    get_recorder,
    render_dump,
)
from deeplearning4j_tpu.utils.health import (
    DEGRADED,
    OK,
    UNHEALTHY,
    StepHangError,
    get_health,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp_conf(n_in=6, n_out=3):
    return (NeuralNetConfiguration.builder().seed(7).list()
            .layer(DenseLayer(n_in=n_in, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .build())


def _xy(n=40, n_in=6, n_out=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.random((n, n_in), np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return x, y


def _wait_until(pred, timeout=8.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    return pred()


# -- flight recorder ----------------------------------------------------------

def test_recorder_ring_is_bounded_and_keeps_newest():
    rec = FlightRecorder(capacity=16, metrics_every=10_000)
    for i in range(300):
        rec.record_step(i, score=float(i), data_wait=0.001, dispatch=0.002)
    snap = rec.snapshot("test")
    assert snap["steps_recorded_total"] == 300
    assert len(snap["steps"]) == 16
    assert snap["last_step"] == 299
    assert [r["step"] for r in snap["steps"]] == list(range(284, 300))
    # scores resolve to floats; phase timings survive
    assert snap["steps"][-1]["score"] == 299.0
    assert snap["steps"][-1]["dispatch"] == pytest.approx(0.002)


def test_recorder_events_and_metrics_deltas():
    rec = FlightRecorder(capacity=8, metrics_every=10_000)
    rec.record_event("compile", compile_kind="output", key="(1, 2)")
    c = metrics_mod.get_registry().counter(
        "bb_test_delta_total", "test").labels()
    rec.record_metrics_delta()  # establishes the baseline sample
    c.inc(5)
    rec.record_metrics_delta()
    snap = rec.snapshot("test")
    assert snap["events"][-1]["kind"] == "compile"
    deltas = snap["metrics_deltas"]
    assert deltas, "second capture should have produced a delta"
    assert deltas[-1]["delta"]["bb_test_delta_total"] == 5


def test_recorder_pending_score_is_not_synced():
    class NeverReady:
        def is_ready(self):
            return False

        def __float__(self):  # a sync would be a contract violation
            raise AssertionError("snapshot must not block on the device")

    rec = FlightRecorder(capacity=4, metrics_every=10_000)
    rec.record_step(0, score=NeverReady())
    snap = rec.snapshot("test")
    assert snap["steps"][0]["score"] == "pending"


def test_dump_write_and_render(tmp_path):
    rec = FlightRecorder(capacity=8, metrics_every=10_000)
    rec.record_step(41, score=0.5, data_wait=0.01, dispatch=0.02)
    rec.record_step(42, score=0.25, data_wait=0.01, dispatch=0.02)
    path = rec.dump(str(tmp_path / "bb.json"), reason="unit test")
    assert path and os.path.exists(path)
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "unit test"
    assert doc["last_step"] == 42
    text = render_dump(doc)
    assert "blackbox dump" in text
    assert "42" in text and "unit test" in text
    # the dumping thread itself is always in the stacks section
    assert "MainThread" in text


# -- watchdog + component health ---------------------------------------------

def test_watchdog_stall_detection_recovery_and_series():
    reg = metrics_mod.get_registry()
    h = get_health()
    stalls0 = reg.counter(
        "watchdog_stall_total", "", ("component",)).labels("bb_demo").value
    seq0 = h.last_seq()
    seen = []
    listener = seen.append
    h.add_listener(listener)
    hb = h.register("bb_demo", stall_after=0.1)
    ev = threading.Event()

    def work():
        with hb.busy():
            ev.wait(10)

    t = threading.Thread(target=work, daemon=True, name="dl4j-bb-demo")
    t.start()
    try:
        assert _wait_until(
            lambda: h.status()["components"]["bb_demo"]["status"] != OK)
        detail = h.status()["components"]["bb_demo"]
        assert detail["stalled_for_seconds"] > 0
        assert "dl4j-bb-demo" in detail["stalled_threads"]
        # the gauge follows the scan, the stall counter opened an episode
        assert _wait_until(lambda: reg.gauge(
            "component_health", "", ("component",))
            .labels("bb_demo").value >= 1)
        assert _wait_until(lambda: reg.counter(
            "watchdog_stall_total", "", ("component",))
            .labels("bb_demo").value == stalls0 + 1)
        # first degradation handed the flight recorder a snapshot (the
        # watchdog thread writes it just after the counter — poll)
        assert _wait_until(
            lambda: get_recorder().last_degradation is not None)
        assert any(e["kind"] == "degraded"
                   for e in get_recorder().snapshot()["events"])
    finally:
        ev.set()
        t.join(5)
    assert _wait_until(
        lambda: h.status()["components"]["bb_demo"]["status"] == OK)

    def pairs():
        return [(tr["from"], tr["to"]) for tr in h.transitions_since(seq0)
                if tr["component"] == "bb_demo"]

    # transitions are appended by the SCAN (status above is live) — poll
    assert _wait_until(lambda: any(to == OK for _, to in pairs()[1:]))
    assert (OK, DEGRADED) in pairs()
    assert _wait_until(lambda: any(
        tr["component"] == "bb_demo" for tr in seen))
    h.remove_listener(listener)
    h.unregister(hb)
    assert "bb_demo" not in h.status()["components"]


def test_shared_heartbeat_oldest_busy_slot_wins():
    """A multi-worker component (the ETL stage) stalls when ANY worker
    wedges — siblings' progress must not mask it."""
    h = get_health()
    hb = h.register("bb_shared", stall_after=0.15)
    stop = threading.Event()
    wedge = threading.Event()

    def healthy_worker():
        while not stop.is_set():
            with hb.busy():
                hb.beat()
                time.sleep(0.01)

    def wedged_worker():
        with hb.busy():
            wedge.wait(10)

    t1 = threading.Thread(target=healthy_worker, daemon=True,
                          name="dl4j-bb-healthy")
    t2 = threading.Thread(target=wedged_worker, daemon=True,
                          name="dl4j-bb-wedged")
    t1.start()
    t2.start()
    try:
        assert _wait_until(
            lambda: h.status()["components"]["bb_shared"]["status"] != OK)
        assert "dl4j-bb-wedged" in \
            h.status()["components"]["bb_shared"]["stalled_threads"]
    finally:
        stop.set()
        wedge.set()
        t1.join(5)
        t2.join(5)
        h.unregister(hb)


def test_idle_component_is_healthy():
    """No busy slot = idle = ok, regardless of how long ago the last
    work happened (waiting for traffic is not a stall)."""
    h = get_health()
    hb = h.register("bb_idle", stall_after=0.05)
    try:
        time.sleep(0.2)
        h.scan()
        assert h.status()["components"]["bb_idle"]["status"] == OK
    finally:
        h.unregister(hb)


# -- fit wiring ---------------------------------------------------------------

def test_fit_records_steps_and_unregisters_heartbeat():
    rec = get_recorder()
    before = rec.snapshot()["steps_recorded_total"]
    net = MultiLayerNetwork(_mlp_conf()).init()
    x, y = _xy(n=40)
    net.fit(x, y, epochs=1, batch_size=10, async_prefetch=False)
    snap = rec.snapshot()
    assert snap["steps_recorded_total"] == before + 4
    last = snap["steps"][-1]
    assert {"ts", "step", "score", "data_wait", "dispatch"} <= set(last)
    # heartbeat lifecycle: registered for the fit, gone afterwards
    assert "fit" not in get_health().status()["components"]


def test_fit_hang_timeout_raises_diagnosable_error():
    net = MultiLayerNetwork(_mlp_conf()).init()
    x, y = _xy(n=20)

    class StallingIterator(DataSetIterator):
        def __iter__(self):
            yield DataSet(x[:10], y[:10])
            for _ in range(1000):  # a python-level wedge, 20s worth
                time.sleep(0.02)

        def reset(self):
            pass

        def batch_size(self):
            return 10

        def total_examples(self):
            return 20

    t0 = time.monotonic()
    with pytest.raises(StepHangError) as ei:
        net.fit(StallingIterator(), epochs=1, async_prefetch=False,
                hang_timeout=0.3)
    assert time.monotonic() - t0 < 15, "hang was not cut short"
    e = ei.value
    assert e.dump_path and os.path.exists(e.dump_path)
    with open(e.dump_path) as f:
        doc = json.load(f)
    assert "hang" in doc["reason"]
    assert doc["last_step"] is not None
    # fit component cleaned up even on the hang path
    assert "fit" not in get_health().status()["components"]


def test_recorder_hot_path_overhead_within_noise():
    """Flight-recorder-on step time vs recorder-off (the PR 3 tracing-off
    baseline): the per-step cost is a ring append, so the A/B must be
    within noise. Asserted twice: a stable microbench bound on
    record_step itself, and a generous wall-clock ratio on real fits."""
    rec = get_recorder()
    t0 = time.perf_counter()
    for i in range(10_000):
        rec.record_step(i, score=None, data_wait=0.0, dispatch=0.001)
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 100e-6, f"record_step cost {per_call * 1e6:.1f}us"

    x, y = _xy(n=200)

    def fit_once():
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(x, y, epochs=1, batch_size=4, async_prefetch=False)  # 50
        t = time.perf_counter()
        net.fit(x, y, epochs=1, batch_size=4, async_prefetch=False)
        return time.perf_counter() - t

    # interleave on/off runs so machine-load drift hits both sides, and
    # compare minima (the noise-free floor); the recorder's true cost is
    # ~µs on a ~ms step, so generous headroom still catches a real
    # hot-path regression (e.g. a per-step registry walk)
    on_t, off_t = [], []
    try:
        for _ in range(3):
            rec.enabled = True
            on_t.append(fit_once())
            rec.enabled = False
            off_t.append(fit_once())
    finally:
        rec.enabled = True
    assert min(on_t) < min(off_t) * 1.8 + 0.1, (on_t, off_t)


# -- injected stalls: pipeline + serving --------------------------------------

def test_prefetch_worker_stall_flips_component_and_recovers():
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.data.prefetch import DevicePrefetchIterator

    x, y = _xy(n=30)
    h = get_health()
    unwedge = threading.Event()
    first = [True]

    def wedging_transform(ds):
        if first[0]:
            first[0] = False
            unwedge.wait(15)
        return ds

    it = DevicePrefetchIterator(
        ListDataSetIterator(DataSet(x, y), 10), depth=1,
        transform=wedging_transform, health_stall_after=0.12)
    got = []

    def consume():
        for ds in it:
            got.append(ds)

    t = threading.Thread(target=consume, daemon=True,
                         name="dl4j-bb-consumer")
    t.start()
    try:
        comp = lambda: h.status()["components"].get("device_prefetch")
        assert _wait_until(lambda: (comp() or {}).get("status") == DEGRADED)
        # the gauge follows the next scan — poll it
        assert _wait_until(lambda: metrics_mod.get_registry().gauge(
            "component_health", "", ("component",))
            .labels("device_prefetch").value >= 1)
    finally:
        unwedge.set()
        t.join(10)
    assert len(got) == 3
    # run complete -> heartbeat unregistered -> gauge back to ok
    assert "device_prefetch" not in h.status()["components"]
    assert metrics_mod.get_registry().gauge(
        "component_health", "", ("component",)) \
        .labels("device_prefetch").value == 0
    it.close()


def test_serving_dispatcher_stall_over_health_route():
    """The acceptance flow: a blocked dispatcher flips GET /health to
    degraded within the watchdog interval, 503s once unhealthy, and
    recovers to ok when unblocked."""
    from deeplearning4j_tpu.serving.inference_server import InferenceServer

    net = MultiLayerNetwork(_mlp_conf(n_in=4, n_out=2)).init()
    srv = InferenceServer(net, max_batch_size=8, health_stall_after=0.2)
    port = srv.start()

    def get_health_route():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=5) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        code, body = get_health_route()
        assert (code, body["status"]) == (200, OK)

        # the registry-JSON scrape cli metrics --watch --url diffs
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?format=registry",
                timeout=5) as r:
            snap = json.loads(r.read())
        assert snap["component_health"]["type"] == "gauge"

        blocked = threading.Event()
        orig = net.output

        def wedged_output(xx, *a, **k):
            blocked.wait(20)
            return orig(xx, *a, **k)

        net.output = wedged_output
        res = []
        client = threading.Thread(
            target=lambda: res.append(np.asarray(srv.inference.output(
                np.random.default_rng(0).random((2, 4), np.float32)))),
            daemon=True, name="dl4j-bb-client")
        client.start()
        # degraded within the watchdog interval...
        assert _wait_until(lambda: get_health_route()[1]["status"] != OK)
        code, body = get_health_route()
        comp = body["components"]["serving_dispatcher"]
        assert comp["status"] in (DEGRADED, UNHEALTHY)
        assert "dl4j-serving-dispatch" in comp["stalled_threads"]
        # ...503 once unhealthy (stall_after * 4)...
        assert _wait_until(lambda: get_health_route()[0] == 503, timeout=10)
        assert get_health_route()[1]["status"] == UNHEALTHY
        # ...and full recovery when unblocked
        net.output = orig
        blocked.set()
        client.join(10)
        assert res and res[0].shape == (2, 2)
        assert _wait_until(
            lambda: get_health_route()[1]["status"] == OK)
        assert get_health_route()[0] == 200
    finally:
        srv.stop()
    # shutdown unregisters the serving components
    comps = get_health().status()["components"]
    assert "serving_dispatcher" not in comps
    assert "serving_collector" not in comps


# -- SIGTERM forensics round-trip ---------------------------------------------

_CHILD_SCRIPT = """
import os, sys, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deeplearning4j_tpu.utils.blackbox import install_crash_hooks
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.data.dataset import DataSet

dump_path, marker = sys.argv[1], sys.argv[2]
install_crash_hooks(dump_path, dump_on_exit=False)
conf = (NeuralNetConfiguration.builder().seed(1).list()
        .layer(DenseLayer(n_in=4, n_out=4, activation="relu"))
        .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                           loss="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
x = rng.random((8, 4), np.float32)
y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]

class Endless(DataSetIterator):
    def __iter__(self):
        while True:
            yield DataSet(x, y)
            time.sleep(0.01)
    def reset(self): pass
    def batch_size(self): return 8
    def total_examples(self): return None

from deeplearning4j_tpu.train.listeners import IterationListener

class Marker(IterationListener):
    # marker keyed on FIT iterations (the prefetch pipeline's iterator
    # position runs ahead of the dispatched steps), so the parent's
    # SIGTERM arrives with >= 4 steps in the flight recorder
    def iteration_done(self, model, iteration, info):
        time.sleep(0.03)
        if iteration >= 3 and not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("ready")

net.set_listeners(Marker())
net.fit(Endless(), epochs=1, async_prefetch=True)
"""


def test_sigterm_mid_fit_leaves_renderable_dump(tmp_path, capsys):
    dump_path = str(tmp_path / "crash.json")
    marker = str(tmp_path / "ready")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD_SCRIPT.format(repo=REPO),
         dump_path, marker],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        assert _wait_until(lambda: os.path.exists(marker), timeout=120,
                           interval=0.1), "child never reached step 4"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(10)
    assert os.path.exists(dump_path), proc.stderr.read().decode()
    with open(dump_path) as f:
        doc = json.load(f)
    assert "signal" in doc["reason"]
    # the fit was mid-flight: at least the marker's 4 steps recorded
    assert doc["last_step"] is not None and doc["last_step"] >= 3
    names = [t["name"] for t in doc["threads"]]
    # the framework's own workers are in the dump with their stacks:
    # the async-prefetch pipeline threads and the watchdog
    assert any(n.startswith("dl4j-pipeline") for n in names), names
    assert "dl4j-watchdog" in names
    dl4j_stacks = [t for t in doc["threads"]
                   if t["name"].startswith("dl4j-") and t["alive"]]
    assert all(t["stack"] for t in dl4j_stacks)

    # and `cli blackbox` renders it
    from deeplearning4j_tpu.cli import main as cli_main

    assert cli_main(["blackbox", dump_path]) == 0
    out = capsys.readouterr().out
    assert "blackbox dump" in out
    assert f"last step index: {doc['last_step']}" in out
    assert "dl4j-watchdog" in out


# -- listener + UI storage path ----------------------------------------------

def test_health_transition_listener_routes_records():
    from deeplearning4j_tpu.train.listeners import HealthTransitionListener
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    storage = InMemoryStatsStorage()
    lst = HealthTransitionListener(storage, session_id="bb-session")
    h = get_health()
    hb = h.register("bb_listener", stall_after=0.08)
    ev = threading.Event()

    def work():
        with hb.busy():
            ev.wait(10)

    t = threading.Thread(target=work, daemon=True, name="dl4j-bb-lst")
    t.start()
    try:
        assert _wait_until(lambda: h.transitions_since(lst._seq))
    finally:
        ev.set()
        t.join(5)
    lst.iteration_done(None, 7, {})
    ups = storage.get_updates("bb-session")
    assert ups, "transition record never routed"
    rec = ups[-1]
    assert rec["iteration"] == 7
    comps = [tr["component"] for tr in rec["health_transitions"]]
    assert "bb_listener" in comps
    assert rec["health_level"]["bb_listener"] >= 1
    # cursor advanced: a second drain with no news routes nothing
    n = len(storage.get_updates("bb-session"))
    _wait_until(lambda: h.status()["components"]["bb_listener"]["status"]
                == OK)
    lst.on_fit_end(None)  # may flush the recovery transition
    h.unregister(hb)
    lst.iteration_done(None, 8, {})
    assert len(storage.get_updates("bb-session")) <= n + 1


def test_stats_listener_embeds_health_history():
    from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener

    storage = InMemoryStatsStorage()
    net = MultiLayerNetwork(_mlp_conf()).init()
    lst = StatsListener(storage, session_id="bb-stats")
    x, y = _xy(n=10)
    net.set_listeners(lst)
    # inject a transition mid-run by stalling a scratch component
    h = get_health()
    hb = h.register("bb_stats_comp", stall_after=0.05)
    ev = threading.Event()

    def work():
        with hb.busy():
            ev.wait(10)

    t = threading.Thread(target=work, daemon=True, name="dl4j-bb-stats")
    t.start()
    try:
        assert _wait_until(lambda: h.transitions_since(lst._health_seq))
        net.fit(x, y, epochs=1, batch_size=10, async_prefetch=False)
    finally:
        ev.set()
        t.join(5)
        h.unregister(hb)
        net.set_listeners()
    ups = storage.get_updates("bb-stats")
    assert ups
    assert any("health_level" in u
               and u["health_level"].get("bb_stats_comp", 0) >= 1
               for u in ups)


# -- cli surfaces -------------------------------------------------------------

def test_cli_blackbox_missing_file(capsys):
    from deeplearning4j_tpu.cli import main as cli_main

    assert cli_main(["blackbox", "/nonexistent/dump.json"]) == 2


def test_cli_metrics_watch_prints_deltas(capsys):
    from deeplearning4j_tpu.cli import main as cli_main

    c = metrics_mod.get_registry().counter(
        "bb_watch_demo_total", "test counter").labels()
    g = metrics_mod.get_registry().gauge("bb_watch_gauge", "test").labels()
    c.inc(1)
    g.set(0)

    def mutate():
        # the delay must land strictly between the watch loop's baseline
        # snapshot (taken ~instantly) and its first tick (at ~1.0s): a
        # 0.35s/1.0s split keeps both margins wide enough that a loaded
        # 2-core CI box can't reorder them (0.08s/0.25s flaked there)
        time.sleep(0.35)
        c.inc(3)
        g.set(7)

    t = threading.Thread(target=mutate, daemon=True, name="dl4j-bb-watch")
    t.start()
    rc = cli_main(["metrics", "--watch", "1.0", "--watch-count", "2"])
    t.join(5)
    assert rc == 0
    out = capsys.readouterr().out
    assert "bb_watch_demo_total" in out
    assert "+3" in out
    assert "bb_watch_gauge" in out and "7" in out
    assert "tick" in out


def test_register_collision_with_live_heartbeat_gets_suffixed_name():
    """Two live registrants of one component name (e.g. two concurrent
    fits with hang_timeout) must BOTH stay under watchdog coverage —
    the newcomer is suffixed, not silently evicting the first."""
    h = get_health()
    hb1 = h.register("bb_collide", stall_after=5.0)
    ev = threading.Event()

    def work():
        with hb1.busy():
            ev.wait(10)

    t = threading.Thread(target=work, daemon=True, name="dl4j-bb-col")
    t.start()
    try:
        assert _wait_until(hb1.has_busy_slots)
        hb2 = h.register("bb_collide", stall_after=5.0)
        assert hb2.name == "bb_collide#2"
        comps = h.status()["components"]
        assert "bb_collide" in comps and "bb_collide#2" in comps
        # idle collision = restart: replaced under the same name
        h.unregister(hb2)
        hb3 = h.register("bb_collide#2", stall_after=5.0)
        assert hb3.name == "bb_collide#2"
        h.unregister(hb3)
    finally:
        ev.set()
        t.join(5)
        h.unregister(hb1)
    assert "bb_collide" not in h.status()["components"]
