"""GloVe — global-vectors embedding learning.

Reference parity: models/embeddings/learning/impl/elements/GloVe.java
(AdaGrad weighted-least-squares over co-occurrence pairs; one shared
syn0 table plus per-word biases — iterateSample computes
``w_i·w_j + b_i + b_j − log X_ij``, weights the squared error by
``min((X/x_max)^alpha, 1)`` and applies AdaGrad per row) and
models/glove/AbstractCoOccurrences.java:322-374 (forward-window
co-occurrence scan, 1/distance weights, mirrored when symmetric; the
count machinery under models/glove/count/ shards this to disk).

TPU-first redesign: the reference trains pair-at-a-time across Java
threads racing on shared arrays; here the co-occurrence table is
accumulated once (native/corpus.cpp corpus_cooc_build when the C++
pipeline is available, a numpy pass otherwise) and training runs as a
jitted fixed-shape batch step — gather both row sets, weighted-lsq
gradient, AdaGrad scale, scatter-add back — with buffer donation, so
the whole epoch is a stream of identical XLA executables instead of a
hot Python/JNI loop.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    VectorsConfiguration,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache

logger = logging.getLogger("deeplearning4j_tpu.nlp")

_EPS = 1e-8


def cooccurrences_indexed(indexed: Sequence[np.ndarray], window: int = 5,
                          symmetric: bool = True
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Numpy co-occurrence accumulation over vocab-indexed sentences —
    same semantics as the native corpus_cooc_build (forward window,
    1/distance weights, optional mirroring). Returns COO arrays
    (rows, cols, weights)."""
    acc: Dict[Tuple[int, int], float] = {}
    for sent in indexed:
        n = sent.size
        for x in range(n):
            stop = min(x + window + 1, n)
            for j in range(x + 1, stop):
                w = 1.0 / (j - x)
                a, b = int(sent[x]), int(sent[j])
                acc[(a, b)] = acc.get((a, b), 0.0) + w
                if symmetric:
                    acc[(b, a)] = acc.get((b, a), 0.0) + w
    if not acc:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.float32))
    keys = np.asarray(list(acc.keys()), np.int32)
    vals = np.asarray(list(acc.values()), np.float32)
    return keys[:, 0], keys[:, 1], vals


@partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _glove_step(syn0, bias, hist0, histb, i, j, logx, f, lr):
    """One AdaGrad weighted-least-squares batch.

    i/j: [B] row indices (padding points at row 0 with f == 0, which
    contributes zero gradient AND zero AdaGrad history). f is the
    precomputed weighting min((X/x_max)^alpha, 1)."""
    wi, wj = syn0[i], syn0[j]
    diff = jnp.sum(wi * wj, axis=-1) + bias[i] + bias[j] - logx
    fdiff = f * diff                      # [B]
    loss = 0.5 * jnp.sum(fdiff * diff)
    gi = fdiff[:, None] * wj
    gj = fdiff[:, None] * wi
    hist0 = hist0.at[i].add(gi * gi).at[j].add(gj * gj)
    histb = histb.at[i].add(fdiff * fdiff).at[j].add(fdiff * fdiff)
    syn0 = (syn0.at[i].add(-lr * gi * jax.lax.rsqrt(hist0[i] + _EPS))
                 .at[j].add(-lr * gj * jax.lax.rsqrt(hist0[j] + _EPS)))
    bias = (bias.at[i].add(-lr * fdiff * jax.lax.rsqrt(histb[i] + _EPS))
                .at[j].add(-lr * fdiff * jax.lax.rsqrt(histb[j] + _EPS)))
    return syn0, bias, hist0, histb, loss


class Glove(SequenceVectors):
    """GloVe model with the SequenceVectors API surface (fit, fit_file,
    word_vector, similarity, words_nearest, WordVectorSerializer).

    Glove-specific hyperparameters live on VectorsConfiguration:
    x_max, glove_alpha, glove_symmetric, glove_shuffle."""

    def __init__(self, conf: Optional[VectorsConfiguration] = None,
                 sequences: Optional[Iterable[Sequence[str]]] = None,
                 vocab: Optional[VocabCache] = None):
        import dataclasses

        conf = (dataclasses.replace(  # never mutate the caller's conf
            conf, use_hierarchic_softmax=False, negative=0)
            if conf is not None else VectorsConfiguration(
                learning_rate=0.05, use_hierarchic_softmax=False,
                negative=0))  # no output tables: one shared syn0 + biases
        super().__init__(conf, sequences, vocab)
        self.bias: Optional[jnp.ndarray] = None
        self.adagrad_state = None

    # -- training -------------------------------------------------------------

    def train_indexed(self, indexed: List[np.ndarray]):
        rows, cols, vals = cooccurrences_indexed(
            indexed, self.conf.window, self.conf.glove_symmetric)
        self.train_cooccurrences(rows, cols, vals)

    def fit_file(self, path: str, lowercase: bool = False):
        """Native path: vocab AND co-occurrence accumulation both run in
        C++ (corpus.cpp); only the COO arrays cross into Python."""
        from deeplearning4j_tpu import native as native_mod

        if not native_mod.native_available():
            return super().fit_file(path, lowercase=lowercase)
        with native_mod.NativeCorpus(path, lowercase=lowercase) as corpus:
            self._vocab_from_native(corpus)
            rows, cols, vals = corpus.cooccurrences(
                self.conf.min_word_frequency, self.conf.window,
                self.conf.glove_symmetric)
        self.train_cooccurrences(rows, cols, vals)
        return self

    def train_cooccurrences(self, rows: np.ndarray, cols: np.ndarray,
                            vals: np.ndarray):
        """AdaGrad weighted-lsq over the co-occurrence COO table."""
        conf = self.conf
        if self.lookup is None:
            self.build_vocab()
        n = int(rows.size)
        if n == 0:
            logger.warning("GloVe: empty co-occurrence table; nothing to do")
            self.last_loss = float("nan")
            return
        V, D = self.lookup.syn0.shape
        logx = np.log(np.maximum(vals, 1e-12)).astype(np.float32)
        f = np.minimum(
            (vals / conf.x_max) ** conf.glove_alpha, 1.0).astype(np.float32)

        syn0 = self.lookup.syn0
        bias = (self.bias if self.bias is not None
                else jnp.zeros((V,), jnp.float32))
        if self.adagrad_state is not None:
            hist0, histb = self.adagrad_state
        else:
            hist0 = jnp.zeros((V, D), jnp.float32)
            histb = jnp.zeros((V,), jnp.float32)

        B = min(conf.batch_size, max(n, 1))
        n_batches = -(-n // B)
        self.last_loss = float("nan")
        for epoch in range(conf.epochs):
            order = (self._rng.permutation(n) if conf.glove_shuffle
                     else np.arange(n))
            losses = []  # device arrays; read back once per epoch so the
            for b in range(n_batches):  # dispatch pipeline stays full
                sel = order[b * B:(b + 1) * B]
                pad = B - sel.size
                bi = np.concatenate([rows[sel], np.zeros(pad, np.int32)])
                bj = np.concatenate([cols[sel], np.zeros(pad, np.int32)])
                bx = np.concatenate([logx[sel], np.zeros(pad, np.float32)])
                bf = np.concatenate([f[sel], np.zeros(pad, np.float32)])
                syn0, bias, hist0, histb, loss = _glove_step(
                    syn0, bias, hist0, histb,
                    jnp.asarray(bi), jnp.asarray(bj),
                    jnp.asarray(bx), jnp.asarray(bf),
                    jnp.float32(conf.learning_rate))
                losses.append(loss)
            self.last_loss = float(np.sum(np.asarray(losses))) / n
            logger.info("GloVe epoch %d: loss/pair %.5f", epoch,
                        self.last_loss)
        self.lookup.syn0 = syn0
        self.bias = bias
        self.adagrad_state = (hist0, histb)
