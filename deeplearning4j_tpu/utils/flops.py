"""Analytic FLOP estimates for MFU reporting.

The standard model-FLOPs accounting (as in the MFU literature): a matmul or
conv contributes 2·MACs forward; a training step costs ≈ 3× forward (one
forward + two matmul-shaped backward passes). Elementwise/normalization
work is excluded — it is bandwidth-, not FLOPs-bound on TPU, and excluding
it makes MFU comparable across frameworks.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration,
    LayerVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import ConvolutionalInput, RecurrentInput


def _layer_forward_flops(conf, it) -> float:
    """Per-example forward FLOPs of one layer given its input type."""
    inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
    if isinstance(inner, L.ConvolutionLayer):
        out = inner.output_type(it)
        k = inner.kernel_size
        return 2.0 * k[0] * k[1] * inner.n_in * inner.n_out * out.height * out.width
    if isinstance(inner, L.Convolution1DLayer):
        out = inner.output_type(it)
        t = out.timesteps or (it.timesteps or 1)
        return 2.0 * inner.kernel_size * inner.n_in * inner.n_out * t
    if isinstance(inner, (L.LSTM, L.GravesLSTM, L.GravesBidirectionalLSTM)):
        t = it.timesteps or 1
        per_step = 2.0 * 4 * inner.n_out * (inner.n_in + inner.n_out)
        mult = 2 if isinstance(inner, L.GravesBidirectionalLSTM) else 1
        return per_step * t * mult
    if isinstance(inner, L.RnnOutputLayer):
        t = it.timesteps or 1
        return 2.0 * inner.n_in * inner.n_out * t
    if isinstance(inner, (L.DenseLayer, L.OutputLayer, L.CenterLossOutputLayer,
                          L.AutoEncoder)):
        return 2.0 * inner.n_in * inner.n_out
    if isinstance(inner, L.EmbeddingLayer):
        return 0.0  # gather, not matmul
    return 0.0


def graph_forward_flops(conf: ComputationGraphConfiguration) -> Optional[float]:
    """Per-example forward FLOPs of a ComputationGraph, via a shape-
    inference walk of the topo order. None if input_types are unset."""
    if conf.input_types is None:
        return None
    types = dict(zip(conf.inputs, conf.input_types))
    total = 0.0
    for name in conf.topological_order():
        if name in types:
            continue
        v = conf.vertices[name]
        its = [types.get(i) for i in conf.vertex_inputs[name]]
        if any(i is None for i in its):
            types[name] = None
            continue
        if isinstance(v, LayerVertex):
            it = its[0]
            if v.preprocessor is not None:
                it = v.preprocessor.output_type(it)
            total += _layer_forward_flops(v.layer, it)
            types[name] = v.layer.output_type(it)
        else:
            types[name] = v.output_type(its)
    return total


def mln_forward_flops(conf) -> Optional[float]:
    """Per-example forward FLOPs of a MultiLayerConfiguration."""
    if conf.input_type is None:
        return None
    it = conf.input_type
    total = 0.0
    for i, layer in enumerate(conf.layers):
        pp = conf.preprocessors.get(str(i))
        if pp is not None:
            it = pp.output_type(it)
        total += _layer_forward_flops(layer, it)
        it = layer.output_type(it)
    return total


def train_step_flops(forward_flops: float, batch: int) -> float:
    """Model FLOPs of one optimizer step: 3× forward (fwd + grad wrt
    activations + grad wrt weights), times the batch."""
    return 3.0 * forward_flops * batch


# bf16 peak matmul throughput per chip, for MFU. v5e: 197 TFLOP/s.
TPU_PEAK_FLOPS = {
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def peak_flops_per_chip(default: float = 197e12) -> float:
    """Best-effort peak bf16 FLOP/s of the current chip."""
    import os

    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env)
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower().replace(" ", "")
        for key, val in TPU_PEAK_FLOPS.items():
            if key in kind:
                return val
    except Exception:
        pass
    return default
