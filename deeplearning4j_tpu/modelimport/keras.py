"""Keras 1.x HDF5 importer.

Reference: deeplearning4j-modelimport — KerasModelImport.java:39 (static
entry points), KerasModel.java:73-75,550-556 (HDF5 attrs ``model_config`` /
``training_config`` / ``model_weights`` root), KerasLayer.java (the layer
dispatcher + field vocabulary), the 13 per-layer translators under
``layers/``, and the dim-ordering transposes in
KerasConvolution.setWeights (KerasConvolution.java:108-138) and
KerasLstm.setWeights (KerasLstm.java:138-178).

TPU-first notes:

- This framework's conv layout is NHWC with HWIO kernels — exactly the
  TensorFlow-backend Keras layout, so ``dim_ordering: "tf"`` weights copy
  with NO transpose (the reference, being NCHW/OIHW, permutes (3,2,0,1)).
  Theano ordering stores OIHW *and* applies true convolution, so those
  kernels are rotated 180° spatially then transposed to HWIO.
- Keras ``Flatten`` on NHWC activations is row-major over (H, W, C) —
  identical to this framework's CnnToFeedForwardPreProcessor reshape, so
  no TensorFlowCnnToFeedForwardPreProcessor-style permutation is needed
  for "tf" ordering.
- Keras LSTM stores 12 arrays (W/U/b × i,f,c,o); they are packed into the
  fused [nIn, 4H] / [H, 4H] / [4H] blocks in this framework's [i|f|g|o]
  gate order (nn/layers/recurrent.py).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph import (
    ElementWiseVertex,
    MergeVertex,
    PreprocessorVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalInput,
    FeedForwardInput,
    RecurrentInput,
)
from deeplearning4j_tpu.nn.conf.network import Builder
from deeplearning4j_tpu.nn.conf.preprocessors import CnnToFeedForwardPreProcessor


class KerasImportError(Exception):
    """Invalid/unsupported Keras configuration
    (reference: InvalidKerasConfigurationException /
    UnsupportedKerasConfigurationException)."""


# --- field vocabulary (KerasLayer.java:46-120) ---

_ACTIVATIONS = {
    "softmax": "softmax",
    "softplus": "softplus",
    "softsign": "softsign",
    "relu": "relu",
    "tanh": "tanh",
    "sigmoid": "sigmoid",
    "hard_sigmoid": "hardsigmoid",
    "linear": "identity",
    "elu": "elu",
}

_LOSSES = {
    "mean_squared_error": "mse",
    "mse": "mse",
    "mean_absolute_error": "mean_absolute_error",
    "mae": "mean_absolute_error",
    "mean_absolute_percentage_error": "mean_absolute_percentage_error",
    "mape": "mean_absolute_percentage_error",
    "mean_squared_logarithmic_error": "mean_squared_logarithmic_error",
    "msle": "mean_squared_logarithmic_error",
    "squared_hinge": "squared_hinge",
    "hinge": "hinge",
    "binary_crossentropy": "xent",
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "kullback_leibler_divergence": "kl_divergence",
    "kld": "kl_divergence",
    "poisson": "poisson",
    "cosine_proximity": "cosine_proximity",
}

_INITS = {
    "uniform": "uniform",
    "zero": "zero",
    "glorot_normal": "xavier",
    "glorot_uniform": "xavier_uniform",
    "he_normal": "relu",
    "he_uniform": "relu_uniform",
    "lecun_uniform": "lecun_uniform",
    "normal": "normal",
    "identity": "identity",
}


def map_activation(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise KerasImportError(f"Unsupported Keras activation: {name!r}")


def map_loss(name: str) -> str:
    try:
        return _LOSSES[name]
    except KeyError:
        raise KerasImportError(f"Unsupported Keras loss: {name!r}")


def map_init(name: Optional[str]) -> Optional[str]:
    if name is None:
        return None
    mapped = _INITS.get(name)
    if mapped is None:
        raise KerasImportError(f"Unsupported Keras weight init: {name!r}")
    return mapped


def _dl4j_dropout(cfg: dict) -> Optional[float]:
    """Keras dropout fraction -> retain probability
    (KerasLayer.getDropoutFromConfig: dropout = 1 - p)."""
    p = cfg.get("dropout", cfg.get("dropout_W", cfg.get("p", 0.0))) or 0.0
    return (1.0 - float(p)) if p else None


def _border(cfg: dict):
    mode = cfg.get("border_mode", "valid")
    if mode == "same":
        return L.ConvolutionMode.SAME
    if mode == "valid":
        return L.ConvolutionMode.TRUNCATE
    raise KerasImportError(f"Unsupported border_mode: {mode!r}")


def _input_type_from_shape(shape: Sequence[Optional[int]], dim_ordering: str):
    """batch_input_shape (without batch dim) -> InputType."""
    dims = [d for d in shape]
    if len(dims) == 1:
        return FeedForwardInput(int(dims[0]))
    if len(dims) == 2:
        ts = None if dims[0] is None else int(dims[0])
        return RecurrentInput(int(dims[1]), ts)
    if len(dims) == 3:
        if dim_ordering == "th":  # (C, H, W)
            c, h, w = dims
        else:  # tf: (H, W, C)
            h, w, c = dims
        return ConvolutionalInput(int(h), int(w), int(c))
    raise KerasImportError(f"Unsupported input shape: {shape}")


# --- per-layer config translators (reference: layers/Keras*.java) ---


def _translate_layer(class_name: str, cfg: dict, dim_ordering: str):
    """Return (layer_conf | None, extras) where extras may carry
    'flatten': True (insert CnnToFF preprocessor before the next layer)."""
    name = cfg.get("name")
    act = cfg.get("activation")
    dropout = _dl4j_dropout(cfg)
    init = map_init(cfg.get("init"))

    if class_name in ("Dense", "TimeDistributedDense"):
        return (
            L.DenseLayer(
                name=name,
                n_out=int(cfg["output_dim"]),
                activation=map_activation(act),
                weight_init=init,
                dropout=dropout,
            ),
            {},
        )
    if class_name == "Activation":
        return L.ActivationLayer(name=name, activation=map_activation(act)), {}
    if class_name == "Dropout":
        return L.DropoutLayer(name=name, dropout=dropout), {}
    if class_name in ("Convolution2D", "Conv2D"):
        subsample = cfg.get("subsample", (1, 1))
        return (
            L.ConvolutionLayer(
                name=name,
                n_out=int(cfg["nb_filter"]),
                kernel_size=(int(cfg["nb_row"]), int(cfg["nb_col"])),
                stride=(int(subsample[0]), int(subsample[1])),
                convolution_mode=_border(cfg),
                activation=map_activation(act),
                weight_init=init,
                dropout=dropout,
            ),
            {},
        )
    if class_name == "Convolution1D":
        return (
            L.Convolution1DLayer(
                name=name,
                n_out=int(cfg["nb_filter"]),
                kernel_size=int(cfg["filter_length"]),
                stride=int(cfg.get("subsample_length", 1)),
                convolution_mode=_border(cfg),
                activation=map_activation(act),
                weight_init=init,
                dropout=dropout,
            ),
            {},
        )
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        pool = cfg.get("pool_size", (2, 2))
        strides = cfg.get("strides") or pool
        return (
            L.SubsamplingLayer(
                name=name,
                pooling_type=(
                    L.PoolingType.MAX
                    if class_name.startswith("Max")
                    else L.PoolingType.AVG
                ),
                kernel_size=(int(pool[0]), int(pool[1])),
                stride=(int(strides[0]), int(strides[1])),
                convolution_mode=_border(cfg),
            ),
            {},
        )
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        pool = int(cfg.get("pool_length", 2))
        stride = cfg.get("stride")
        return (
            L.Subsampling1DLayer(
                name=name,
                pooling_type=(
                    L.PoolingType.MAX
                    if class_name.startswith("Max")
                    else L.PoolingType.AVG
                ),
                kernel_size=pool,
                stride=int(stride) if stride else pool,
                convolution_mode=_border(cfg),
            ),
            {},
        )
    if class_name in (
        "GlobalMaxPooling1D",
        "GlobalMaxPooling2D",
        "GlobalAveragePooling1D",
        "GlobalAveragePooling2D",
    ):
        return (
            L.GlobalPoolingLayer(
                name=name,
                pooling_type=(
                    L.PoolingType.MAX if "Max" in class_name else L.PoolingType.AVG
                ),
            ),
            {},
        )
    if class_name == "ZeroPadding2D":
        pad = cfg.get("padding", (1, 1))
        return (
            L.ZeroPaddingLayer(
                name=name,
                padding=(int(pad[0]), int(pad[0]), int(pad[1]), int(pad[1])),
            ),
            {},
        )
    if class_name == "BatchNormalization":
        if int(cfg.get("mode", 0)) != 0:
            raise KerasImportError(
                "Only BatchNormalization mode=0 is supported "
                "(KerasBatchNormalization.java enforces the same)"
            )
        return (
            L.BatchNormalization(
                name=name,
                decay=float(cfg.get("momentum", 0.99)),
                eps=float(cfg.get("epsilon", 1e-3)),
            ),
            {},
        )
    if class_name == "Embedding":
        return (
            L.EmbeddingLayer(
                name=name,
                n_in=int(cfg["input_dim"]),
                n_out=int(cfg["output_dim"]),
                has_bias=False,
                activation="identity",
                weight_init=init,
            ),
            {},
        )
    if class_name == "LSTM":
        return (
            L.LSTM(
                name=name,
                n_out=int(cfg["output_dim"]),
                activation=map_activation(act),
                gate_activation=map_activation(cfg.get("inner_activation")),
                forget_gate_bias_init=(
                    1.0 if cfg.get("forget_bias_init", "one") == "one" else 0.0
                ),
                weight_init=init,
                dropout=dropout,
            ),
            {"return_sequences": bool(cfg.get("return_sequences", False))},
        )
    if class_name == "Flatten":
        return None, {"flatten": True}
    if class_name == "InputLayer":
        return None, {"input": True}
    raise KerasImportError(f"Unsupported Keras layer: {class_name!r}")


# --- weight readers ---


def _strip_param_name(layer_name: str, weight_name: str) -> str:
    """'dense_1_W:0' or 'dense_1_W' -> 'W' (KerasModel.java:326 comment)."""
    base = weight_name.rsplit("/", 1)[-1]
    if base.endswith(":0"):
        base = base[:-2]
    prefix = layer_name + "_"
    if base.startswith(prefix):
        base = base[len(prefix):]
    return base


def load_keras_weights(h5group) -> Dict[str, Dict[str, np.ndarray]]:
    """Read {layer_name: {short_param_name: array}} from a Keras weights
    group (the ``model_weights`` root or a weights-only file root), using
    the ``layer_names``/``weight_names`` attributes the Keras 1.x writer
    emits (KerasModel.helperImportWeights, KerasModel.java:299-360)."""
    out: Dict[str, Dict[str, np.ndarray]] = {}
    layer_names = [
        n.decode() if isinstance(n, bytes) else str(n)
        for n in h5group.attrs.get("layer_names", list(h5group.keys()))
    ]
    for lname in layer_names:
        grp = h5group[lname]
        wnames = [
            n.decode() if isinstance(n, bytes) else str(n)
            for n in grp.attrs.get("weight_names", list(grp.keys()))
        ]
        if not wnames:
            continue
        params = {}
        for wn in wnames:
            params[_strip_param_name(lname, wn)] = np.asarray(grp[wn])
        out[lname] = params
    return out


def _conv_kernel_to_hwio(W: np.ndarray, dim_ordering: str) -> np.ndarray:
    """Keras conv kernel -> HWIO.

    tf ordering already IS (kh, kw, in, out). Theano stores (out, in, kh,
    kw) and applies true convolution (filters flipped), so rotate 180° then
    transpose (KerasConvolution.java:119-138)."""
    if dim_ordering == "th":
        return np.ascontiguousarray(W[:, :, ::-1, ::-1].transpose(2, 3, 1, 0))
    return W


def _pack_lstm(params: Dict[str, np.ndarray]):
    """Keras 1.x LSTM arrays -> fused {W:[nIn,4H], RW:[H,4H], b:[4H]} in
    this framework's [i|f|g|o] gate order (KerasLstm.java:138-178 does the
    analogous packing into DL4J's [c|f|o|i] order)."""
    try:
        Ws = [params["W_i"], params["W_f"], params["W_c"], params["W_o"]]
        Us = [params["U_i"], params["U_f"], params["U_c"], params["U_o"]]
        bs = [params["b_i"], params["b_f"], params["b_c"], params["b_o"]]
    except KeyError as e:
        raise KerasImportError(f"Keras LSTM layer missing parameter {e}")
    return {
        "W": np.concatenate(Ws, axis=1),
        "RW": np.concatenate(Us, axis=1),
        "b": np.concatenate(bs, axis=0),
    }


def _layer_params_to_native(conf, kparams: Dict[str, np.ndarray], dim_ordering: str):
    """Map one Keras layer's weight dict onto this framework's param dict
    (and BN running state). Returns (params, state_or_None)."""
    if isinstance(conf, (L.LSTM, L.GravesLSTM)):
        return _pack_lstm(kparams), None
    if isinstance(conf, L.BatchNormalization):
        # Keras 1.x names: gamma, beta, running_mean, running_std (the
        # latter holds the VARIANCE — KerasBatchNormalization.java:129-133
        # maps it to GLOBAL_VAR)
        params = {"gamma": kparams["gamma"], "beta": kparams["beta"]}
        state = {
            "mean": kparams["running_mean"],
            "var": kparams.get("running_std", kparams.get("running_var")),
        }
        if state["var"] is None:
            raise KerasImportError("BatchNormalization missing running_std")
        return params, state
    if isinstance(conf, L.ConvolutionLayer):
        out = {"W": _conv_kernel_to_hwio(kparams["W"], dim_ordering)}
        if "b" in kparams:
            out["b"] = kparams["b"]
        return out, None
    if isinstance(conf, L.Convolution1DLayer):
        W = kparams["W"]
        if W.ndim == 4:  # Keras 1 stores (filter_length, 1, nIn, nOut)
            W = W.reshape(W.shape[0], W.shape[2], W.shape[3])
        out = {"W": W}
        if "b" in kparams:
            out["b"] = kparams["b"]
        return out, None
    if isinstance(conf, L.EmbeddingLayer):
        return {"W": kparams["W"]}, None
    if isinstance(conf, (L.DenseLayer, L.OutputLayer)):
        return {"W": kparams["W"], "b": kparams["b"]}, None
    raise KerasImportError(f"No weight mapping for layer {type(conf).__name__}")


# --- model config parsing ---


def _parse_model_config(model_config_json: str):
    cfg = json.loads(model_config_json)
    class_name = cfg.get("class_name")
    if class_name not in ("Sequential", "Model"):
        raise KerasImportError(f"Unsupported Keras model class: {class_name!r}")
    return class_name, cfg["config"]


def _training_loss(training_config_json: Optional[str]) -> Optional[str]:
    if not training_config_json:
        return None
    tc = json.loads(training_config_json)
    loss = tc.get("loss")
    if isinstance(loss, dict):  # per-output dict: take the single entry
        loss = next(iter(loss.values()))
    return map_loss(loss) if isinstance(loss, str) else None


def import_keras_sequential_config(
    model_config_json: str,
    training_config_json: Optional[str] = None,
    *,
    precision: str = "f32",
):
    """Keras Sequential JSON -> MultiLayerConfiguration
    (reference: KerasModelImport.importKerasSequentialConfiguration).

    Returns (conf, layer_names) where layer_names[i] is the Keras layer
    name supplying weights for network layer i (None for plain reshapes)."""
    class_name, layer_list = _parse_model_config(model_config_json)
    if class_name != "Sequential":
        raise KerasImportError("Not a Sequential model; use import_keras_model_config")
    loss = _training_loss(training_config_json)

    builder = Builder().weight_init("xavier").precision(precision).list()
    input_type = None
    dim_ordering = "tf"
    pending_flatten = False
    layer_names: List[Optional[str]] = []
    n_layers = len(layer_list)
    for i, entry in enumerate(layer_list):
        cname = entry["class_name"]
        cfg = dict(entry.get("config", {}))
        if "dim_ordering" in cfg:
            dim_ordering = cfg["dim_ordering"]
        if input_type is None and "batch_input_shape" in cfg:
            input_type = _input_type_from_shape(
                cfg["batch_input_shape"][1:], dim_ordering
            )
        conf, extras = _translate_layer(cname, cfg, dim_ordering)
        if extras.get("input"):
            continue
        if extras.get("flatten"):
            pending_flatten = True
            continue
        if conf is None:
            continue
        is_last = i == n_layers - 1 or all(
            e["class_name"] in ("Activation", "Dropout") for e in layer_list[i + 1:]
        )
        if loss is not None and is_last and isinstance(conf, L.DenseLayer):
            # final Dense under a training config becomes the loss head
            # (reference: KerasLoss appends a LossLayer; an OutputLayer is
            # this framework's fused dense+loss equivalent)
            act = conf.activation
            for e in layer_list[i + 1:]:
                if e["class_name"] == "Activation":
                    act = map_activation(e["config"].get("activation"))
            conf = L.OutputLayer(
                name=conf.name,
                n_out=conf.n_out,
                activation=act,
                weight_init=conf.weight_init,
                dropout=conf.dropout,
                loss=loss,
            )
            loss = None
        idx = len(layer_names)
        if pending_flatten:
            builder.input_pre_processor(idx, CnnToFeedForwardPreProcessor())
            pending_flatten = False
        builder.layer(conf)
        layer_names.append(cfg.get("name"))
        if isinstance(conf, L.OutputLayer) and loss is None:
            break
    if input_type is not None:
        builder.set_input_type(input_type)
    return builder.build(), layer_names


def import_keras_model_config(
    model_config_json: str,
    training_config_json: Optional[str] = None,
    *,
    precision: str = "f32",
):
    """Keras functional ``Model`` JSON -> ComputationGraphConfiguration
    (reference: KerasModel.getComputationGraphConfiguration,
    KerasModel.java:377). Returns (conf, layer_names)."""
    class_name, cfg = _parse_model_config(model_config_json)
    if class_name != "Model":
        raise KerasImportError("Not a functional Model; use the Sequential path")
    loss = _training_loss(training_config_json)

    layers = cfg["layers"]
    output_names = [o[0] for o in cfg["output_layers"]]
    gb = Builder().weight_init("xavier").precision(precision).graph_builder()
    input_types = []
    dim_ordering = "tf"
    layer_names: List[Optional[str]] = []
    name_alias: Dict[str, str] = {}  # keras name -> graph vertex feeding it

    for entry in layers:
        cname = entry["class_name"]
        lcfg = dict(entry.get("config", {}))
        kname = lcfg.get("name") or entry.get("name")
        if "dim_ordering" in lcfg:
            dim_ordering = lcfg["dim_ordering"]
        inbound = entry.get("inbound_nodes") or []
        inputs = [name_alias.get(n[0], n[0]) for n in (inbound[0] if inbound else [])]

        if cname == "InputLayer":
            gb.add_inputs(kname)
            input_types.append(
                _input_type_from_shape(lcfg["batch_input_shape"][1:], dim_ordering)
            )
            continue
        if cname == "Merge":
            mode = lcfg.get("mode", "concat")
            if mode == "concat":
                gb.add_vertex(kname, MergeVertex(), *inputs)
            elif mode in ("sum", "ave", "mul", "max"):
                op = {"sum": "add", "ave": "avg", "mul": "product", "max": "max"}[mode]
                gb.add_vertex(kname, ElementWiseVertex(op=op), *inputs)
            else:
                raise KerasImportError(f"Unsupported Merge mode: {mode!r}")
            continue
        if cname == "Flatten":
            gb.add_vertex(
                kname,
                PreprocessorVertex(preprocessor=CnnToFeedForwardPreProcessor()),
                *inputs,
            )
            continue
        conf, extras = _translate_layer(cname, lcfg, dim_ordering)
        if conf is None:
            # passthrough (e.g. unhandled no-op): alias this name
            if inputs:
                name_alias[kname] = inputs[0]
            continue
        if loss is not None and kname in output_names and isinstance(conf, L.DenseLayer):
            conf = L.OutputLayer(
                name=conf.name,
                n_out=conf.n_out,
                activation=conf.activation,
                weight_init=conf.weight_init,
                dropout=conf.dropout,
                loss=loss,
            )
        gb.add_layer(kname, conf, *inputs)
        layer_names.append(kname)

    gb.set_outputs(*[name_alias.get(n, n) for n in output_names])
    if input_types:
        gb.set_input_types(*input_types)
    return gb.build(), layer_names


# --- full import (config + weights) ---


def _read_archive(path: str):
    import h5py

    f = h5py.File(path, "r")
    attrs = f.attrs
    mc = attrs.get("model_config")
    if mc is None:
        f.close()
        raise KerasImportError(
            f"{path} has no model_config attribute — not a Keras "
            "save_model() archive (KerasModelImport expects the same)"
        )
    if isinstance(mc, bytes):
        mc = mc.decode()
    tc = attrs.get("training_config")
    if isinstance(tc, bytes):
        tc = tc.decode()
    weights_root = f["model_weights"] if "model_weights" in f else f
    return f, str(mc), (str(tc) if tc is not None else None), weights_root


def _dim_ordering_of(model_config_json: str) -> str:
    cfg = json.loads(model_config_json)
    stack = [cfg]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            if "dim_ordering" in node:
                return node["dim_ordering"]
            stack.extend(node.values())
        elif isinstance(node, list):
            stack.extend(node)
    return "tf"


def _apply_weights(net, layer_names, weights, dim_ordering):
    """Copy imported weights into an initialized network, casting to the
    network's parameter dtype (KerasModel.copyWeightsToLayer)."""
    import jax.numpy as jnp

    confs = list(net.layer_confs)
    for i, kname in enumerate(layer_names):
        if kname is None or kname not in weights:
            continue
        params, state = _layer_params_to_native(confs[i], weights[kname], dim_ordering)
        tmpl = net.params_list[i]
        net.params_list[i] = {
            k: jnp.asarray(v, tmpl[k].dtype if k in tmpl else None)
            for k, v in params.items()
        }
        for k in tmpl:
            if k not in net.params_list[i]:
                raise KerasImportError(
                    f"layer {kname}: imported params missing {k!r}"
                )
        if state is not None:
            stmpl = net.state_list[i] or {}
            net.state_list[i] = {
                k: jnp.asarray(v, stmpl[k].dtype if k in stmpl else None)
                for k, v in state.items()
            }
    return net


def import_keras_sequential_model_and_weights(
    path: str, *, enforce_training_config: bool = False, precision: str = "f32"
):
    """Import a Keras 1.x Sequential ``save_model()`` HDF5 archive ->
    initialized MultiLayerNetwork with copied weights
    (reference: KerasModelImport.importKerasSequentialModelAndWeights)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    f, mc, tc, wroot = _read_archive(path)
    try:
        if enforce_training_config and tc is None:
            raise KerasImportError("Archive has no training_config")
        conf, layer_names = import_keras_sequential_config(mc, tc, precision=precision)
        weights = load_keras_weights(wroot)
    finally:
        f.close()
    net = MultiLayerNetwork(conf).init()
    net = _apply_weights(net, layer_names, weights, _dim_ordering_of(mc))
    # free pre-flight: shapeflow over the translated configuration — a
    # mistranslated archive is diagnosed at import (logged findings, also
    # on net.import_preflight), not at trace time
    from deeplearning4j_tpu.analysis import preflight_report

    net.import_preflight = preflight_report(net.conf, origin=path)
    return net


def import_keras_model_and_weights(
    path: str, *, enforce_training_config: bool = False, precision: str = "f32"
):
    """Import a Keras 1.x functional ``Model`` archive -> initialized
    ComputationGraph (reference:
    KerasModelImport.importKerasModelAndWeights, KerasModelImport.java:39)."""
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph

    f, mc, tc, wroot = _read_archive(path)
    try:
        if enforce_training_config and tc is None:
            raise KerasImportError("Archive has no training_config")
        model_class, _ = _parse_model_config(mc)
        if model_class == "Sequential":
            f.close()
            return import_keras_sequential_model_and_weights(
                path,
                enforce_training_config=enforce_training_config,
                precision=precision,
            )
        conf, layer_names = import_keras_model_config(mc, tc, precision=precision)
        weights = load_keras_weights(wroot)
    finally:
        if f.id.valid:
            f.close()
    net = ComputationGraph(conf).init()
    from deeplearning4j_tpu.analysis import preflight_report

    net.import_preflight = preflight_report(net.conf, origin=path)
    dim_ordering = _dim_ordering_of(mc)
    # graph params are keyed by vertex order; map vertex name -> index
    confs = {}
    for i, name in enumerate(net.layer_vertex_names):
        confs[name] = i

    import jax.numpy as jnp

    for kname in layer_names:
        if kname not in weights or kname not in confs:
            continue
        i = confs[kname]
        params, state = _layer_params_to_native(
            net._layer_confs[i], weights[kname], dim_ordering
        )
        tmpl = net.params_list[i]
        net.params_list[i] = {
            k: jnp.asarray(v, tmpl[k].dtype if k in tmpl else None)
            for k, v in params.items()
        }
        if state is not None:
            stmpl = net.state_list[i] or {}
            net.state_list[i] = {
                k: jnp.asarray(v, stmpl[k].dtype if k in stmpl else None)
                for k, v in state.items()
            }
    return net
