"""Serving entry points: k-NN REST server (reference:
deeplearning4j-nearestneighbor-server), model-inference REST server
(bucketed+pipelined ParallelInference behind POST /predict, plus the
continuous-batching autoregressive decode engine behind POST
/generate), and ParallelInference itself (parallel/)."""

from deeplearning4j_tpu.serving.decode import DecodeEngine
from deeplearning4j_tpu.serving.inference_server import InferenceServer
from deeplearning4j_tpu.serving.knnserver import NearestNeighborsServer

__all__ = ["DecodeEngine", "InferenceServer", "NearestNeighborsServer"]
