"""Tiny shared HTTP scaffolding for the framework's servers (k-NN
serving, training UI, embedding parameter server, Keras-backend entry
point). One place for the Content-Length / parse / respond / error
boilerplate the four servers would otherwise each re-implement.

Robustness contract: every socket read on a handler thread carries a
per-connection timeout (`request_timeout`, default 30s). Without it a
single slowloris client — open the connection, send headers, then
trickle or stall the body — pins one `dl4j-http-*` thread forever and,
repeated, starves the ThreadingHTTPServer. Timed-out connections are
dropped (no response: the peer is by definition not reading) and
counted under `http_request_timeout_total`.

Distributed tracing (utils/tracing, W3C trace-context): with tracing
enabled, every dispatched request runs under an `http/server` span that
JOINS the caller's trace when the request carries a valid `traceparent`
header (inference server, paramserver routes, the UI remote receiver —
every server on this scaffold inherits it), and roots a fresh trace when
it doesn't — a malformed header is treated as absent, never as a
half-empty context. On the client side, `traced_headers()` merges the
active context into an outbound header dict. Both hooks degrade to one
flag check when tracing is off (the serving hot-path overhead guard
covers them).

Tenant identity (utils/tenancy) rides the same rails: `_dispatch`
attaches the `X-Tenant` header's value for the handler's duration and
`traced_headers()` injects the ambient tenant outbound, so a request's
tenant crosses process boundaries next to its traceparent.
"""

from __future__ import annotations

import json
import math
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple

from deeplearning4j_tpu.utils import faultpoints as _faults
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import tenancy as _tenancy
from deeplearning4j_tpu.utils import tracing as _tracing

# handler contract: fn(path, body_bytes, headers) ->
#   (status, content_type, payload_bytes)            or
#   (status, content_type, payload_bytes, extra_headers_dict)  or
#   None for "no such route"
# A payload that is an ITERATOR of byte chunks (not bytes) streams back
# as a chunked HTTP/1.1 response — each chunk is flushed as produced
# (the decode engine's /generate token stream rides this).
Handler = Callable[[str, bytes, dict], Optional[Tuple]]


def _finite(obj):
    """Replace non-finite floats with None, recursively. json.dumps
    serializes float("nan") as bare `NaN`, which is NOT JSON — strict
    parsers (and most non-Python clients) reject the whole body. An
    idle endpoint's percentile fields are the canonical trigger: a
    /metrics scrape before the first request must still parse."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


def json_response(obj, code: int = 200,
                  headers: Optional[dict] = None) -> Tuple:
    # common case (all-finite payloads, e.g. large /predict bodies) stays
    # on the C-speed serializer; only a non-finite payload pays the
    # Python-level _finite walk
    try:
        payload = json.dumps(obj, allow_nan=False)
    except ValueError:
        payload = json.dumps(_finite(obj), allow_nan=False)
    if headers:
        return code, "application/json", payload.encode(), dict(headers)
    return code, "application/json", payload.encode()


def html_response(text: str, code: int = 200) -> Tuple[int, str, bytes]:
    return code, "text/html", text.encode()


def traced_headers(headers: Optional[dict] = None) -> dict:
    """Outbound header dict with the active span context injected as a
    W3C `traceparent` — the client half of cross-process propagation
    (paramserver client, UI remote router). One flag check when tracing
    is off; the input dict is never mutated."""
    out = dict(headers) if headers else {}
    tp = _tracing.current_traceparent()
    if tp is not None:
        out["traceparent"] = tp
    # the tenant identity rides next to the traceparent (one
    # thread-local read when no tenant is attached): a paramserver pull
    # from a metered fit carries the same identity serving books under
    t = _tenancy.current_tenant()
    if t is not None:
        out[_tenancy.HEADER] = t
    return out


class JsonHttpServer:
    """Threaded HTTP server with pluggable GET/POST handlers.

    Handlers may raise: the error is returned as a 400 JSON body and the
    server keeps serving (a malformed request must never kill a
    dashboard/serving process)."""

    def __init__(self, *, get: Optional[Handler] = None,
                 post: Optional[Handler] = None, port: int = 0,
                 request_timeout: float = 30.0):
        self._get = get
        self._post = post
        self.port = int(port)
        # <= 0 means "no timeout" (the repo-wide 0-disables convention);
        # passing 0.0 through would make socketserver settimeout(0.0)
        # the connection NON-BLOCKING and drop every request
        self.request_timeout = (None if request_timeout is None
                                or float(request_timeout) <= 0
                                else float(request_timeout))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._m_timeouts = _metrics.get_registry().counter(
            "http_request_timeout_total",
            "connections dropped because a read exceeded the "
            "per-connection timeout (slowloris protection)").labels()

    def start(self) -> int:
        outer = self

        class _H(BaseHTTPRequestHandler):
            # socketserver.StreamRequestHandler.setup() applies this to
            # the connection: EVERY read (request line, headers, body)
            # has a deadline — one stalled client cannot pin the thread
            timeout = outer.request_timeout

            def log_message(self, *a):
                pass

            def log_error(self, fmt, *a):
                # the base handler's request-line/header timeout path
                # ("Request timed out: ...") reports only through
                # log_error — hook it so those drops are counted too
                if "timed out" in fmt:
                    outer._m_timeouts.inc()

            def _dispatch(self, handler: Optional[Handler]):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n) if n else b""
                except (socket.timeout, TimeoutError):
                    # slowloris body: drop the connection without a
                    # response — the peer is, by definition, not reading
                    outer._m_timeouts.inc()
                    self.close_connection = True
                    return
                # trace join: a valid traceparent header makes this
                # request's spans part of the caller's trace; absent or
                # malformed -> attach(None), a clean fresh root. The
                # whole block is one flag check when tracing is off.
                traced = _tracing.is_enabled()
                if traced:
                    tok = _tracing.attach(_tracing.parse_traceparent(
                        self.headers.get("traceparent")))
                    span = _tracing.span("http/server",
                                         method=self.command,
                                         path=self.path)
                else:
                    span = _tracing.NULL_SPAN
                # tenant identity rides NEXT TO the traceparent: attach
                # the X-Tenant header (if any) for the handler's
                # duration, so books/spend/exemplars on this thread
                # carry the caller's identity. Always-on — attach(None)
                # is one thread-local store; handlers that extract a
                # JSON-field tenant themselves still win (explicit args
                # override the ambient value downstream).
                ten_tok = _tenancy.attach(
                    _tenancy.from_headers(self.headers))
                try:
                    with span:
                        try:
                            # chaos hook: an `error` fault here is a
                            # handler crash (500, connection survives); a
                            # `latency`/`hang` is a stalled handler thread
                            _faults.fault_point("http_handler",
                                                path=self.path)
                            out = handler(self.path, body,
                                          dict(self.headers)) \
                                if handler else None
                            if out is None:
                                out = json_response(
                                    {"error": "not found"}, 404)
                        except _faults.FaultInjected as e:
                            out = json_response(
                                {"error": f"{type(e).__name__}: {e}"}, 500)
                        except Exception as e:  # keep serving
                            out = json_response(
                                {"error": f"{type(e).__name__}: {e}"}, 400)
                        code, ctype, payload = out[:3]
                        extra = out[3] if len(out) > 3 else None
                        if isinstance(payload, (bytes, bytearray)):
                            self.send_response(code)
                            self.send_header("Content-Type", ctype)
                            self.send_header("Content-Length",
                                             str(len(payload)))
                            if extra:
                                for k, v in extra.items():
                                    self.send_header(k, str(v))
                            self.end_headers()
                            self.wfile.write(payload)
                        else:
                            # streaming payload. A client that spoke
                            # HTTP/1.1 gets chunked framing (per-request
                            # protocol upgrade; the Content-Length path
                            # above stays 1.0); an HTTP/1.0 client
                            # cannot de-frame chunks, so it gets the raw
                            # flushed body with read-to-close framing.
                            chunked = self.request_version != "HTTP/1.0"
                            if chunked:
                                self.protocol_version = "HTTP/1.1"
                            self.send_response(code)
                            self.send_header("Content-Type", ctype)
                            if chunked:
                                self.send_header("Transfer-Encoding",
                                                 "chunked")
                            if extra:
                                for k, v in extra.items():
                                    self.send_header(k, str(v))
                            self.end_headers()
                            for chunk in payload:
                                if not chunk:
                                    continue
                                chunk = bytes(chunk)
                                if chunked:
                                    chunk = (b"%x\r\n" % len(chunk)
                                             + chunk + b"\r\n")
                                self.wfile.write(chunk)
                                self.wfile.flush()
                            if chunked:
                                self.wfile.write(b"0\r\n\r\n")
                            # one response per connection for streamed
                            # bodies: the peer reads to the terminal
                            # chunk (or to close); keep-alive buys
                            # nothing here
                            self.close_connection = True
                finally:
                    _tenancy.detach(ten_tok)
                    if traced:
                        _tracing.detach(tok)

            def do_GET(self):
                self._dispatch(outer._get)

            def do_POST(self):
                self._dispatch(outer._post)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), _H)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=f"dl4j-http-{self.port}")
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def join(self):
        if self._thread is not None:
            self._thread.join()
