"""Weight initialization schemes.

Mirrors the reference's WeightInit enum + WeightInitUtil
(deeplearning4j-nn/.../nn/weights/WeightInit.java, WeightInitUtil.java) and
the distribution configs (nn/conf/distribution/). Same math, but drawn with
JAX's counter-based threefry PRNG so initialization is reproducible per-seed
and per-parameter regardless of device count or evaluation order — a
property the reference's sequential java.util.Random stream cannot give.

fan_in / fan_out follow the reference's convention: for a dense kernel
[nIn, nOut] fan_in=nIn, fan_out=nOut; for conv kernels
[kh, kw, cin, cout] fan_in = cin*kh*kw, fan_out = cout*kh*kw
(WeightInitUtil.initWeights receives fanIn/fanOut computed that way by each
ParamInitializer).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


class WeightInit:
    ZERO = "zero"
    ONES = "ones"
    UNIFORM = "uniform"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    XAVIER_LEGACY = "xavier_legacy"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    NORMAL = "normal"
    DISTRIBUTION = "distribution"
    IDENTITY = "identity"


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    fan_in: float,
    fan_out: float,
    scheme: str = WeightInit.XAVIER,
    distribution: Optional[dict] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Draw one weight tensor. `distribution` is the serialized distribution
    config used by scheme == DISTRIBUTION, e.g. {"type": "normal",
    "mean": 0, "std": 0.01} (reference: nn/conf/distribution/*)."""
    shape = tuple(int(s) for s in shape)
    s = scheme.lower()
    if s == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if s == WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if s == WeightInit.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError(f"IDENTITY init needs a square 2d shape, got {shape}")
        return jnp.eye(shape[0], dtype=dtype)
    if s == WeightInit.UNIFORM:
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if s == WeightInit.XAVIER:
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if s == WeightInit.XAVIER_UNIFORM:
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if s == WeightInit.XAVIER_FAN_IN:
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if s == WeightInit.XAVIER_LEGACY:
        std = math.sqrt(1.0 / (shape[0] * shape[-1]))
        return std * jax.random.normal(key, shape, dtype)
    if s == WeightInit.RELU:
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if s == WeightInit.RELU_UNIFORM:
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if s == WeightInit.SIGMOID_UNIFORM:
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if s == WeightInit.LECUN_NORMAL:
        std = math.sqrt(1.0 / fan_in)
        return std * jax.random.normal(key, shape, dtype)
    if s == WeightInit.LECUN_UNIFORM:
        a = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)
    if s == WeightInit.NORMAL:
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if s == WeightInit.DISTRIBUTION:
        return _from_distribution(key, shape, distribution or {}, dtype)
    raise ValueError(f"unknown weight init scheme {scheme!r}")


def _from_distribution(key, shape, dist: dict, dtype):
    kind = dist.get("type", "normal").lower()
    if kind in ("normal", "gaussian"):
        mean = float(dist.get("mean", 0.0))
        std = float(dist.get("std", 1.0))
        return mean + std * jax.random.normal(key, shape, dtype)
    if kind == "uniform":
        lo = float(dist.get("lower", -1.0))
        hi = float(dist.get("upper", 1.0))
        return jax.random.uniform(key, shape, dtype, minval=lo, maxval=hi)
    if kind == "binomial":
        n = int(dist.get("trials", 1))
        p = float(dist.get("probability", 0.5))
        draws = jax.random.bernoulli(key, p, (n,) + tuple(shape))
        return jnp.sum(draws.astype(dtype), axis=0)
    if kind == "truncated_normal":
        mean = float(dist.get("mean", 0.0))
        std = float(dist.get("std", 1.0))
        return mean + std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
    raise ValueError(f"unknown distribution {kind!r}")
