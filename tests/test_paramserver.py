"""Async embedding parameter server (parallel/paramserver.py) — the
Aeron-PS analog: row-sharded tables, synchronous pulls, fire-and-forget
pushes, two concurrent workers training one skip-gram model."""

import threading

import numpy as np

from deeplearning4j_tpu.parallel.paramserver import (
    EmbeddingParameterServer,
    EmbeddingPSClient,
)


def test_pull_push_round_trip_sharded():
    rng = np.random.default_rng(0)
    t0 = rng.standard_normal((10, 4)).astype(np.float32)
    s1 = EmbeddingParameterServer({"syn0": t0.copy()})
    s2 = EmbeddingParameterServer({"syn0": t0.copy()})
    p1, p2 = s1.start(), s2.start()
    try:
        client = EmbeddingPSClient(
            [f"http://127.0.0.1:{p1}", f"http://127.0.0.1:{p2}"])
        rows = np.array([3, 0, 7, 2])
        got = client.pull("syn0", rows)
        np.testing.assert_allclose(got, t0[rows], rtol=1e-6)

        deltas = np.ones((4, 4), np.float32)
        client.push_async("syn0", rows, deltas)
        client.flush()
        got2 = client.pull("syn0", rows)
        np.testing.assert_allclose(got2, t0[rows] + 1.0, rtol=1e-6)
        # each row landed only on its modulo-owner
        assert s1.pushes_applied >= 1 and s2.pushes_applied >= 1
    finally:
        s1.stop()
        s2.stop()


def test_two_workers_async_sgd_converges():
    """Two workers doing Hogwild-style pulls/pushes against one server
    drive a toy embedding objective down (the reference's async-SGD
    semantics incl. acknowledged nondeterminism, DeepWalk.java:223)."""
    rng = np.random.default_rng(1)
    vocab, dim = 30, 8
    server = EmbeddingParameterServer({
        "syn0": (rng.standard_normal((vocab, dim)) * 0.1).astype(np.float32)})
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    # target: push word vectors of even ids toward +e0, odd toward -e0
    target = np.zeros((vocab, dim), np.float32)
    target[::2, 0] = 1.0
    target[1::2, 0] = -1.0

    def worker(seed):
        client = EmbeddingPSClient([url])
        w_rng = np.random.default_rng(seed)
        for _ in range(60):
            rows = w_rng.choice(vocab, size=8, replace=False)
            vecs = client.pull("syn0", rows)
            grad = vecs - target[rows]
            client.push_async("syn0", rows, -0.3 * grad)
        client.flush()

    threads = [threading.Thread(target=worker, args=(s,)) for s in (7, 8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = server.tables["syn0"]
    err = float(np.mean((final - target) ** 2))
    assert err < 0.02, err
    assert server.pushes_applied > 100


def test_empty_pull_returns_well_formed_array():
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((6, 5), np.float32)})
    port = server.start()
    try:
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"])
        out = client.pull("syn0", np.array([], np.int64))
        assert out.shape == (0, 5) and out.dtype == np.float32
    finally:
        server.stop()


def test_dead_endpoint_drops_push_and_counts_it():
    """A dead shard must not kill the drain thread (which would wedge
    push_async once the queue fills) — the push is dropped, counted, and
    later pushes to live endpoints still apply."""
    server = EmbeddingParameterServer({"syn0": np.zeros((4, 3), np.float32)})
    port = server.start()
    try:
        # two "shards": the second URL is a closed port. replay_capacity=0
        # disables the failover replay buffer — this test pins the
        # degrade-by-dropping path (test_paramserver_failover.py covers
        # park-and-replay)
        client = EmbeddingPSClient(
            [f"http://127.0.0.1:{port}", "http://127.0.0.1:1"],
            timeout=2.0, max_retries=1, retry_backoff=0.01,
            replay_capacity=0)
        rows = np.array([1, 3])  # odd rows -> owner 1 (the dead one)
        client.push_async("syn0", rows, np.ones((2, 3), np.float32))
        client.flush()
        assert client.dropped_pushes == 1
        # drain thread is still alive: a push owned by the live shard lands
        client.push_async("syn0", np.array([0, 2]),
                          np.ones((2, 3), np.float32))
        client.flush()
        assert server.tables["syn0"][0, 0] == 1.0
        assert server.tables["syn0"][2, 0] == 1.0
    finally:
        server.stop()


def test_binary_payload_throughput():
    """The hot path is raw bytes, not JSON — measure pushes/sec for a
    realistic [1024, 128] f32 row batch and assert a sane floor (the old
    JSON path measured ~10x slower at this size)."""
    import time

    dim, n_rows, n_pushes = 128, 1024, 50
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((65536, dim), np.float32)})
    port = server.start()
    try:
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"],
                                   queue_size=8)
        rng = np.random.default_rng(0)
        rows = rng.choice(65536, size=n_rows, replace=False)
        deltas = rng.standard_normal((n_rows, dim)).astype(np.float32)
        client.push_async("syn0", rows, deltas)  # warm the connection
        client.flush()
        t0 = time.perf_counter()
        for _ in range(n_pushes):
            client.push_async("syn0", rows, deltas)
        client.flush()
        dt = time.perf_counter() - t0
        rate = n_pushes / dt
        mb_s = n_pushes * deltas.nbytes / dt / 1e6
        print(f"PS binary push rate: {rate:.0f}/s ({mb_s:.0f} MB/s)")
        assert client.dropped_pushes == 0
        assert rate > 20, rate  # raw-bytes floor; JSON path was ~an order under
    finally:
        server.stop()
