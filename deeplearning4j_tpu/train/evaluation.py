"""Evaluation suite.

Analog of the reference's eval/ package: Evaluation (accuracy, precision,
recall, F1, confusion matrix — eval/Evaluation.java, 1,514 LoC),
RegressionEvaluation, ROC/ROCBinary/ROCMultiClass, and the IEvaluation SPI
(incremental accumulation over batches, mergeable across workers — the
property Spark map-side evaluation relies on, impl/multilayer/evaluation/).

Device work (argmax, confusion counts) happens in jnp; accumulation state is
small host-side numpy, so evaluation streams over any iterator without
holding activations.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class Prediction:
    """Per-example record metadata (reference: eval/meta/Prediction.java —
    actual/predicted class plus the caller's record metadata, for
    inspecting which examples were misclassified)."""

    actual: int
    predicted: int
    record_meta: Any = None


class IEvaluation:
    """SPI: incremental, mergeable evaluation (reference: eval/IEvaluation)."""

    def eval_batch(self, labels, predictions, mask=None):
        raise NotImplementedError

    def merge(self, other: "IEvaluation") -> "IEvaluation":
        raise NotImplementedError


class Evaluation(IEvaluation):
    """Multi-class classification evaluation over one-hot (or probability)
    labels/predictions."""

    def __init__(self, num_classes: Optional[int] = None, labels_list=None,
                 top_n: int = 1):
        self.num_classes = num_classes
        self.labels_list = labels_list
        self.confusion: Optional[np.ndarray] = None  # [true, predicted]
        # top-N accuracy (reference: Evaluation(int topN), topNAccuracy())
        self.top_n = max(1, int(top_n))
        self.top_n_correct = 0
        self.top_n_total = 0
        # per-example record metadata (reference: Evaluation record-meta
        # overloads + eval/meta/Prediction.java)
        self.predictions: List[Prediction] = []

    def _ensure(self, n):
        if self.confusion is None:
            self.num_classes = n
            self.confusion = np.zeros((n, n), dtype=np.int64)

    def eval_batch(self, labels, predictions, mask=None, record_meta=None):
        """labels/predictions: [batch, nClasses] (or [batch, time, nClasses]
        with optional [batch, time] mask — time-distributed evaluation as in
        the reference's evalTimeSeries). record_meta: optional per-example
        metadata sequence (one entry per EXAMPLE; for time-series labels
        each entry covers all of that example's timesteps); kept with each
        prediction for error inspection (reference: evaluate(iter,
        metaData))."""
        labels = jnp.asarray(labels)
        predictions = jnp.asarray(predictions)
        time_steps = labels.shape[1] if labels.ndim == 3 else 1
        if labels.ndim == 3:
            n = labels.shape[-1]
            labels = labels.reshape(-1, n)
            predictions = predictions.reshape(-1, n)
            if mask is not None:
                flat = np.asarray(mask).reshape(-1) > 0
            else:
                flat = None
        else:
            flat = np.asarray(mask).reshape(-1) > 0 if mask is not None else None
        t = np.asarray(jnp.argmax(labels, axis=-1))
        p = np.asarray(jnp.argmax(predictions, axis=-1))
        probs = np.asarray(predictions)
        if flat is not None:
            t, p, probs = t[flat], p[flat], probs[flat]
        self._ensure(int(labels.shape[-1]))
        np.add.at(self.confusion, (t, p), 1)
        if self.top_n > 1:
            k = min(self.top_n, probs.shape[-1])
            topk = np.argpartition(-probs, k - 1, axis=-1)[:, :k]
            self.top_n_correct += int((topk == t[:, None]).any(axis=1).sum())
            self.top_n_total += t.size
        if record_meta is not None:
            metas = [m for m in record_meta for _ in range(time_steps)]
            if len(metas) != (flat.size if flat is not None else t.size):
                raise ValueError(
                    f"record_meta has {len(metas) // time_steps} entries "
                    f"for a batch of "
                    f"{(flat.size if flat is not None else t.size) // time_steps}")
            if flat is not None:
                metas = [m for m, keep in zip(metas, flat) if keep]
            for ti, pi, m in zip(t, p, metas):
                self.predictions.append(Prediction(int(ti), int(pi), m))

    def top_n_accuracy(self) -> float:
        if self.top_n == 1:
            return self.accuracy()
        return (self.top_n_correct / self.top_n_total
                if self.top_n_total else 0.0)

    def get_prediction_errors(self) -> List[Prediction]:
        """Misclassified examples with their metadata (reference:
        Evaluation.getPredictionErrors)."""
        return [p for p in self.predictions if p.actual != p.predicted]

    def get_predictions(self, actual_cls: int,
                        predicted_cls: int) -> List[Prediction]:
        return [p for p in self.predictions
                if p.actual == actual_cls and p.predicted == predicted_cls]

    # alias matching the reference API
    eval = eval_batch

    def merge(self, other: "Evaluation") -> "Evaluation":
        if other.confusion is not None:
            self._ensure(other.confusion.shape[0])
            self.confusion += other.confusion
        self.top_n_correct += other.top_n_correct
        self.top_n_total += other.top_n_total
        self.predictions += other.predictions
        return self

    # -- metrics -------------------------------------------------------------
    def _tp(self):
        return np.diag(self.confusion).astype(np.float64)

    def accuracy(self) -> float:
        if self.confusion is None:
            return 0.0
        total = self.confusion.sum()
        return float(self._tp().sum() / total) if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        if self.confusion is None:
            return 0.0
        col = self.confusion.sum(axis=0).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(col > 0, self._tp() / col, np.nan)
        if cls is not None:
            return float(np.nan_to_num(per[cls]))
        return float(np.nanmean(per)) if not np.all(np.isnan(per)) else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if self.confusion is None:
            return 0.0
        row = self.confusion.sum(axis=1).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(row > 0, self._tp() / row, np.nan)
        if cls is not None:
            return float(np.nan_to_num(per[cls]))
        return float(np.nanmean(per)) if not np.all(np.isnan(per)) else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        """Per-class, binary (2-class: class 1's F1, Evaluation.java:949),
        or macro = mean of per-class F1 over classes where precision AND
        recall are defined (Evaluation.java:954-965 fBeta Macro — NOT the
        harmonic mean of macro-precision/macro-recall)."""
        if self.confusion is None:
            return 0.0
        if cls is not None:
            p = self.precision(cls)
            r = self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) > 0 else 0.0
        n = self.confusion.shape[0]
        if n == 2:
            tp = float(self.confusion[1, 1])
            fp = float(self.confusion[0, 1])
            fn = float(self.confusion[1, 0])
            denom = 2 * tp + fp + fn
            return 2 * tp / denom if denom > 0 else 0.0
        col = self.confusion.sum(axis=0).astype(np.float64)
        row = self.confusion.sum(axis=1).astype(np.float64)
        tp = self._tp()
        vals = []
        for i in range(n):
            if col[i] == 0 or row[i] == 0:  # p or r undefined: excluded
                continue
            p, r = tp[i] / col[i], tp[i] / row[i]
            vals.append(2 * p * r / (p + r) if (p + r) > 0 else 0.0)
        return float(np.mean(vals)) if vals else 0.0

    def stats(self) -> str:
        n = self.confusion.shape[0] if self.confusion is not None else 0
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes: {n}",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "",
            "Confusion matrix (rows=actual, cols=predicted):",
            str(self.confusion),
            "==================================================================",
        ]
        return "\n".join(lines)


class RegressionEvaluation(IEvaluation):
    """Per-column regression metrics (reference: eval/RegressionEvaluation
    — MSE, MAE, RMSE, RSE, correlation)."""

    def __init__(self, n_columns: Optional[int] = None):
        self.n = 0
        self.sum_err2 = None
        self.sum_abs = None
        self.sum_label = None
        self.sum_label2 = None
        self.sum_pred = None
        self.sum_pred2 = None
        self.sum_lp = None

    def eval_batch(self, labels, predictions, mask=None):
        l = np.asarray(labels, dtype=np.float64)
        p = np.asarray(predictions, dtype=np.float64)
        if l.ndim == 3:
            l = l.reshape(-1, l.shape[-1])
            p = p.reshape(-1, p.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1) > 0
            l, p = l[m], p[m]
        if self.sum_err2 is None:
            c = l.shape[-1]
            for name in ("sum_err2", "sum_abs", "sum_label", "sum_label2",
                         "sum_pred", "sum_pred2", "sum_lp"):
                setattr(self, name, np.zeros(c))
        d = p - l
        self.n += l.shape[0]
        self.sum_err2 += (d * d).sum(0)
        self.sum_abs += np.abs(d).sum(0)
        self.sum_label += l.sum(0)
        self.sum_label2 += (l * l).sum(0)
        self.sum_pred += p.sum(0)
        self.sum_pred2 += (p * p).sum(0)
        self.sum_lp += (l * p).sum(0)

    eval = eval_batch

    def merge(self, other: "RegressionEvaluation"):
        if other.sum_err2 is None:
            return self
        if self.sum_err2 is None:
            for name in ("sum_err2", "sum_abs", "sum_label", "sum_label2",
                         "sum_pred", "sum_pred2", "sum_lp"):
                setattr(self, name, np.array(getattr(other, name)))
            self.n = other.n
            return self
        self.n += other.n
        for name in ("sum_err2", "sum_abs", "sum_label", "sum_label2",
                     "sum_pred", "sum_pred2", "sum_lp"):
            setattr(self, name, getattr(self, name) + getattr(other, name))
        return self

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_err2[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.sum_err2[col] / self.n))

    def correlation_r2(self, col: int = 0) -> float:
        n = self.n
        num = n * self.sum_lp[col] - self.sum_label[col] * self.sum_pred[col]
        den = np.sqrt(n * self.sum_label2[col] - self.sum_label[col] ** 2) * np.sqrt(
            n * self.sum_pred2[col] - self.sum_pred[col] ** 2
        )
        return float((num / den) ** 2) if den > 0 else 0.0

    def stats(self) -> str:
        cols = len(self.sum_err2) if self.sum_err2 is not None else 0
        lines = ["Regression evaluation:"]
        for c in range(cols):
            lines.append(
                f" col {c}: MSE={self.mean_squared_error(c):.6f} "
                f"MAE={self.mean_absolute_error(c):.6f} "
                f"RMSE={self.root_mean_squared_error(c):.6f} "
                f"R^2={self.correlation_r2(c):.4f}"
            )
        return "\n".join(lines)


class ROC(IEvaluation):
    """Binary ROC with exact threshold sweep over accumulated scores
    (reference: eval/ROC.java uses a fixed threshold-step approximation; we
    keep all scores — memory is fine at framework-test scale — and compute
    the exact AUC)."""

    def __init__(self, threshold_steps: int = 0):
        self.scores = []
        self.labels = []

    def eval_batch(self, labels, predictions, mask=None):
        l = np.asarray(labels, dtype=np.float64)
        p = np.asarray(predictions, dtype=np.float64)
        if l.ndim == 2 and l.shape[-1] == 2:
            # [P(class0), P(class1)] convention, positive = column 1
            l = l[:, 1]
            p = p[:, 1]
        self.labels.append(l.reshape(-1))
        self.scores.append(p.reshape(-1))

    eval = eval_batch

    def merge(self, other: "ROC"):
        self.labels += other.labels
        self.scores += other.scores
        return self

    def calculate_auc(self) -> float:
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="stable")
        y = y[order]
        pos = y.sum()
        neg = len(y) - pos
        if pos == 0 or neg == 0:
            return 0.0
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        tpr = np.concatenate([[0.0], tps / pos])
        fpr = np.concatenate([[0.0], fps / neg])
        return float(np.trapezoid(tpr, fpr))


class ROCMultiClass(IEvaluation):
    """One-vs-all ROC per class (reference: eval/ROCMultiClass.java)."""

    def __init__(self):
        self.per_class = {}

    def eval_batch(self, labels, predictions, mask=None):
        l = np.asarray(labels)
        p = np.asarray(predictions)
        for c in range(l.shape[-1]):
            roc = self.per_class.setdefault(c, ROC())
            roc.eval_batch(l[..., c], p[..., c])

    eval = eval_batch

    def merge(self, other: "ROCMultiClass"):
        for c, roc in other.per_class.items():
            if c in self.per_class:
                self.per_class[c].merge(roc)
            else:
                self.per_class[c] = roc
        return self

    def calculate_auc(self, cls: int) -> float:
        return self.per_class[cls].calculate_auc()


class ROCBinary(IEvaluation):
    """Independent per-output-column binary ROC (reference:
    eval/ROCBinary.java — multi-label outputs, one ROC per column, with
    optional per-example mask)."""

    def __init__(self):
        self.per_column = {}

    def eval_batch(self, labels, predictions, mask=None):
        l = np.asarray(labels, dtype=np.float64)
        p = np.asarray(predictions, dtype=np.float64)
        if l.ndim == 3:
            l = l.reshape(-1, l.shape[-1])
            p = p.reshape(-1, p.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1) > 0
            l, p = l[m], p[m]
        for c in range(l.shape[-1]):
            roc = self.per_column.setdefault(c, ROC())
            roc.eval_batch(l[:, c], p[:, c])

    eval = eval_batch

    def merge(self, other: "ROCBinary"):
        for c, roc in other.per_column.items():
            if c in self.per_column:
                self.per_column[c].merge(roc)
            else:
                self.per_column[c] = roc
        return self

    def calculate_auc(self, col: int = 0) -> float:
        return self.per_column[col].calculate_auc()


class EvaluationBinary(IEvaluation):
    """Per-output-column binary evaluation at threshold 0.5
    (reference: eval/EvaluationBinary.java)."""

    def __init__(self):
        self.tp = self.fp = self.tn = self.fn = None

    def eval_batch(self, labels, predictions, mask=None):
        l = np.asarray(labels) > 0.5
        p = np.asarray(predictions) > 0.5
        if l.ndim == 3:
            l = l.reshape(-1, l.shape[-1])
            p = p.reshape(-1, p.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1) > 0
            l, p = l[m], p[m]
        if self.tp is None:
            c = l.shape[-1]
            self.tp = np.zeros(c); self.fp = np.zeros(c)
            self.tn = np.zeros(c); self.fn = np.zeros(c)
        self.tp += (l & p).sum(0)
        self.fp += (~l & p).sum(0)
        self.tn += (~l & ~p).sum(0)
        self.fn += (l & ~p).sum(0)

    eval = eval_batch

    def merge(self, other: "EvaluationBinary"):
        if other.tp is None:
            return self
        if self.tp is None:
            self.tp, self.fp = np.array(other.tp), np.array(other.fp)
            self.tn, self.fn = np.array(other.tn), np.array(other.fn)
            return self
        self.tp += other.tp; self.fp += other.fp
        self.tn += other.tn; self.fn += other.fn
        return self

    def accuracy(self, col: int = 0) -> float:
        tot = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float((self.tp[col] + self.tn[col]) / tot) if tot else 0.0

    def f1(self, col: int = 0) -> float:
        denom = 2 * self.tp[col] + self.fp[col] + self.fn[col]
        return float(2 * self.tp[col] / denom) if denom else 0.0
