"""Shared network machinery for MultiLayerNetwork and ComputationGraph.

The reference factors this via the Model interface + BaseLayer inheritance
(nn/api/Model.java); here it is a small base class holding the pieces that
are identical for sequential and DAG networks: listener management, the
epoch/iteration fit loop (with async prefetch and ETL timing), the
batch-transform hook used by parallel.ParallelWrapper, and the flattened
parameter view API (params()/setParams(), reference:
MultiLayerNetwork.java:102-104 flattenedParams).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.nn.params import (
    flat_to_params,
    num_params,
    param_table,
    params_to_flat,
)


class NetworkBase:
    """Common trainable-network state + loops. Subclasses implement
    `_fit_dataset(ds)` (one optimizer step or TBPTT segment loop) and
    `_ordered_layer_confs()` (layer configs aligned with params_list)."""

    def __init__(self):
        self.listeners = []
        self.iteration = 0
        self.epoch = 0
        self.params_list = None
        self.state_list = None
        self.upd_state = None
        self._score = None  # last minibatch score (device array, lazy read)
        self._last_etl_ms = 0.0
        # opt-in per-iteration grad/update/param mean-magnitude collection
        # for the stats/UI pipeline (reference: BaseStatsListener payloads)
        self._collect_stats = False
        self._last_stats = None
        # hook applied to each DataSet before the step — installed by
        # parallel.ParallelWrapper to shard the batch across the mesh
        self._batch_transform = None
        # fuse K consecutive same-shape minibatches into ONE jitted
        # dispatch (set_fused_steps) — the dispatch-latency amortizer
        self._fused_k = 1
        # forward (`output`) traces compiled so far — bumped by the
        # subclasses' shape-keyed output caches; serving layers surface it
        # so a compile storm is a metric, not a latency mystery. The lock
        # makes concurrent cache misses on one key produce ONE entry
        # (ParallelInference calls output() from several threads)
        self._output_compiles = 0
        self._output_cache_lock = threading.Lock()

    # -- to be provided by subclasses ----------------------------------------

    def init(self):
        raise NotImplementedError

    def _fit_dataset(self, ds):
        raise NotImplementedError

    def _ordered_layer_confs(self) -> List:
        """Layer configs in flattening order, aligned with params_list."""
        raise NotImplementedError

    def _require_init(self):
        if self.params_list is None:
            self.init()

    @property
    def output_compile_count(self) -> int:
        """Forward traces compiled by `output()` so far — one per distinct
        (training, input shape/dtype) key. Steady state for a serving
        workload is a constant (one per batch bucket); growth under
        traffic means shape churn is forcing recompiles."""
        return self._output_compiles

    def _cached_output_fn(self, key, make_fn):
        """Shape-keyed get-or-insert into the `output()` jit cache, bumping
        `output_compile_count` on insert. Under the lock so concurrent
        cache misses on one key (ParallelInference calls output() from
        several threads) produce ONE entry; the actual trace happens at
        call time outside the lock and jax serializes it internally."""
        with self._output_cache_lock:
            if not isinstance(self._output_fn, dict):
                self._output_fn = {}
            fn = self._output_fn.get(key)
            if fn is None:
                fn = self._output_fn[key] = make_fn()
                self._output_compiles += 1
            return fn

    # -- listeners -----------------------------------------------------------

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    def set_collect_stats(self, flag: bool = True):
        """Toggle fused per-iteration grad/update/param mean-magnitude
        collection (used by ui.StatsListener). Rebuilds the train step."""
        flag = bool(flag)
        if flag != self._collect_stats:
            self._collect_stats = flag
            self._train_step_fn = None
            if hasattr(self, "_trunc_step_fn"):
                self._trunc_step_fn = None
        return self

    def set_fused_steps(self, k: int):
        """Run up to `k` consecutive same-shape minibatches as ONE jitted
        dispatch (a `lax.scan` over the stacked batches — same math, same
        per-step lr/rng/iteration bookkeeping, k-1 fewer host->device
        round-trips). The host-side analog of the reference's
        AsyncDataSetIterator throughput role (MultiLayerNetwork.java:
        1023-1025) taken to its XLA conclusion: when dispatch latency is
        the bottleneck (small models, remote links), amortize it.

        Fusion engages only when it is observationally equivalent to the
        per-step loop: no listeners (per-iteration callbacks must see
        their iteration's params), no stats collection, no batch
        transform, and the subclass supports it (`_fused_fit_supported`);
        partial/ragged chunks fall back to per-step fits."""
        self._fused_k = max(1, int(k))
        return self

    def _fused_fit_supported(self) -> bool:
        """Whether this network can run `_fit_datasets_fused`."""
        return False

    def _fit_datasets_fused(self, ds_list):
        raise NotImplementedError

    @staticmethod
    def _step_rng_and_t(key, t0, i):
        """Per-step (rng, t) inside a fused scan: t0 is the iteration
        counter as EXACT uint32 (float32 would collapse consecutive
        steps' dropout rng past 2^24 iterations), i the scan index. The
        ONE derivation every fused program shares with `_run_step`'s
        per-step fold_in(key, iteration)."""
        import jax
        import jax.numpy as jnp

        ti = t0 + jnp.asarray(i, t0.dtype)
        return jax.random.fold_in(key, ti), ti.astype(jnp.float32)

    def _ds_signature(self, ds):
        """Shape/mask signature — only identically-shaped consecutive
        batches are stacked into one fused dispatch."""
        sh = lambda a: None if a is None else tuple(a.shape)
        if hasattr(ds, "features_masks"):  # MultiDataSet
            return (
                tuple(sh(f) for f in ds.features),
                tuple(sh(y) for y in ds.labels),
                None if ds.features_masks is None
                else tuple(sh(m) for m in ds.features_masks),
                None if ds.labels_masks is None
                else tuple(sh(m) for m in ds.labels_masks),
            )
        return (sh(ds.features), sh(ds.labels), sh(ds.features_mask),
                sh(ds.labels_mask))

    def _notify(self, batch_size, ds=None):
        if not self.listeners:
            return
        info = {
            "score": lambda: self._score,
            "batch_size": batch_size,
            "etl_ms": self._last_etl_ms,
            "stats": lambda: self._last_stats,
            # the batch that produced this iteration (activation-visualizing
            # listeners forward it through the net; lambda keeps it lazy)
            "batch": lambda: ds,
        }
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration - 1, info)

    # -- the fit loop --------------------------------------------------------

    def _run_fit(self, iterator, epochs: int, async_prefetch: bool,
                 prefetch_buffer: int = 4):
        if async_prefetch and not isinstance(iterator, AsyncDataSetIterator):
            iterator = AsyncDataSetIterator(iterator, prefetch_buffer)
        fuse_k = self._fused_k if (
            self._fused_k > 1
            and not self.listeners
            and not self._collect_stats
            and self._batch_transform is None
            and self._fused_fit_supported()
        ) else 1
        for _ in range(epochs):
            for lst in self.listeners:
                lst.on_epoch_start(self, self.epoch)
            t_etl = time.perf_counter()
            buf, sig = [], None
            for ds in iterator:
                self._last_etl_ms = (time.perf_counter() - t_etl) * 1e3
                if self._batch_transform is not None:
                    ds = self._batch_transform(ds)
                if fuse_k > 1:
                    s = self._ds_signature(ds)
                    if buf and s != sig:
                        self._flush_fused(buf, fuse_k)
                        buf = []
                    sig = s
                    buf.append(ds)
                    if len(buf) == fuse_k:
                        self._flush_fused(buf, fuse_k)
                        buf = []
                else:
                    self._fit_dataset(ds)
                t_etl = time.perf_counter()
            if buf:
                self._flush_fused(buf, fuse_k)
            for lst in self.listeners:
                lst.on_epoch_end(self, self.epoch)
            self.epoch += 1
            iterator.reset()
        return self

    def _flush_fused(self, buf, fuse_k):
        """Full chunks run fused; ragged tails fall back to per-step fits
        (one jitted program per chunk size would defeat the cache)."""
        if len(buf) == fuse_k:
            self._fit_datasets_fused(buf)
        else:
            for ds in buf:
                self._fit_dataset(ds)

    # -- flattened params API ------------------------------------------------

    def params(self):
        """Flattened parameter vector (reference: Model.params())."""
        self._require_init()
        return params_to_flat(self._ordered_layer_confs(), self.params_list)

    def set_params(self, flat):
        self._require_init()
        self.params_list = flat_to_params(
            self._ordered_layer_confs(), self.params_list, flat
        )

    def num_params(self) -> int:
        self._require_init()
        return num_params(self._ordered_layer_confs(), self.params_list)

    def param_table(self):
        self._require_init()
        return param_table(self._ordered_layer_confs(), self.params_list)

    def summary(self) -> str:
        self._require_init()
        lines = ["=" * 70]
        total = 0
        for i, (conf, p) in enumerate(
            zip(self._ordered_layer_confs(), self.params_list)
        ):
            n = sum(int(np.prod(v.shape)) for v in p.values())
            total += n
            lines.append(f"{i:>3}  {type(conf).__name__:<28} params: {n}")
        lines.append(f"total params: {total}")
        lines.append("=" * 70)
        return "\n".join(lines)
