"""FLOP accounting for MFU reporting — now a thin wrapper over the
jaxpr cost model (analysis/costmodel.py).

`train_step_flops_for(net, batch)` is the one entry point: it traces the
net's actual optimizer step and returns the MXU-family FLOPs the program
really runs (source `"costmodel"`). The hand-written per-layer estimator
below — 2·MACs forward × 3 for the step, the original MFU arithmetic —
is demoted to the fallback for nets the cost model cannot trace (no
InputType on the conf) and to the cheap lazy default the fit loop's
devprof sampling starts from; every surfaced number carries its
`flops_source` so the two accountings can never be silently conflated.
Elementwise/normalization work stays excluded from the MFU numerator in
BOTH accountings (bandwidth-, not FLOPs-bound on TPU; exclusion keeps
MFU comparable across frameworks).

Chip tables (peak matmul FLOP/s, HBM size, HBM bandwidth) live here too
— the denominators of MFU and the roofline ridge.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

logger = logging.getLogger("deeplearning4j_tpu")

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration,
    LayerVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import ConvolutionalInput, RecurrentInput


def _layer_forward_flops(conf, it) -> float:
    """Per-example forward FLOPs of one layer given its input type."""
    inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
    if isinstance(inner, L.ConvolutionLayer):
        out = inner.output_type(it)
        k = inner.kernel_size
        return 2.0 * k[0] * k[1] * inner.n_in * inner.n_out * out.height * out.width
    if isinstance(inner, L.Convolution1DLayer):
        out = inner.output_type(it)
        t = out.timesteps or (it.timesteps or 1)
        return 2.0 * inner.kernel_size * inner.n_in * inner.n_out * t
    if isinstance(inner, (L.LSTM, L.GravesLSTM, L.GravesBidirectionalLSTM)):
        t = it.timesteps or 1
        per_step = 2.0 * 4 * inner.n_out * (inner.n_in + inner.n_out)
        mult = 2 if isinstance(inner, L.GravesBidirectionalLSTM) else 1
        return per_step * t * mult
    if isinstance(inner, L.RnnOutputLayer):
        t = it.timesteps or 1
        return 2.0 * inner.n_in * inner.n_out * t
    if isinstance(inner, (L.DenseLayer, L.OutputLayer, L.CenterLossOutputLayer,
                          L.AutoEncoder)):
        return 2.0 * inner.n_in * inner.n_out
    if isinstance(inner, L.EmbeddingLayer):
        return 0.0  # gather, not matmul
    return 0.0


def graph_forward_flops(conf: ComputationGraphConfiguration) -> Optional[float]:
    """Per-example forward FLOPs of a ComputationGraph, via a shape-
    inference walk of the topo order. None if input_types are unset."""
    if conf.input_types is None:
        return None
    types = dict(zip(conf.inputs, conf.input_types))
    total = 0.0
    for name in conf.topological_order():
        if name in types:
            continue
        v = conf.vertices[name]
        its = [types.get(i) for i in conf.vertex_inputs[name]]
        if any(i is None for i in its):
            types[name] = None
            continue
        if isinstance(v, LayerVertex):
            it = its[0]
            if v.preprocessor is not None:
                it = v.preprocessor.output_type(it)
            total += _layer_forward_flops(v.layer, it)
            types[name] = v.layer.output_type(it)
        else:
            types[name] = v.output_type(its)
    return total


def mln_forward_flops(conf) -> Optional[float]:
    """Per-example forward FLOPs of a MultiLayerConfiguration."""
    if conf.input_type is None:
        return None
    it = conf.input_type
    total = 0.0
    for i, layer in enumerate(conf.layers):
        pp = conf.preprocessors.get(str(i))
        if pp is not None:
            it = pp.output_type(it)
        total += _layer_forward_flops(layer, it)
        it = layer.output_type(it)
    return total


def train_step_flops(forward_flops: float, batch: int) -> float:
    """Analytic model FLOPs of one optimizer step: 3× forward (fwd +
    grad wrt activations + grad wrt weights), times the batch."""
    return 3.0 * forward_flops * batch


def forward_flops(conf) -> Optional[float]:
    """Per-example analytic forward FLOPs of either conf flavor."""
    from deeplearning4j_tpu.nn.conf.graph import (
        ComputationGraphConfiguration,
    )

    if isinstance(conf, ComputationGraphConfiguration):
        return graph_forward_flops(conf)
    return mln_forward_flops(conf)


def _unbounded_recurrent(conf) -> bool:
    """Does this conf consume recurrent input with NO fixed timestep
    count? The per-layer walk then prices one timestep, and a
    "per-example" number derived from it would be ~seq_len× off."""
    its = getattr(conf, "input_types", None) \
        or (getattr(conf, "input_type", None),)
    return any(isinstance(it, RecurrentInput) and not it.timesteps
               for it in its if it is not None)


def analytic_step_flops_per_example(conf) -> Tuple[Optional[float], str]:
    """(per-example optimizer-step FLOPs, "analytic") — the lazy default
    devprof's live MFU gauges start from. Recurrent confs without a
    fixed timestep count return (None, "analytic"): the walk prices ONE
    timestep, and reporting that as per-example would publish an MFU
    ~seq_len× too small — no number beats a confidently wrong one
    (attach a cost model, or fix the InputType's timesteps)."""
    if _unbounded_recurrent(conf):
        return None, "analytic"
    fwd = forward_flops(conf)
    if fwd is None or fwd <= 0:
        return None, "analytic"
    return 3.0 * fwd, "analytic"


def train_step_flops_for(net, batch: int, *, timesteps: int = 16,
                         prefer_cost_model: bool = True
                         ) -> Tuple[Optional[float], str]:
    """Model FLOPs of one of `net`'s optimizer steps at `batch` —
    `(flops, source)` where source is `"costmodel"` (jaxpr trace of the
    real step, MXU families only) or `"analytic"` (the per-layer
    fallback). The trace runs with vendor helpers disabled: model FLOPs
    are implementation-independent, and opaque pallas custom calls
    would otherwise count zero."""
    if prefer_cost_model:
        try:
            from deeplearning4j_tpu.analysis.costmodel import (
                train_step_cost,
            )

            with _helpers_disabled():
                cm = train_step_cost(net, batch_size=batch,
                                     timesteps=timesteps)
            if cm.model_flops > 0:
                return cm.model_flops, "costmodel"
        except Exception:
            logger.warning(
                "cost-model FLOP trace failed; falling back to the "
                "analytic per-layer estimate", exc_info=True)
    fwd = forward_flops(net.conf)
    if fwd is None or fwd <= 0:
        return None, "analytic"
    if _unbounded_recurrent(net.conf):
        fwd *= timesteps  # the analytic walk priced ONE timestep
    return train_step_flops(fwd, batch), "analytic"


class _helpers_disabled:
    """Disable every registered vendor helper for the duration of a
    cost-model trace, restoring the caller's kill-switch state on exit
    (the same save/restore discipline as bench._run_ab)."""

    _OPS = ("conv2d", "batch_norm", "bn_backward", "lstm_sequence")

    def __enter__(self):
        from deeplearning4j_tpu.ops.helpers import (
            helper_enabled,
            set_helper_enabled,
        )

        self._set = set_helper_enabled
        self._saved = {op: helper_enabled(op) for op in self._OPS}
        for op in self._OPS:
            set_helper_enabled(op, False)
        return self

    def __exit__(self, *exc):
        for op, enabled in self._saved.items():
            if enabled is not None:
                self._set(op, enabled)
        return False


# bf16 peak matmul throughput per chip, for MFU. v5e: 197 TFLOP/s.
TPU_PEAK_FLOPS = {
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

# HBM capacity per chip — the JX008 residency ceiling.
TPU_HBM_BYTES = {
    "v5e": 16e9,
    "v5litepod": 16e9,
    "v4": 32e9,
    "v5p": 95e9,
    "v6e": 32e9,
}

# HBM bandwidth per chip — the roofline ridge denominator.
TPU_HBM_BANDWIDTH = {
    "v5e": 819e9,
    "v5litepod": 819e9,
    "v4": 1228e9,
    "v5p": 2765e9,
    "v6e": 1640e9,
}

# Aggregate inter-chip (ICI) bandwidth per chip — the denominator of the
# `train_step_collective_seconds{source="estimate"}` gradient-allreduce
# cost model (parallel/sharded.MeshPlan). Approximate public figures for
# all links of one chip combined; an estimate's denominator, clearly
# labeled as such wherever it surfaces.
TPU_ICI_BANDWIDTH = {
    "v5e": 200e9,
    "v5litepod": 200e9,
    "v4": 300e9,
    "v5p": 600e9,
    "v6e": 448e9,
}


def _chip_lookup(table: dict, env_var: str, default):
    import os

    env = os.environ.get(env_var)
    if env:
        return float(env)
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower().replace(" ", "")
        for key, val in table.items():
            if key in kind:
                return val
    except Exception:
        pass
    return default


def peak_flops_per_chip(default: float = 197e12) -> float:
    """Best-effort peak bf16 FLOP/s of the current chip."""
    return _chip_lookup(TPU_PEAK_FLOPS, "BENCH_PEAK_FLOPS", default)


def peak_hbm_bytes_per_chip(default: Optional[float] = None
                            ) -> Optional[float]:
    """HBM capacity of the current chip; None off-TPU (a CPU host's RAM
    is not the ceiling the JX008 check is about) unless BENCH_HBM_BYTES
    forces one."""
    return _chip_lookup(TPU_HBM_BYTES, "BENCH_HBM_BYTES", default)


def hbm_bandwidth_per_chip(default: float = 819e9) -> float:
    """HBM bandwidth of the current chip (roofline ridge); the v5e
    figure stands in off-TPU — the roofline is a TPU-shaped model."""
    return _chip_lookup(TPU_HBM_BANDWIDTH, "BENCH_HBM_BANDWIDTH", default)


def ici_bandwidth_per_chip(default: float = 200e9) -> float:
    """Aggregate ICI bandwidth of the current chip — the gradient
    all-reduce estimate's denominator; the v5e figure stands in off-TPU
    (the estimate is a TPU-shaped cost model, labeled `estimate`)."""
    return _chip_lookup(TPU_ICI_BANDWIDTH, "BENCH_ICI_BANDWIDTH", default)
