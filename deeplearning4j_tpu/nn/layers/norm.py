"""Normalization layers: batch normalization and local response normalization.

Reference impls: nn/layers/normalization/BatchNormalization.java (+
CudnnBatchNormalizationHelper) and LocalResponseNormalization.java (+ cuDNN
helper). Both compile to fused XLA element-wise/reduction code here; no
helper SPI required for the base path.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.registry import LayerContext, register_layer
from deeplearning4j_tpu.ops.activations import apply_activation


# -- batch normalization -----------------------------------------------------

def batchnorm_init(key, conf: L.BatchNormalization, dtype):
    n = int(conf.n_in)
    return {
        "gamma": jnp.full((n,), conf.gamma, dtype),
        "beta": jnp.full((n,), conf.beta, dtype),
    }


def batchnorm_state(conf: L.BatchNormalization, dtype):
    n = int(conf.n_in)
    return {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}


def batchnorm_forward(conf: L.BatchNormalization, params, x, ctx: LayerContext):
    """Normalizes over all axes but the last (channels for NHWC, features
    for 2d). Training uses batch statistics and EMA-updates the running
    stats (decay semantics as the reference: global = decay*global +
    (1-decay)*batch); inference uses the running stats."""
    axes = tuple(range(x.ndim - 1))
    eps = conf.eps
    state = ctx.state or {}
    if ctx.training:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        d = conf.decay
        new_state = {
            "mean": d * state.get("mean", jnp.zeros_like(mean)) + (1 - d) * mean,
            "var": d * state.get("var", jnp.ones_like(var)) + (1 - d) * var,
        }
    else:
        mean = state.get("mean")
        var = state.get("var")
        if mean is None:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
        new_state = None
    inv = lax.rsqrt(var.astype(x.dtype) + eps)
    xhat = (x - mean.astype(x.dtype)) * inv
    if conf.lock_gamma_beta:
        y = xhat
    else:
        y = params["gamma"].astype(x.dtype) * xhat + params["beta"].astype(x.dtype)
    return y, new_state


def batchnorm_order(conf):
    return ("gamma", "beta")


register_layer(
    L.BatchNormalization, batchnorm_init, batchnorm_forward,
    order_fn=batchnorm_order, state_fn=batchnorm_state,
)


# -- local response normalization -------------------------------------------

def _no_params(key, conf, dtype):
    return {}


def lrn_forward(conf: L.LocalResponseNormalization, params, x, ctx: LayerContext):
    """Cross-channel LRN on NHWC: y = x / (k + alpha*sum_window(x^2))^beta
    (reference: LocalResponseNormalization.java; window of size n centered
    on each channel). reduce_window over the channel axis."""
    n = int(conf.n)
    half = n // 2
    sq = x * x
    window = (1, 1, 1, n)
    strides = (1, 1, 1, 1)
    padding = [(0, 0), (0, 0), (0, 0), (half, n - 1 - half)]
    ssum = lax.reduce_window(sq, 0.0, lax.add, window, strides, padding)
    denom = (conf.k + conf.alpha * ssum) ** conf.beta
    return x / denom, None


register_layer(L.LocalResponseNormalization, _no_params, lrn_forward)
