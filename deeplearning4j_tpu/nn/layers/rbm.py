"""Restricted Boltzmann machine.

Reference: nn/layers/feedforward/rbm/RBM.java — contrastiveDivergence()
(:102) runs CD-k Gibbs chains: propUp (:224), sampleHiddenGivenVisible
(:223), gibbhVh (:208), propDown (:276), with BINARY/GAUSSIAN/RECTIFIED
unit-type switches (:228,279).

TPU-first shape: the whole CD-k chain — both matmuls per Gibbs step and the
Bernoulli sampling — is one jitted computation; the CD statistics
(positive/negative phase outer products) are returned as a gradient-shaped
pytree so the standard updater applies them like any other gradient.
CD is not the gradient of a tractable objective, so this is computed
explicitly rather than via autodiff (the reference does the same — the
Gibbs chain is hand-rolled there too).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.core import apply_dropout
from deeplearning4j_tpu.nn.layers.registry import LayerContext, register_layer
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import apply_activation


def rbm_init(key, conf: L.RBM, dtype):
    kw, _ = jax.random.split(key)
    W = init_weights(kw, (conf.n_in, conf.n_out), conf.n_in, conf.n_out,
                     conf.weight_init, conf.dist, dtype)
    return {
        "W": W,
        "b": jnp.full((conf.n_out,), conf.bias_init or 0.0, dtype),  # hidden
        "vb": jnp.zeros((conf.n_in,), dtype),  # visible
    }


def rbm_forward(conf: L.RBM, params, x, ctx: LayerContext):
    """Supervised path = propUp: activation(x W + hidden bias) (reference:
    RBM.java activate/propUp :224)."""
    x = apply_dropout(x, conf.dropout, ctx)
    z = x @ params["W"] + params["b"]
    return apply_activation(conf.activation, z, key=ctx.rng, training=ctx.training), None


def rbm_order(conf):
    return ("W", "b", "vb")


register_layer(L.RBM, rbm_init, rbm_forward, order_fn=rbm_order)


def _prop_up(conf, params, v):
    pre = v @ params["W"] + params["b"]
    if conf.hidden_unit == "gaussian":
        return pre
    if conf.hidden_unit == "rectified":
        return jax.nn.relu(pre)
    return jax.nn.sigmoid(pre)


def _prop_down(conf, params, h):
    pre = h @ params["W"].T + params["vb"]
    if conf.visible_unit == "gaussian":
        return pre
    return jax.nn.sigmoid(pre)


def _sample_hidden(conf, h_prob, key):
    if conf.hidden_unit == "binary":
        return jax.random.bernoulli(key, h_prob).astype(h_prob.dtype)
    if conf.hidden_unit == "gaussian":
        return h_prob + jax.random.normal(key, h_prob.shape, h_prob.dtype)
    return h_prob  # rectified: use the mean (reference uses NReLU sampling)


def rbm_cd_stats(conf: L.RBM, params, v0, rng):
    """One CD-k estimate. Returns (grads pytree matching params, per-example
    reconstruction cross-entropy as the monitoring score) — gradient sign
    convention: DESCENT direction for the updater (minimize -logp)."""
    bsz = v0.shape[0]
    h0_prob = _prop_up(conf, params, v0)
    h = _sample_hidden(conf, h0_prob, jax.random.fold_in(rng, 0))
    vk = v0
    hk_prob = h0_prob
    for step in range(int(conf.k)):
        vk = _prop_down(conf, params, h)
        hk_prob = _prop_up(conf, params, vk)
        h = _sample_hidden(conf, hk_prob, jax.random.fold_in(rng, step + 1))
    inv_b = 1.0 / bsz
    grads = {
        "W": -(v0.T @ h0_prob - vk.T @ hk_prob) * inv_b,
        "b": -jnp.mean(h0_prob - hk_prob, axis=0),
        "vb": -jnp.mean(v0 - vk, axis=0),
    }
    if conf.sparsity:
        # sparsity penalty pushes mean hidden activation toward the target
        grads["b"] = grads["b"] + conf.sparsity * jnp.mean(h0_prob, axis=0)
    eps = 1e-7
    vr = jnp.clip(_prop_down(conf, params, h0_prob), eps, 1 - eps)
    recon_xent = -jnp.sum(v0 * jnp.log(vr) + (1 - v0) * jnp.log(1 - vr), axis=-1)
    return grads, recon_xent
