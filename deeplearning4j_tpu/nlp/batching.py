"""Host-side example generation for embedding training.

The reference walks sentences in VectorCalculationsThread workers and
batches (target, context) updates into aggregate ops
(SequenceVectors.java:285-289, SkipGram.java:266-271). Here the host
produces fixed-shape numpy batches (static shapes keep ONE compiled
step) and the device does all the math. Pair extraction is fully
vectorized — a Python-per-pair loop caps throughput at ~10^4 words/sec,
two orders of magnitude below what the device step sustains.

Conventions (word2vec.c / reference parity):
- dynamic window: per center position the effective window is
  `window - b` with b ~ U[0, window)  (word2vec.c: b = next_random % window).
- skip-gram trains input = CONTEXT word, output = center word.
- CBOW trains input = mean of window words, output = center.
- subsampling of frequent words happens while indexing the sentence.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np


class BatchPlan:
    """Static-shape batch configuration + vectorized output-side fill."""

    def __init__(self, *, batch_size: int, context_size: int,
                 hs_arrays=None, negative: int = 0,
                 unigram: Optional[np.ndarray] = None, with_doc: bool = False,
                 device_negatives: bool = False, skip_h_mask: bool = False):
        self.B = int(batch_size)
        self.C = max(1, int(context_size))
        self.negative = int(negative)
        self.with_doc = with_doc
        self.hs = hs_arrays  # (codes [V,L], points [V,L], lengths [V]) or None
        self.unigram = unigram
        # transfer-volume knobs: sample negatives on device from the
        # resident unigram table; omit h_mask when it is identically one
        # (skip-gram — padded rows are no-ops via row_mask alone)
        self.device_negatives = device_negatives
        self.skip_h_mask = skip_h_mask

    def make_batch(self, h_idx, h_mask, targets, doc_idx, rng) -> dict:
        """Assemble one fixed-shape batch from N<=B example rows,
        zero-padding (and masking) the tail. Masks are int8 — they are
        cast to the table dtype on device; bytes on the host link matter
        more than a cast."""
        N = targets.shape[0]
        B, C = self.B, self.C
        b = {
            "h_idx": np.zeros((B, C), np.int32),
            "row_mask": np.zeros((B,), np.int8),
        }
        b["h_idx"][:N] = h_idx
        b["row_mask"][:N] = 1
        if not self.skip_h_mask:
            b["h_mask"] = np.zeros((B, C), np.int8)
            b["h_mask"][:N] = h_mask
        if self.hs is not None:
            codes, points, lengths = self.hs
            L = codes.shape[1]
            b["codes"] = np.zeros((B, L), np.int8)
            b["points"] = np.zeros((B, L), np.int32)
            b["hs_mask"] = np.zeros((B, L), np.int8)
            b["codes"][:N] = codes[targets]
            b["points"][:N] = points[targets]
            b["hs_mask"][:N] = (
                np.arange(L)[None, :] < lengths[targets][:, None]
            )
        if self.negative > 0:
            b["pos"] = np.zeros((B,), np.int32)
            b["pos"][:N] = targets
            if not self.device_negatives:
                b["neg"] = np.zeros((B, self.negative), np.int32)
                t = self.unigram
                b["neg"][:N] = t[rng.integers(0, t.size, (N, self.negative))]
        if self.with_doc:
            b["doc_idx"] = np.zeros((B,), np.int32)
            if doc_idx is not None:
                b["doc_idx"][:N] = doc_idx
        return b


def group_batches(batches, plan: BatchPlan, scan_size: int, lr_fn):
    """Stack consecutive batches into [S, ...] groups for the scanned
    device step (one dispatch per group). The final short group is padded
    with all-zero no-op batches (row_mask=0). lr_fn(rows_into_group) gives
    each inner batch its LR. Yields (stacked_dict, lrs [S], valid_rows)."""
    import jax.numpy as jnp

    buf: List[dict] = []

    def emit(buf):
        lrs = []
        n = 0
        for b in buf:
            lrs.append(lr_fn(n))
            n += int(b["row_mask"].sum())
        if len(buf) < scan_size:
            zero = {k: np.zeros_like(v) for k, v in buf[0].items()}
            pad = scan_size - len(buf)
            buf = buf + [zero] * pad
            lrs = lrs + [lrs[-1]] * pad
        stacked = {
            k: jnp.asarray(np.stack([b[k] for b in buf])) for k in buf[0]
        }
        return stacked, jnp.asarray(np.asarray(lrs, np.float32)), n

    for b in batches:
        buf.append(b)
        if len(buf) == scan_size:
            yield emit(buf)
            buf = []
    if buf:
        yield emit(buf)


def keep_probabilities(counts: np.ndarray, sample: float) -> Optional[np.ndarray]:
    """word2vec subsampling keep-probability per vocab index."""
    if sample <= 0:
        return None
    total = counts.sum()
    f = counts / max(total, 1)
    keep = (np.sqrt(f / sample) + 1.0) * (sample / np.maximum(f, 1e-12))
    return np.minimum(keep, 1.0)


def subsample(indices: np.ndarray, keep_prob: Optional[np.ndarray], rng) -> np.ndarray:
    if keep_prob is None or indices.size == 0:
        return indices
    return indices[rng.random(indices.size) < keep_prob[indices]]


def skipgram_examples(sent: np.ndarray, window: int, rng):
    """Vectorized (input=context, target=center) pair extraction with the
    dynamic window. Returns (inputs [N], targets [N])."""
    n = sent.size
    if n < 2:
        return (np.zeros(0, np.int64),) * 2
    w = window - rng.integers(0, window, n)  # effective window per center
    ins, tgts = [], []
    for d in range(1, window + 1):
        # context ahead of center: center i, context i+d
        ok = w[: n - d] >= d
        if ok.any():
            ins.append(sent[d:][ok])
            tgts.append(sent[: n - d][ok])
        # context behind center: center i, context i-d
        ok = w[d:] >= d
        if ok.any():
            ins.append(sent[: n - d][ok])
            tgts.append(sent[d:][ok])
    if not ins:
        return (np.zeros(0, np.int64),) * 2
    return np.concatenate(ins), np.concatenate(tgts)


def window_examples(sent: np.ndarray, window: int, rng):
    """Vectorized CBOW/DM extraction: per center, the surrounding window
    as a mask-padded row. Returns (ctx [n, 2*window], mask [n, 2*window],
    targets [n])."""
    n = sent.size
    if n == 0:
        return (
            np.zeros((0, 2 * window), np.int64),
            np.zeros((0, 2 * window), np.float32),
            np.zeros(0, np.int64),
        )
    w = window - rng.integers(0, window, n)
    offsets = np.concatenate(
        [np.arange(-window, 0), np.arange(1, window + 1)]
    )  # [2W]
    pos = np.arange(n)[:, None] + offsets[None, :]          # [n, 2W]
    dist = np.abs(offsets)[None, :]
    valid = (pos >= 0) & (pos < n) & (dist <= w[:, None])
    ctx = sent[np.clip(pos, 0, n - 1)]
    return ctx, valid.astype(np.float32), sent


def generate_batches(
    sentences, plan: BatchPlan, *, window: int, mode: str, rng,
    doc_ids: Optional[Sequence[int]] = None,
) -> Iterator[dict]:
    """Stream fixed-shape batches. mode: skipgram | cbow | dm | dbow.
    For dm/dbow, doc_ids aligns with sentences. Examples from all
    sentences are pooled, then sliced into B-sized batches (tail rows
    masked to true no-ops)."""
    sents = list(sentences)
    docs = list(doc_ids) if doc_ids is not None else None

    h_idx_l: List[np.ndarray] = []
    h_mask_l: List[np.ndarray] = []
    tgt_l: List[np.ndarray] = []
    doc_l: List[np.ndarray] = []

    for si, sent in enumerate(sents):
        if sent.size == 0:
            continue
        if mode == "skipgram":
            ins, tgts = skipgram_examples(sent, window, rng)
            if ins.size == 0:
                continue
            h_idx_l.append(ins[:, None])
            h_mask_l.append(np.ones((ins.size, 1), np.float32))
            tgt_l.append(tgts)
            if docs is not None:
                doc_l.append(np.full(ins.size, docs[si], np.int64))
        elif mode in ("cbow", "dm"):
            ctx, mask, tgts = window_examples(sent, window, rng)
            if mode == "cbow":
                keepr = mask.any(axis=1)  # centers with no context: skip
                ctx, mask, tgts = ctx[keepr], mask[keepr], tgts[keepr]
            if tgts.size == 0:
                continue
            h_idx_l.append(ctx)
            h_mask_l.append(mask)
            tgt_l.append(tgts)
            if docs is not None:
                doc_l.append(np.full(tgts.size, docs[si], np.int64))
        elif mode == "dbow":
            # the doc vector alone predicts each word (reference: DBOW.java)
            h_idx_l.append(np.zeros((sent.size, 1), np.int64))
            h_mask_l.append(np.zeros((sent.size, 1), np.float32))
            tgt_l.append(sent)
            doc_l.append(np.full(sent.size, docs[si], np.int64))
        else:
            raise ValueError(f"unknown mode {mode!r}")

    if not tgt_l:
        return
    C = plan.C
    h_idx = np.concatenate([
        np.pad(a, ((0, 0), (0, C - a.shape[1]))) for a in h_idx_l
    ])
    h_mask = np.concatenate([
        np.pad(a, ((0, 0), (0, C - a.shape[1]))) for a in h_mask_l
    ])
    targets = np.concatenate(tgt_l)
    doc_idx = np.concatenate(doc_l) if doc_l else None

    N = targets.size
    for start in range(0, N, plan.B):
        sl = slice(start, min(start + plan.B, N))
        yield plan.make_batch(
            h_idx[sl], h_mask[sl], targets[sl],
            None if doc_idx is None else doc_idx[sl], rng,
        )
