"""ParallelWrapper — DEPRECATED facade over the mainline sharded step.

Reference: deeplearning4j-scaleout/deeplearning4j-scaleout-parallelwrapper/
src/main/java/org/deeplearning4j/parallelism/ParallelWrapper.java — N worker
threads each holding a full model replica, barrier every
`averagingFrequency` iterations, then parameter + updater-state averaging
across replicas (:417-424, :231-262).

There is nothing left for a wrapper to do: `fit()` itself now runs the
single jitted, donated, NamedSharding data-parallel optimizer step over
the device mesh (nn/netbase.set_mesh + parallel/sharded.MeshPlan), with
the gradient all-reduce in-graph. Per-step gradient allreduce is
mathematically ⊇ parameter averaging with frequency=1 when each "worker"
contributes one shard of the global batch:

    averaged params = mean_i (θ - lr·g_i) = θ - lr·mean_i(g_i)

(asserted by tests/test_parallel.py::test_allreduce_equals_parameter_
averaging). Higher averaging frequencies trade accuracy for communication
that ICI does not need; they are intentionally not reproduced.

This class remains as a thin API-parity shim: construction attaches the
mesh plan to the model (`model.set_mesh(mesh)`) and `fit()` delegates to
the model's own fit loop — no per-interval host round-trip of parameters,
no replicas, no averaging step. New code should call `net.set_mesh(mesh)`
(or just `net.fit(...)`, which attaches a mesh automatically on
multi-device platforms) and drop the wrapper. See MIGRATION.md.
"""

from __future__ import annotations

import logging
import warnings

import jax
import numpy as np

from deeplearning4j_tpu.data.iterators import (
    DataSetIterator,
    StackedDataSetIterator,
)
from deeplearning4j_tpu.parallel.mesh import (
    data_parallel_mesh,
    pad_wrap,
    placement_for_batch,
)

logger = logging.getLogger("deeplearning4j_tpu")


class ParallelWrapper:
    """Deprecated data-parallel trainer facade (see module doc).

    Args:
        model: an initialized (or initializable) MultiLayerNetwork or
            ComputationGraph.
        mesh: a `jax.sharding.Mesh` with a "data" axis; defaults to a 1-D
            mesh over all visible devices.
        workers: how many iterator minibatches form one global step
            (reference: each DefaultTrainer consumed one minibatch between
            barriers). Default 1 — the iterator's batches are already
            global.
        averaging_frequency: accepted for API parity; only 1 is meaningful
            here because allreduce happens every step (see module doc).
        prefetch_buffer: async host-side prefetch depth.
    """

    def __init__(
        self,
        model,
        mesh=None,
        workers: int = 1,
        averaging_frequency: int = 1,
        prefetch_buffer: int = 4,
    ):
        if averaging_frequency != 1:
            raise ValueError(
                "averaging_frequency > 1 is a CPU/PCIe-era tradeoff; the "
                "per-step ICI gradient allreduce used here is exact "
                "averaging with frequency=1 (see parallel/wrapper.py doc)"
            )
        if type(self) is ParallelWrapper:  # subclasses (multihost) are not
            warnings.warn(
                "ParallelWrapper is deprecated: fit() runs the sharded "
                "data-parallel step itself on multi-device platforms — "
                "call net.set_mesh(mesh) (or nothing at all) instead",
                DeprecationWarning, stacklevel=2)
        self.model = model
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.workers = int(workers)
        self.prefetch_buffer = prefetch_buffer
        model._require_init()
        # the whole former wrapper body — replicated placement, batch
        # sharding, mesh-aware step jit — now lives on the net itself
        model.set_mesh(self.mesh, plan=self._make_plan(self.mesh))
        self.n_shards = model._mesh_plan.n_data_shards

    def _make_plan(self, mesh):
        """The MeshPlan to attach; None = the standard single-process
        plan. MultiHostDataParallel overrides with the DCN plan."""
        return None

    # -- training ------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128, async_prefetch: bool = True):
        """Train data-parallel by delegating to the model's own sharded
        fit loop. Accepts the same inputs as MultiLayerNetwork.fit;
        `batch_size` is the GLOBAL batch (sharded across devices). With
        workers > 1 and an iterator input, each step consumes `workers`
        minibatches as one global batch. The model keeps its mesh plan
        after this call — it IS a sharded net now, not a wrapped one."""
        net = self.model
        data_in = data
        if self.workers > 1:
            if not isinstance(data, DataSetIterator):
                raise ValueError("workers > 1 requires a DataSetIterator input")
            data_in = StackedDataSetIterator(data, self.workers)
        if net._mesh_plan is None or net._mesh_plan.mesh is not self.mesh:
            # re-attach after an unset_mesh
            net.set_mesh(self.mesh, plan=self._make_plan(self.mesh))
        net.fit(data_in, labels, epochs=epochs, batch_size=batch_size,
                async_prefetch=async_prefetch,
                prefetch_buffer=self.prefetch_buffer)
        return net

    # -- sharded inference ---------------------------------------------------

    def output(self, x):
        """Data-parallel forward pass: shards the batch, same replicated
        params. Non-divisible batches are padded by wrapping and the pad
        rows sliced off the result — sharded execution and a stable trace
        shape instead of the replicated fallback."""
        xx = np.asarray(x)
        n = xx.shape[0]
        pad = (-n) % self.n_shards
        if pad:
            xx = pad_wrap(xx, self.n_shards)
        sh = placement_for_batch(self.mesh, xx.shape[0])
        out = self.model.output(jax.device_put(xx, sh))
        return out[:n] if pad else out
