"""Run ledger + SLO rules (utils/runledger, analysis/slo): continuous
recording, declarative alert lifecycle, cross-run regression analysis.

Covers the PR's acceptance criteria: the off-path overhead contract
(<10 µs hooks, fit A/B within noise), the injected-degradation round
trip (faultpoints latency on `replica_forward` flips the p99 burn-rate
rule to firing — health DEGRADED, `/alerts` lists it, `cli slo --check`
exits 1 — and releasing the fault resolves it), `cli runs compare`
flagging a deliberately mis-set input pipeline on the right metric
family, ledger replay through `cli metrics --ledger`, and the
stats-storage retention knob answering `get_updates` consistently."""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.analysis import slo
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import faultpoints as fp
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import runledger

N_IN = 6


@pytest.fixture(autouse=True)
def _clean_slate():
    """No leftover fault plan, no leftover attached ledger, no leftover
    health conditions — SLO state must never leak across tests."""
    fp.clear()
    runledger.detach()
    yield
    fp.clear()
    runledger.detach()
    h = _health.get_health()
    with h._lock:
        leftovers = list(h._conditions)
    for comp in leftovers:
        h.set_condition(comp, _health.OK, reason="test teardown")


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Updater.SGD).learning_rate(0.05).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _xy(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, N_IN)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


# -- rule engine (pure) -------------------------------------------------------


def test_threshold_and_drift_rules_with_selectors():
    rules = slo.SLORuleSet([
        slo.SLORule(name="depth", kind="threshold",
                    series="serving_queue_depth", op=">", value=4.0),
        slo.SLORule(name="mfu", kind="drift", series="step_mfu",
                    op="<", reference=0.8, frac=0.5,
                    severity="warning"),
        slo.SLORule(name="live_mem", kind="drift",
                    series='device_memory_bytes{kind="live"}',
                    op=">", reference=1000.0, frac=0.9),
    ])
    # below every limit: nothing pending/firing
    out = rules.evaluate(1.0, {
        "serving_queue_depth": 2.0,
        'step_mfu{source="costmodel"}': 0.5,
        'device_memory_bytes{kind="live"}': 100.0,
        'device_memory_bytes{kind="params"}': 5000.0,  # label-filtered out
    })
    assert out == [] and rules.firing() == []
    # queue over capacity + mfu collapsed + live over 900
    out = rules.evaluate(2.0, {
        "serving_queue_depth": 9.0,
        'step_mfu{source="costmodel"}': 0.1,
        'device_memory_bytes{kind="live"}': 950.0,
    })
    assert sorted(t["rule"] for t in out) == ["depth", "live_mem", "mfu"]
    assert all(t["to"] == "firing" for t in out)
    # absence of data is not an alert: rules with no matching series
    # resolve, and the resolution transitions say so
    out = rules.evaluate(3.0, {})
    assert sorted(t["rule"] for t in out) == ["depth", "live_mem", "mfu"]
    assert all(t["to"] == "resolved" for t in out)


def test_for_seconds_debounce_pending_then_firing():
    rules = slo.SLORuleSet([slo.SLORule(
        name="r", kind="threshold", series="g", op=">", value=1.0,
        for_seconds=5.0)])
    assert rules.evaluate(0.0, {"g": 2.0}) == []  # pending
    assert rules.status()[0]["state"] == "pending"
    assert rules.evaluate(3.0, {"g": 2.0}) == []  # still inside for:
    out = rules.evaluate(6.0, {"g": 2.0})  # held long enough
    assert [t["to"] for t in out] == ["firing"]
    # one clean sample resolves, and the pending clock restarts fresh
    out = rules.evaluate(7.0, {"g": 0.0})
    assert [t["to"] for t in out] == ["resolved"]
    assert rules.evaluate(8.0, {"g": 2.0}) == []  # pending again


def test_rate_of_change_rule():
    rules = slo.SLORuleSet([slo.SLORule(
        name="oom", kind="rate_of_change", series="oom_total",
        op=">", value=0.0)])
    assert rules.evaluate(0.0, {"oom_total": 0.0}) == []  # no prior
    assert rules.evaluate(1.0, {"oom_total": 0.0}) == []  # flat
    out = rules.evaluate(2.0, {"oom_total": 1.0})  # an OOM landed
    assert [t["to"] for t in out] == ["firing"]
    out = rules.evaluate(3.0, {"oom_total": 1.0})  # no new OOMs
    assert [t["to"] for t in out] == ["resolved"]


def _hist_sample(good, total, le="0.1"):
    """Synthetic histogram scalars: `good` under the `le` bucket out of
    `total` observations."""
    return {
        "lat:count": float(total),
        "lat:sum": float(total) * 0.01,
        f"lat:bucket:{le}": float(good),
        "lat:bucket:+Inf": float(total),
    }


def test_burn_rate_rule_windowed():
    rules = slo.SLORuleSet([slo.SLORule(
        name="p99", kind="burn_rate", series="lat",
        objective=0.9, threshold_ms=100.0, window_seconds=0.0,
        max_burn=1.0, min_events=5)])
    assert rules.evaluate(0.0, _hist_sample(0, 0)) == []  # no traffic
    # 20 requests, all under 100ms: burn 0
    assert rules.evaluate(1.0, _hist_sample(20, 20)) == []
    # next window: 10 more, 8 of them slow -> bad_frac 0.8, burn 8 > 1
    out = rules.evaluate(2.0, _hist_sample(22, 30))
    assert [t["to"] for t in out] == ["firing"]
    assert out[0]["value"] == pytest.approx(8.0)
    # fewer than min_events in the window: insufficient data = resolved
    out = rules.evaluate(3.0, _hist_sample(23, 31))
    assert [t["to"] for t in out] == ["resolved"]
    # traffic resumes fast: stays resolved
    assert rules.evaluate(4.0, _hist_sample(43, 51)) == []
    assert rules.status()[0]["fired_total"] == 1


def test_rule_serde_roundtrip_and_validation():
    pack = slo.default_rule_pack(
        serving={"default_deadline_ms": 100.0, "queue_capacity": 4})
    text = json.dumps({"rules": [r.to_dict() for r in pack]})
    rs = slo.SLORuleSet.from_json(text)
    assert [r.name for r in rs.rules] == [r.name for r in pack]
    burn = next(r for r in rs.rules
                if r.name == "serving_p99_deadline_burn")
    assert burn.threshold_ms == 100.0 and burn.objective == 0.99
    assert burn.series == "serving_output_seconds"
    with pytest.raises(ValueError):
        slo.SLORule(name="x", kind="nope", series="g")
    with pytest.raises(ValueError):
        slo.SLORule(name="x", kind="threshold", series="g")  # no value
    with pytest.raises(ValueError):
        slo.SLORuleSet.from_dicts([{"name": "x", "kind": "threshold",
                                    "series": "g", "value": 1.0,
                                    "bogus_field": 2}])


def test_default_rule_pack_from_cost_model():
    from deeplearning4j_tpu.analysis.costmodel import train_step_cost
    from deeplearning4j_tpu.nn.conf import InputType

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Updater.SGD).learning_rate(0.05).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN))
            .build())
    cm = train_step_cost(MultiLayerNetwork(conf).init(), batch_size=2)
    pack = slo.default_rule_pack(cost_model=cm)
    by_name = {r.name: r for r in pack}
    mfu = by_name["mfu_below_roofline"]
    assert mfu.kind == "drift" and mfu.op == "<"
    assert mfu.reference == pytest.approx(cm.roofline()["mfu_ceiling"])
    assert mfu.reference_source == "costmodel:mfu_ceiling"
    # CPU container: no HBM budget -> no residency rule (None off-TPU)
    from deeplearning4j_tpu.utils.flops import peak_hbm_bytes_per_chip

    if peak_hbm_bytes_per_chip() is None:
        assert "hbm_residency" not in by_name


# -- the ledger artifact ------------------------------------------------------


def test_ledger_records_reconstructs_and_enriches(tmp_path):
    path = str(tmp_path / "run.jsonl")
    reg = _metrics.get_registry()
    c = reg.counter("ledger_demo_total", "t").labels()
    led = runledger.RunLedger(path, sample_every=60.0,
                              links={"bench": "BENCH_x.json"})
    runledger.attach(led)
    try:
        c.inc(5)
        net = _net()
        x, y = _xy(24)
        net.fit(x, y, epochs=1, batch_size=8, async_prefetch=False)
        led.sample_now()
        c.inc(2)
        led.add_link("trace", "trace.jsonl")
    finally:
        led.close()
    assert runledger.current() is None  # close() detaches
    doc = runledger.read_ledger(path)
    man = doc["manifest"]
    assert man["run_id"] == led.run_id
    assert man["devices"].get("platform") == "cpu"
    assert man["links"] == {"bench": "BENCH_x.json",
                            "trace": "trace.jsonl"}
    # the fit hook handed the net over; the recorder thread enriched
    # the manifest via an append-only note
    assert man.get("config_hash") and man.get("network_type") \
        == "MultiLayerNetwork"
    assert man.get("flops_source") in ("analytic", "costmodel")
    samples = list(runledger.iter_samples(doc))
    assert len(samples) >= 3  # t0 baseline + manual + final
    last = samples[-1][1]
    assert last["ledger_demo_total"] == 7.0
    assert last["fit_step_total"] >= 3.0
    # delta rows really are deltas: the untouched counter appears in
    # the first sample only
    sample_rows = [r for r in doc["rows"] if r["kind"] == "sample"]
    appearances = ["ledger_demo_total" in r["values"]
                   for r in sample_rows]
    assert appearances[1] is True  # the +5 landed in the 2nd sample
    # histogram buckets ride along for offline burn-rate evaluation
    assert any(":bucket:" in k for k in last)


def test_ledger_rollup_retention_bounds_the_artifact(tmp_path):
    path = str(tmp_path / "soak.jsonl")
    g = _metrics.get_registry().gauge("soak_gauge", "t").labels()
    led = runledger.RunLedger(path, sample_every=60.0,
                              raw_window=8, rollup_chunk=4)
    led.start()
    try:
        for i in range(40):
            g.set(float(i))
            led.sample_now()
    finally:
        led.close()
    doc = runledger.read_ledger(path)
    kinds = [r["kind"] for r in doc["rows"]]
    n_samples = kinds.count("sample")
    n_rollups = kinds.count("rollup")
    assert n_rollups >= 5  # ~30 old samples folded, 4 per rollup
    assert n_samples <= 8 + 4 + 2  # raw window + slack + final
    # reconstruction through rollups stays exact: the final absolute
    # value survives the folding
    samples = list(runledger.iter_samples(doc))
    assert samples[-1][1]["soak_gauge"] == 39.0
    # rollups carry the span stats
    roll = next(r for r in doc["rows"] if r["kind"] == "rollup")
    st = roll["series"]["soak_gauge"]
    assert st["min"] <= st["mean"] <= st["max"]
    assert st["last"] == st["max"]  # monotone gauge in this test


def test_hook_overhead_unattached_and_fit_ab_within_noise():
    """The off-by-default overhead contract: with no ledger attached
    both hooks are one flag check (<10 µs — the PR 6 record_step pin),
    and recording ON leaves sampled fit wall time within noise of a
    no-ledger A/B (the ledger samples on its own daemon, never the fit
    thread)."""
    assert runledger.current() is None
    net = _net()
    t0 = time.perf_counter()
    for _ in range(10_000):
        runledger.note_fit_step(net)
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 10e-6, f"note_fit_step cost {per_call * 1e6:.2f}us"
    t0 = time.perf_counter()
    for _ in range(10_000):
        runledger.note_request()
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 10e-6, f"note_request cost {per_call * 1e6:.2f}us"

    x, y = _xy(n=120)

    def fit_once():
        fnet = MultiLayerNetwork(
            NeuralNetConfiguration.builder().seed(3).updater(Updater.SGD)
            .learning_rate(0.05).weight_init("xavier").list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent")).build()).init()
        fnet.fit(x, y, epochs=1, batch_size=4, async_prefetch=False)
        t = time.perf_counter()
        fnet.fit(x, y, epochs=1, batch_size=4, async_prefetch=False)
        return time.perf_counter() - t

    import tempfile

    on_t, off_t = [], []
    for i in range(2):
        led = runledger.RunLedger(os.path.join(
            tempfile.gettempdir(),
            f"_ab_ledger_{os.getpid()}_{i}.jsonl"), sample_every=30.0)
        runledger.attach(led)
        try:
            on_t.append(fit_once())
        finally:
            led.close()
            os.unlink(led.path)
        off_t.append(fit_once())
    # interleaved minima, generous bound (same guard style as the
    # flight-recorder A/B): catches a real hot-path regression (a
    # per-step sample or registry walk), not scheduler noise
    assert min(on_t) < min(off_t) * 1.8 + 0.1, (on_t, off_t)


def test_fit_run_ledger_knob_owns_and_closes(tmp_path):
    path = str(tmp_path / "fit.jsonl")
    net = _net()
    x, y = _xy(32)
    net.fit(x, y, epochs=1, batch_size=8, async_prefetch=False,
            run_ledger=path)
    # the fit-scoped ledger closed and detached itself
    assert runledger.current() is None
    doc = runledger.read_ledger(path)
    samples = list(runledger.iter_samples(doc))
    assert len(samples) >= 2
    assert samples[-1][1]["fit_step_total"] \
        - samples[0][1].get("fit_step_total", 0) == 4.0


# -- the injected-degradation acceptance round trip ---------------------------


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_alert_lifecycle_under_injected_latency(tmp_path):
    """The satellite acceptance: a faultpoints latency rule on
    `replica_forward` flips the burn-rate rule to firing (health
    DEGRADED with the rule named, `/alerts` lists it, `cli slo --check`
    exits 1 on the recorded ledger), and releasing the fault resolves
    it — deterministic and seeded."""
    from deeplearning4j_tpu.serving import InferenceServer

    path = str(tmp_path / "serve.jsonl")
    rules = [slo.SLORule(
        name="p99_deadline_burn", kind="burn_rate",
        series="serving_output_seconds",
        objective=0.9, threshold_ms=100.0, window_seconds=0.0,
        max_burn=1.0, min_events=3, severity="error",
        component="serving", for_seconds=0.0)]
    led = runledger.RunLedger(path, sample_every=60.0, rules=rules)
    server = InferenceServer(_net(), port=0, max_batch_size=4,
                             batch_timeout_ms=1.0,
                             warmup_shape=(N_IN,), run_ledger=led)
    port = server.start()
    url = f"http://127.0.0.1:{port}"

    def predict(n=1):
        for i in range(n):
            body = json.dumps({"features": [[0.1] * N_IN]}).encode()
            req = urllib.request.Request(
                f"{url}/predict", data=body,
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=20).read()

    def alerts():
        with urllib.request.urlopen(f"{url}/alerts", timeout=10) as r:
            return json.loads(r.read().decode())

    try:
        predict(4)  # fast traffic
        led.sample_now()
        assert led.rules.firing() == []
        # inject 150ms on every device forward (seeded plan)
        plan = fp.FaultPlan(seed=1).add("replica_forward", "latency",
                                        every_nth=1, latency_ms=150.0)
        with fp.active(plan):
            predict(4)  # every request now blows the 100ms objective
            led.sample_now()
        assert led.rules.firing() == ["p99_deadline_burn"]
        # health: the owning component is DEGRADED, condition names the
        # rule
        comp = _health.get_health().status()["components"]["serving"]
        assert comp["status"] == "degraded"
        assert "p99_deadline_burn" in comp["condition"]["reason"]
        # /alerts lists the firing rule machine-readably
        a = alerts()
        assert a["firing"] == ["p99_deadline_burn"]
        state = next(r for r in a["rules"]
                     if r["rule"] == "p99_deadline_burn")
        assert state["state"] == "firing" and state["value"] > 1.0
        # the firing emitted a finding, a counter, and a flight-recorder
        # event
        assert any(f.code == "SLO001" for f in led.findings)
        scalars = _metrics.get_registry().scalar_values()
        assert scalars.get(
            'slo_alerts_total{rule="p99_deadline_burn",'
            'severity="error"}', 0) >= 1
        from deeplearning4j_tpu.utils.blackbox import get_recorder

        with get_recorder()._lock:
            events = [dict(e) for e in get_recorder()._events]
        assert any(e.get("kind") == "slo_alert"
                   and e.get("rule") == "p99_deadline_burn"
                   for e in events)
        # release the fault: fast traffic resolves the rule and clears
        # the health condition
        predict(4)
        led.sample_now()
        assert led.rules.firing() == []
        assert alerts()["firing"] == []
        comps = _health.get_health().status()["components"]
        assert comps.get("serving", {}).get("status", "ok") == "ok"
    finally:
        server.stop()
        led.close()
    # offline gate: the recorded ledger replays through the manifest's
    # own rule pack and the firing window fails --check
    from deeplearning4j_tpu import cli

    assert cli.main(["slo", "--ledger", path, "--check"]) == 1
    # the non-check form reports without gating
    assert cli.main(["slo", "--ledger", path]) == 0


# -- cross-run regression analysis --------------------------------------------


class _SlowListIterator(ListDataSetIterator):
    """The deliberately mis-set pipeline: a per-batch stall where the
    prefetch would have hidden it."""

    def __init__(self, dataset, batch, delay_s):
        super().__init__(dataset, batch)
        self.delay_s = delay_s

    def __iter__(self):
        for ds in super().__iter__():
            time.sleep(self.delay_s)
            yield ds


def test_runs_compare_flags_data_wait_regression(tmp_path, capsys):
    """Two recorded runs — one healthy, one with a stalling input
    pipeline — and `cli runs compare --json` names the regression on
    the fit_data_wait family, machine-readably."""
    x, y = _xy(n=96, seed=3)
    ref_path = str(tmp_path / "ref.jsonl")
    cand_path = str(tmp_path / "cand.jsonl")
    _net(seed=5).fit(ListDataSetIterator(DataSet(x, y), 8), epochs=1,
                     async_prefetch=False, run_ledger=ref_path)
    _net(seed=5).fit(_SlowListIterator(DataSet(x, y), 8, 0.012),
                     epochs=1, async_prefetch=False,
                     run_ledger=cand_path)
    from deeplearning4j_tpu import cli

    assert cli.main(["runs", "compare", ref_path, cand_path,
                     "--json", "-"]) == 0
    report = json.loads(capsys.readouterr().out)
    fams = report["regression_families"]
    assert any(f.startswith("fit_data_wait_seconds") for f in fams), fams
    row = next(r for r in report["regressions"]
               if r["series"] == "fit_data_wait_seconds:mean")
    assert row["ratio"] > 2.0  # 12ms stalls vs in-memory slicing
    # and the listing surface sees both runs
    assert cli.main(["runs", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "2 run(s)" in out


def test_cli_metrics_ledger_replay(tmp_path, capsys):
    path = str(tmp_path / "replay.jsonl")
    c = _metrics.get_registry().counter("replay_demo_total", "t").labels()
    led = runledger.RunLedger(path, sample_every=60.0)
    led.start()
    try:
        c.inc(3)
        led.sample_now()
        c.inc(4)
    finally:
        led.close()
    from deeplearning4j_tpu import cli

    assert cli.main(["metrics", "--ledger", path]) == 0
    out = capsys.readouterr().out
    assert "replaying" in out
    assert "replay_demo_total  +3" in out
    assert "replay_demo_total  +4" in out
    assert ":bucket:" not in out  # tick view stays scalar


# -- stats-storage retention (satellite) --------------------------------------


def _record(i):
    return {"iteration": i, "ts": float(i), "score": float(i) * 0.5,
            "samples_per_sec": 10.0, "etl_ms": 1.0}


@pytest.mark.parametrize("store_kind", ["file", "sqlite"])
def test_stats_storage_retention_consistent(tmp_path, store_kind):
    from deeplearning4j_tpu.ui.storage import (
        FileStatsStorage,
        SqliteStatsStorage,
    )

    path = str(tmp_path / f"stats_{store_kind}.bin")
    cap = 20
    if store_kind == "file":
        store = FileStatsStorage(path, max_updates_per_session=cap)
    else:
        store = SqliteStatsStorage(path, max_updates_per_session=cap)
    store.put_static_info("s", {"start_time": 0.0})
    for i in range(100):
        store.put_update("s", _record(i))
    ups = store.get_updates("s")
    # capped (compaction may lag up to cap//2 appends past the cap)
    assert len(ups) <= cap + cap // 2
    its = [u["iteration"] for u in ups]
    # ordered, no duplicates, newest record always survives, and the
    # newest half is raw (exact tail)
    assert its == sorted(set(its))
    assert its[-1] == 99
    assert its[-cap // 2:] == list(range(100 - cap // 2, 100))
    # since_iteration answers consistently on the capped store
    recent = store.get_updates("s", since_iteration=90)
    assert [u["iteration"] for u in recent] == list(range(91, 100))
    # a reopened store (cold read) stays consistent: an ordered subset
    # of the live view (open may compact down to the cap), same exact
    # newest tail
    if store_kind == "file":
        again = FileStatsStorage(path, max_updates_per_session=cap)
    else:
        store.close()
        again = SqliteStatsStorage(path, max_updates_per_session=cap)
    re_its = [u["iteration"] for u in again.get_updates("s")]
    assert len(re_its) <= cap + cap // 2
    assert set(re_its) <= set(its)
    assert re_its == sorted(set(re_its))
    assert re_its[-cap // 2:] == its[-cap // 2:]
    if store_kind == "sqlite":
        again.close()


def test_stats_storage_uncapped_unchanged(tmp_path):
    from deeplearning4j_tpu.ui.storage import FileStatsStorage

    store = FileStatsStorage(str(tmp_path / "u.bin"))
    for i in range(50):
        store.put_update("s", _record(i))
    assert len(store.get_updates("s")) == 50


# -- UI surfaces --------------------------------------------------------------


def test_ui_alerts_and_system_live_routes(tmp_path):
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    ui = UIServer(InMemoryStatsStorage(), port=0)  # never start()ed

    def route_json(route):
        resp = ui._get(route, b"", {})
        assert resp is not None, route
        return json.loads(resp[2].decode())

    # no ledger attached: explicit note, not an error
    d = route_json("/train/alerts/data")
    assert d["ledger"] is None and "note" in d
    # the alerts page itself renders
    page = ui._get("/train/alerts", b"", {})
    assert b"alerts" in page[2]
    # with a ledger + rules: rule states flow through
    led = runledger.RunLedger(str(tmp_path / "ui.jsonl"),
                              sample_every=60.0,
                              rules=[slo.SLORule(
                                  name="g", kind="threshold", series="g",
                                  op=">", value=1.0)])
    runledger.attach(led)
    try:
        d = route_json("/train/alerts/data")
        assert d["run_id"] == led.run_id
        assert [r["rule"] for r in d["rules"]] == ["g"]
    finally:
        led.close()
    # the system view samples the live devprof/serving gauges into
    # chartable history (PR 9's headline gauges visible in the UI)
    _metrics.get_registry().gauge(
        "step_mfu", "measured model-FLOPs utilization over the last "
        "devprof sample window", ("source",)).labels("costmodel").set(0.31)
    d1 = route_json("/train/system/data")
    d2 = route_json("/train/system/data")
    key = 'step_mfu{source="costmodel"}'
    assert key in d2["live"]
    assert len(d2["live"][key]) == len(d1["live"][key]) + 1
    assert d2["live"][key][-1][1] == pytest.approx(0.31)


# -- health condition mechanics -----------------------------------------------


def test_health_condition_merges_and_clears():
    h = _health.get_health()
    h.set_condition("cond_demo", _health.DEGRADED, reason="rule r1")
    st = h.status()
    assert st["components"]["cond_demo"]["status"] == "degraded"
    assert st["status"] != "ok"
    scalars = _metrics.get_registry().scalar_values()
    assert scalars['component_health{component="cond_demo"}'] == 1.0
    # clearing removes the synthetic component entirely
    h.set_condition("cond_demo", _health.OK)
    assert "cond_demo" not in h.status()["components"]
    assert _metrics.get_registry().scalar_values()[
        'component_health{component="cond_demo"}'] == 0.0
    # clearing a condition never asserted is a no-op (no transition)
    seq = h.last_seq()
    h.set_condition("never_set", _health.OK)
    assert h.last_seq() == seq
