"""Common utilities: precision policy, registries, pytree helpers."""
