"""Test harness configuration.

Mirrors the reference's test-backend strategy (SURVEY.md §4): tests run on
the CPU backend with a virtual 8-device mesh so data-parallel equivalence
tests (n-device == 1-device) run without TPU hardware — the analog of the
reference's local[N] Spark contexts and thread-based ParallelWrapper tests.

Must set env vars before jax is imported anywhere.
"""

import os
import sys

# Note: this image's axon sitecustomize imports jax at interpreter start, so
# env vars set here are read too late; the config updates below are what
# actually select the CPU backend (backends initialize lazily). XLA_FLAGS is
# still read at first backend init, so setting it here works.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Numeric parity tests assume true-f32 matmuls/convs (the TPU bench path
# deliberately runs bf16 — that is a PrecisionPolicy choice, not a default).
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402

# Crash forensics for the tier-1 session (scripts/t1.sh sets
# T1_BLACKBOX_ARTIFACT): arm the flight recorder's SIGTERM/faulthandler/
# atexit hooks so a wedged session killed by the suite timeout leaves a
# dump naming the stuck thread (render with `cli blackbox <artifact>`)
# instead of just "pytest died".
_bb_artifact = os.environ.get("T1_BLACKBOX_ARTIFACT")
if _bb_artifact:
    from deeplearning4j_tpu.utils.blackbox import install_crash_hooks

    install_crash_hooks(_bb_artifact)

# Auto-mesh OFF by default under the suite (same discipline as the
# devprof line below): the virtual platform above exposes 8 devices, so
# fit()'s mainline multi-device default would otherwise compile an
# 8-way SPMD program for every tiny fit in the suite — slow on a 2-core
# box and a behavior change under hundreds of single-device numeric
# tests. The dedicated sharding tests opt in (set_mesh / monkeypatch),
# and scripts/t1.sh runs the 2-simulated-device AUTO-mesh smoke in its
# own interpreter with DL4J_AUTO_MESH=1. setdefault, not assignment, so
# that smoke run's explicit =1 wins.
os.environ.setdefault("DL4J_AUTO_MESH", "0")

# Pallas interpret mode OFF for the suite, whatever the invoking shell
# exported: on the CPU test backend the conv/BN kernel probes must refuse
# the real kernel path (tests that want interpret-mode numerics flip
# pcb._INTERPRET themselves via the module fixture, and restore it).
os.environ["DL4J_PALLAS_INTERPRET"] = "0"

# Device-profiler sampling OFF under tier-1 (utils/devprof): the sampled
# block_until_ready would add timing jitter to every fit-heavy test on a
# loaded CI box. Tests that exercise the sampler configure it locally
# (and restore) — the suite's default stays timing-stable.
from deeplearning4j_tpu.utils import devprof as _devprof  # noqa: E402

_devprof.configure(sample_every=0)

# Opt-in session run ledger (scripts/t1.sh T1_LEDGER_DUMP=1): record the
# shared metrics registry's trajectory over the whole pytest session to
# a per-run artifact (utils/runledger), next to the metrics/trace dumps
# — replay with `cli metrics --ledger <artifact>`. The ledger's own
# dl4j-ledger daemon is excluded from the thread-leak guard below (it
# legitimately spans every test); ledgers that TESTS create are not.
_t1_ledger = None
if os.environ.get("T1_LEDGER_DUMP"):
    from deeplearning4j_tpu.utils import runledger as _t1_runledger

    _t1_ledger = _t1_runledger.RunLedger(
        os.environ.get("T1_LEDGER_ARTIFACT", "/tmp/_t1_ledger.jsonl"),
        sample_every=5.0,
        manifest={"run_id": "t1-session"})
    _t1_ledger.start()  # record only — not attach()ed, so the fit/
    # serving hooks stay on their no-ledger path and the overhead
    # guard tests measure what production measures

# Opt-in trace artifact (scripts/t1.sh T1_TRACE_DUMP=1): accumulate every
# span any tracing-enabled test records into one session JSONL, next to
# the metrics dump. Tests deliberately clear the global ring in their
# teardown (never leak spans across tests), so a plain end-of-session
# export would be empty — instead the global tracer's clear() flushes the
# ring to the artifact first, and sessionfinish flushes the remainder.
_t1_trace_path = (os.environ.get("T1_TRACE_ARTIFACT", "/tmp/_t1_trace.jsonl")
                  if os.environ.get("T1_TRACE_DUMP") else None)
if _t1_trace_path:
    import json as _json

    from deeplearning4j_tpu.utils import tracing as _t1_tracing

    try:
        os.unlink(_t1_trace_path)  # fresh artifact per session
    except OSError:
        pass

    def _t1_trace_flush():
        evs = _t1_tracing.get_tracer().recent()
        if evs:
            with open(_t1_trace_path, "a") as f:
                for ev in evs:
                    f.write(_json.dumps(ev) + "\n")

    _t1_orig_clear = _t1_tracing.Tracer.clear

    def _t1_clear_with_flush(self):
        if self is _t1_tracing.get_tracer():
            _t1_trace_flush()
        _t1_orig_clear(self)

    _t1_tracing.Tracer.clear = _t1_clear_with_flush


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests (multi-process, large fits)")


# -- input-pipeline thread-leak guard -----------------------------------------
# Every background thread the data pipeline spawns carries the
# PIPELINE_THREAD_PREFIX name. After each test, none may survive: a live
# one is a producer left blocked on a queue nobody drains (exactly the
# AsyncDataSetIterator break-mid-epoch leak this guard was added to
# catch). The grace window lets a worker that is already past its last
# put finish dying.

import weakref  # noqa: E402

_PIPELINE_LEAKS = []
# thread OBJECTS already charged to a test (idents get recycled, objects
# don't); weak so a reported thread that finally dies can be collected
_REPORTED_LEAKED_THREADS = weakref.WeakSet()


def _live_pipeline_threads():
    import threading

    from deeplearning4j_tpu.data.iterators import PIPELINE_THREAD_PREFIX

    # the ledger recorder daemon (utils/runledger, dl4j-ledger-*) is
    # held to the same contract as pipeline workers: a test that starts
    # a RunLedger must close() it (which unregisters the heartbeat and
    # joins the thread). The session-scoped T1_LEDGER_DUMP ledger is
    # exempt — it deliberately spans the whole run.
    session_ledger_thread = getattr(_t1_ledger, "_thread", None)
    # dl4j-sparse-* (parallel/sparse prefetch workers) are held to the
    # same contract: SparseEmbeddingPipeline.close() joins its worker
    return sorted(((t, t.name) for t in threading.enumerate()
                   if (t.name.startswith(PIPELINE_THREAD_PREFIX)
                       or t.name.startswith("dl4j-ledger")
                       or t.name.startswith("dl4j-sparse"))
                   and t is not session_ledger_thread
                   and t.is_alive()
                   and t not in _REPORTED_LEAKED_THREADS),
                  key=lambda pair: pair[1])


@pytest.fixture(autouse=True)
def _pipeline_thread_leak_guard(request):
    yield
    import time

    deadline = time.monotonic() + 2.0
    leaked = _live_pipeline_threads()
    while leaked and time.monotonic() < deadline:
        time.sleep(0.05)
        leaked = _live_pipeline_threads()
    if leaked:
        # charge each leaked thread to the test that leaked it, once —
        # without this, one leak would cascade failures across the rest
        # of the session
        _REPORTED_LEAKED_THREADS.update(t for t, _ in leaked)
        names = [name for _, name in leaked]
        _PIPELINE_LEAKS.append((request.node.nodeid, names))
        pytest.fail(
            f"leaked input-pipeline worker threads: {names} — a pipeline "
            "stage was not closed (close-on-break contract, "
            "data/iterators.py)", pytrace=False)


def pytest_sessionfinish(session, exitstatus):
    # One greppable line for scripts/t1.sh: the thread-leak guard's
    # verdict for the whole session (each leak also failed its test).
    if _PIPELINE_LEAKS:
        print(f"\nT1 THREAD GUARD: {len(_PIPELINE_LEAKS)} test(s) leaked "
              "pipeline worker threads:")
        for nodeid, names in _PIPELINE_LEAKS:
            print(f"T1 THREAD GUARD:   {nodeid}: {names}")
    else:
        print("\nT1 THREAD GUARD: ok (no leaked pipeline worker threads)")

    # Checkpoint tmp-orphan guard (scripts/t1.sh greps the verdict):
    # every checkpoint/snapshot/latest.json write goes tmp + os.replace,
    # so a `*.tmp` file still in the session's tmp dirs after the run is
    # a writer that died (or was never joined) mid-save — exactly the
    # torn-file class the atomic-rename discipline exists to prevent.
    # (CheckpointListener._gc sweeps stale orphans at runtime; the guard
    # catches tests that leave them behind without ever GC-ing.)
    try:
        basetemp = session.config._tmp_path_factory.getbasetemp()
    except Exception:
        basetemp = None
    orphans = []
    if basetemp is not None:
        try:
            orphans = sorted(
                str(p) for p in basetemp.rglob("*.tmp*")
                if "checkpoint_iter" in p.name
                or p.name.startswith(("latest.json.", "tables.npz.")))
        except OSError:
            pass
    if orphans:
        print(f"T1 CKPT TMP GUARD: {len(orphans)} orphaned checkpoint "
              "tmp file(s) left by the run:")
        for p in orphans:
            print(f"T1 CKPT TMP GUARD:   {p}")
    else:
        print("T1 CKPT TMP GUARD: ok (no orphaned checkpoint tmp files)")

    # Perf snapshot (scripts/t1.sh greps the verdict): the static cost
    # model's totals for the tiny preset, recomputed every session — a
    # FLOP-accounting change (a costmodel.py edit, a new primitive rule)
    # moves these numbers, so accidental model drift is visible in the
    # gate output instead of silently re-basing every MFU claim.
    try:
        from deeplearning4j_tpu.analysis.costmodel import train_step_cost
        from deeplearning4j_tpu.models.resnet import tiny_resnet_conf
        from deeplearning4j_tpu.nn.compgraph import ComputationGraph

        _cm = train_step_cost(ComputationGraph(tiny_resnet_conf()).init(),
                              batch_size=2)
        print(f"T1 PERF SNAPSHOT: tiny_resnet(batch=2) "
              f"model_flops={_cm.model_flops:.0f} "
              f"flops_total={_cm.flops_total:.0f} "
              f"bytes_total={_cm.bytes_total:.0f} "
              f"activation_peak_bytes={_cm.activation_peak_bytes}")
    except Exception as e:  # the snapshot must never fail the suite
        print(f"T1 PERF SNAPSHOT: unavailable ({type(e).__name__}: {e})")

    # Opt-in trace artifact (scripts/t1.sh T1_TRACE_DUMP=1): flush
    # whatever the session's final tests left in the ring; everything
    # earlier was flushed by the clear() hook above. Render with
    # `cli trace <artifact>`.
    if _t1_trace_path:
        try:
            _t1_trace_flush()
        except Exception as e:  # an artifact failure must not fail the
            # suite
            print(f"[conftest] trace dump failed: {e}", file=sys.stderr)

    # Opt-in session run ledger (scripts/t1.sh T1_LEDGER_DUMP=1): final
    # sample + close, so the artifact ends with the session's last
    # registry state (replay: cli metrics --ledger <artifact>).
    if _t1_ledger is not None:
        try:
            _t1_ledger.close()
        except Exception as e:  # an artifact failure must not fail the
            # suite
            print(f"[conftest] ledger dump failed: {e}", file=sys.stderr)

    # Opt-in observability artifact (scripts/t1.sh T1_METRICS_DUMP=1):
    # dump the process-global metrics registry after the run so compile
    # counts / helper events can be diffed across PRs.
    if not os.environ.get("T1_METRICS_DUMP"):
        return
    import json

    from deeplearning4j_tpu.utils.metrics import get_registry

    path = os.environ.get("T1_METRICS_ARTIFACT", "/tmp/_t1_metrics.json")
    try:
        with open(path, "w") as f:
            json.dump(get_registry().snapshot(), f, indent=2, sort_keys=True)
    except Exception as e:  # an artifact failure must not fail the suite
        print(f"[conftest] metrics dump failed: {e}", file=sys.stderr)


@pytest.fixture
def rng_key():
    import jax

    return jax.random.PRNGKey(12345)
