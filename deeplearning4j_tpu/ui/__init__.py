"""Observability pipeline (reference: deeplearning4j-ui-parent, ~30k LoC).

Capability map:
- StatsListener (ui/stats.py)       <- BaseStatsListener.java:51,103-124
- storage SPI + impls (ui/storage.py) <- api/storage/StatsStorage.java,
  InMemoryStatsStorage / FileStatsStorage (MapDB/sqlite variants collapse
  into the file store — mechanism, not engine, is the capability)
- compact wire codec (ui/codec.py)  <- SBE-generated codecs (ui/stats/sbe/)
- dashboard server (ui/server.py)   <- PlayUIServer + TrainModule routes
  (/train/overview, /train/model, /train/system) + RemoteReceiverModule
"""

from deeplearning4j_tpu.ui.stats import (ConvolutionalIterationListener,
    StatsListener)
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage,
    InMemoryStatsStorage,
    RemoteUIStatsStorageRouter,
    StatsStorage,
)
from deeplearning4j_tpu.ui.server import UIServer

__all__ = [
    "ConvolutionalIterationListener",
    "StatsListener",
    "StatsStorage",
    "InMemoryStatsStorage",
    "FileStatsStorage",
    "RemoteUIStatsStorageRouter",
    "UIServer",
]
