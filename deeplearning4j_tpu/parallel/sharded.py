"""MeshPlan — the mainline multi-chip train-step sharding authority.

This is the SPMD data-parallel recipe (Megatron-style in-graph
collectives) promoted from `parallel/wrapper.py`'s opt-in batch-transform
hook into the thing `fit()` does by default on a multi-device platform:

* parameters + updater state are committed to the mesh **replicated**
  (or left in whatever NamedSharding a tp/pp helper already placed them
  with — `shard_params_tp` placements are honored, never clobbered);
* every global batch is **sharded on the "data" axis** (dim 0), padded
  and loss-masked to a stable shard-divisible shape so the tail batch
  neither recompiles nor drops to replicated execution;
* the optimizer step is ONE jitted program built with explicit
  `NamedSharding` in-shardings and the single-sourced donation rule
  (`netbase._step_donate_argnums`, audited by JX006), with the gradient
  all-reduce pinned **inside the program** by a sharding constraint at
  the grad site — there is no host-side averaging anywhere in the step
  path (the DL4J ParallelWrapper semantics this replaces: per-step
  gradient psum/mean == parameter averaging with frequency 1, see
  tests/test_parallel.py::test_allreduce_equals_parameter_averaging).

Attach with `net.set_mesh(mesh)` (None = 1-D "data" mesh over all
devices). `fit()` attaches one automatically when more than one device
is visible — disable with `DL4J_AUTO_MESH=0` (tests/conftest.py does,
so the 8-virtual-device tier-1 suite doesn't shard every tiny fit; the
dedicated sharding tests and the t1.sh 2-device smoke opt back in).

tp/pp/sp compose via config: build the mesh with `mesh_2d` and apply
`shard_params_tp` BEFORE `set_mesh` — `place_net` keeps any leaf
already committed to this mesh, and `jit_step` derives per-leaf
in-shardings from the live placement, so Megatron column/row splits ride
the same jitted step. The pipeline/sequence helpers (`pipeline_apply`,
`ring_self_attention`) stay shard_map-level building blocks for models
that need them.
"""

from __future__ import annotations

import inspect
import os
from typing import Optional, Tuple

import numpy as np


def auto_mesh_enabled() -> bool:
    """Should `fit()` auto-attach a data-parallel mesh on a multi-device
    platform? Default yes — the mainline multi-chip path. `DL4J_AUTO_MESH=0`
    disables (read per fit, so tests can flip it per-case)."""
    return os.environ.get("DL4J_AUTO_MESH", "1") not in ("0", "false", "no")


def _jax():
    import jax

    return jax


class MeshPlan:
    """Sharding plan of one net over one `jax.sharding.Mesh`.

    Single source of truth for: parameter/updater placement, batch
    sharding (the `_batch_transform` the input pipeline runs off the
    dispatch critical path), the step jit's in-shardings + donation, the
    in-graph gradient-reduction constraint, and the per-step collective
    accounting (`allreduce_bytes_total` / `train_step_collective_seconds`).
    """

    def __init__(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec

        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, data_shards

        if DATA_AXIS not in mesh.axis_names:
            raise ValueError(
                f"mesh axes {mesh.axis_names} have no '{DATA_AXIS}' axis — "
                "the sharded train step needs one to split the batch over")
        self.mesh = mesh
        self.n_data_shards = data_shards(mesh)
        self.replicated = NamedSharding(mesh, PartitionSpec())
        # batch dim 0 over "data"; stacked variants (fused multi-batch
        # programs, [K, B, ...]) shard dim 1
        self.batch = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
        self.batch_stacked = NamedSharding(
            mesh, PartitionSpec(None, DATA_AXIS))
        # pad-up-to target: largest shard-divisible batch seen this fit,
        # so a short tail reuses the full batches' executable (reset by
        # the fit loop at each run start)
        self._pad_target = 0
        # per-net cached gradient payload bytes (the allreduce books)
        self._payload_bytes: Optional[int] = None

    # -- placement -----------------------------------------------------------

    def _on_this_mesh(self, a) -> bool:
        jax = _jax()
        if not isinstance(a, jax.Array):
            return False
        sh = getattr(a, "sharding", None)
        return getattr(sh, "mesh", None) == self.mesh

    def place_net(self, net) -> "MeshPlan":
        """Commit the net's params, layer state and updater state to the
        mesh, replicated — the once-per-attach analog of the reference
        copying the source model into every worker replica. Leaves a
        tp/pp helper already committed to THIS mesh keep their sharding
        (re-putting them replicated would silently all-gather a
        deliberately distributed weight)."""
        jax = _jax()

        def put(a):
            if a is None or self._on_this_mesh(a):
                return a
            return jax.device_put(a, self.replicated)

        tm = lambda t: jax.tree_util.tree_map(put, t)
        net.params_list = tm(net.params_list)
        net.state_list = tm(net.state_list)
        net.upd_state = tm(net.upd_state)
        self._payload_bytes = None
        return self

    def tree_shardings(self, tree):
        """Per-leaf NamedShardings of a live pytree — the in-shardings of
        the params/updater arguments. Leaves not committed to this mesh
        (e.g. freshly-restored checkpoint numpy) fall back to replicated,
        which is what the step's first dispatch will commit them to."""
        jax = _jax()
        return jax.tree_util.tree_map(
            lambda a: a.sharding if self._on_this_mesh(a) else self.replicated,
            tree)

    # -- batch sharding ------------------------------------------------------

    def reset_pad_target(self) -> None:
        """Per-fit state: a later fit with a smaller batch size must not
        keep padding to the old larger shape."""
        self._pad_target = 0

    def _stage_array(self, a, sh, pad: int, target: int):
        """One batch array onto the mesh. Fast paths, in order: already
        committed with the target sharding -> zero-copy passthrough
        (the `_pipeline_staged` contract extended to sharded placement —
        a pre-staged batch is never transferred twice); already a device
        array and no pad needed -> device-side reshard, no host hop.
        Only a padded tail takes the host round-trip (np.resize wrap)."""
        jax = _jax()
        if a is None:
            return None
        if pad == 0 and isinstance(a, jax.Array):
            cur = getattr(a, "sharding", None)
            if cur == sh:
                return a
            try:
                if cur is not None and cur.is_equivalent_to(sh, a.ndim):
                    return a
            except Exception:
                pass
            return jax.device_put(a, sh)
        from deeplearning4j_tpu.parallel.mesh import pad_wrap

        return jax.device_put(pad_wrap(np.asarray(a), target), sh)

    def shard_batch(self, ds):
        """Shard a global batch's dim 0 across the data axis (DataSet or
        MultiDataSet — ComputationGraph fit yields the latter). Installed
        as the net's `_batch_transform`, so under async_prefetch it runs
        inside the device-prefetch worker thread, off the dispatch
        critical path.

        Pad-and-mask tail handling (moved verbatim from the old
        ParallelWrapper): a batch not divisible by the shard count is
        padded to the next multiple by WRAPPING examples and the pad rows
        are excluded from the loss via an all-zero labels-mask row
        (losses use masked_example_mean, so the padded step computes
        exactly the unpadded score/gradients). A labels mask of ones is
        supplied for full batches too, keeping ONE trace signature — the
        tail batch neither recompiles nor drops to replicated serial
        execution. Wrapped pad rows do still enter batch-norm batch
        statistics — a stochastic duplicate-sample effect on the tail
        step only."""
        jax = _jax()
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet

        n = ds.num_examples()
        target = max(n + ((-n) % self.n_data_shards), self._pad_target)
        self._pad_target = target
        pad = target - n
        sh = self.batch

        def stage(a):
            return self._stage_array(a, sh, pad, target)

        def pad_lmask(lm):
            """Existing labels mask: pad rows of zeros. Absent: 0/1
            vector."""
            if lm is not None:
                if pad == 0:
                    return stage(lm)
                lm = np.asarray(lm)
                z = np.zeros((pad,) + lm.shape[1:], lm.dtype)
                return jax.device_put(np.concatenate([lm, z]), sh)
            m = np.ones((n + pad,), np.float32)
            if pad:
                m[n:] = 0.0
            return jax.device_put(m, sh)

        if isinstance(ds, MultiDataSet):
            lmasks = ds.labels_masks
            if lmasks is None:
                lmasks = [None] * len(ds.labels)
            out = MultiDataSet(
                [stage(f) for f in ds.features],
                [stage(l) for l in ds.labels],
                None if ds.features_masks is None
                else [stage(m) for m in ds.features_masks],
                [pad_lmask(m) for m in lmasks],
            )
        else:
            out = DataSet(
                stage(ds.features),
                stage(ds.labels),
                stage(ds.features_mask),
                pad_lmask(ds.labels_mask),
            )
        # listeners/counters must see the REAL example count, not the pad
        out.reported_examples = getattr(ds, "reported_examples", None) or n
        return out

    # -- the sharded step jit ------------------------------------------------

    def jit_step(self, net, step, *, donate_argnums: Tuple[int, ...],
                 data_argnums: Tuple[int, ...] = (3,),
                 stacked_data: bool = False):
        """jit an optimizer-step body with explicit NamedSharding
        in-shardings: per-leaf placements for params (argnum 0) and
        updater state (argnum 2) — which is what lets tp-sharded weights
        ride the same program — the batch sharding for the data argnums,
        replicated for everything else (layer state, lr, t, rng). The
        donation rule arrives from the ONE definition every step builder
        uses (`netbase._step_donate_argnums`, recorded on the net for the
        JX006 audit); donated in/out layouts match because the step body
        constrains its gradient (and hence its outputs) back to the
        parameter shardings."""
        jax = _jax()
        n_args = len(inspect.signature(step).parameters)
        data_sh = self.batch_stacked if stacked_data else self.batch
        in_shardings = []
        for i in range(n_args):
            if i == 0:
                in_shardings.append(self.tree_shardings(net.params_list))
            elif i == 2:
                in_shardings.append(self.tree_shardings(net.upd_state))
            elif i in data_argnums:
                in_shardings.append(data_sh)
            else:
                in_shardings.append(self.replicated)
        return jax.jit(step, in_shardings=tuple(in_shardings),
                       donate_argnums=donate_argnums)

    def grad_shardings(self, net):
        """Per-leaf shardings the step body constrains its gradients to
        (`with_sharding_constraint` right after value_and_grad): the
        parameter shardings. For replicated dp params this pins the
        cross-device psum/mean INSIDE the program at the grad site —
        the in-graph all-reduce; tp-sharded params keep their sharded
        gradients (no gather)."""
        return self.tree_shardings(net.params_list)

    # -- collective accounting ----------------------------------------------

    def grad_payload_bytes(self, net) -> int:
        """Logical all-reduce payload of ONE optimizer step: the summed
        gradient leaf bytes (== parameter bytes). Cached — shapes are
        static for a fit."""
        if self._payload_bytes is None:
            jax = _jax()
            total = 0
            for leaf in jax.tree_util.tree_leaves(net.params_list):
                nb = getattr(leaf, "nbytes", None)
                if nb:
                    total += int(nb)
            self._payload_bytes = total
        return self._payload_bytes

    def collective_seconds_estimate(self, net) -> float:
        """Cost-model ESTIMATE of one step's gradient all-reduce time:
        ring all-reduce moves 2(n-1)/n of the payload over each chip's
        ICI links (`flops.ici_bandwidth_per_chip`). An estimate, not a
        measurement — labeled as such on the metric; the roofline's
        honesty discipline (every published number names its source)."""
        n = self.n_data_shards
        if n <= 1:
            return 0.0
        from deeplearning4j_tpu.utils.flops import ici_bandwidth_per_chip

        wire = 2.0 * (n - 1) / n * self.grad_payload_bytes(net)
        return wire / ici_bandwidth_per_chip()

    def describe(self) -> dict:
        return {
            "devices": int(self.mesh.devices.size),
            "axes": {name: int(self.mesh.shape[name])
                     for name in self.mesh.axis_names},
            "data_shards": self.n_data_shards,
        }
