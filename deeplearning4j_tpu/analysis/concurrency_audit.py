"""Concurrency audit — merged static + runtime lock-discipline findings.

The two halves of lock checking live in different modules on purpose:

- utils/locktrace is the RUNTIME sanitizer (lockdep-style): armed via
  ``DL4J_LOCKCHECK=1`` it witnesses real acquisition orders, blocking
  calls under held locks, and jitted dispatches entered with a lock
  held, with bounded stack witnesses.
- analysis/lint is the LEXICAL pass: `with lock:` nesting plus the
  acquire()/release() call form, no execution needed.

This module is where they meet. ``report()`` joins the two lock-order
graphs — runtime lock classes are keyed by construction site
(``path.py:123``) and the linter records which lexical lock key
(``Class.attr``) each ``threading.Lock()`` assignment site constructs,
so edges witnessed both ways collapse onto one node and carry an
``origin`` label: ``static`` (lexically provable, never yet executed),
``runtime`` (witnessed under load, lexically invisible — e.g. locks
taken through helper indirection), or ``both``. Cycles in the MERGED
graph become CN001 errors naming every edge's origin and witness;
runtime blocking-under-lock records become CN002 and dispatch-under-
lock CN003 warnings (the lexical pass emits its own CN002/CN003 for
what it can see without running — same codes, same baseline).

Gate: ``--smoke`` runs a dedicated serving + decode + sparse/paramserver
exercise with the sanitizer armed, then diffs ALL CN finding names
against ``scripts/lock_baseline.txt`` (the lint.sh/tier-1 name-diff
pattern: the gate starts green on a committed — ideally empty —
baseline and only regressions fail). Wired into scripts/t1.sh as the
``T1 LOCK AUDIT:`` line.

Run: python -m deeplearning4j_tpu.analysis.concurrency_audit
       [--smoke] [--json -] [--names-out PATH] [--baseline FILE]
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import Dict, List, Tuple

from deeplearning4j_tpu.analysis.findings import (
    ERROR,
    WARNING,
    Finding,
    format_findings,
    summarize,
)
from deeplearning4j_tpu.utils import locktrace

logger = logging.getLogger("deeplearning4j_tpu")

_EMPTY_SNAP = {"enabled": False, "locks": {}, "edges": [], "blocking": [],
               "dispatch": []}


def _static(paths=None, base_dir=None):
    from deeplearning4j_tpu.analysis import lint

    findings, edges, ctor_sites = lint.collect(
        paths or lint.DEFAULT_TARGETS, base_dir)
    cn = [f for f in findings if f.code.startswith("CN")]
    return cn, edges, ctor_sites


def merged_edges(static_edges: Dict[Tuple[str, str], str], snap: dict,
                 ctor_sites: Dict[str, str]) -> Dict[Tuple[str, str], dict]:
    """One edge map over both graphs. Runtime construction sites that
    the linter attributed to a lexical lock key are renamed to that key
    so the same lock is ONE node regardless of which half saw it."""
    out: Dict[Tuple[str, str], dict] = {}
    for (a, b), loc in static_edges.items():
        out[(a, b)] = {"src": a, "dst": b, "origin": "static",
                       "location": loc}
    for e in snap.get("edges", []):
        a = ctor_sites.get(e["src"], e["src"])
        b = ctor_sites.get(e["dst"], e["dst"])
        rec = out.get((a, b))
        if rec is None:
            out[(a, b)] = {"src": a, "dst": b, "origin": "runtime",
                           "count": e["count"], "thread": e["thread"],
                           "witness": e["witness"]}
        else:
            rec["origin"] = "both"
            rec["count"] = rec.get("count", 0) + e["count"]
            rec.setdefault("thread", e["thread"])
            rec.setdefault("witness", e["witness"])
    return out


def report(runtime: bool = True, paths=None, base_dir=None) -> dict:
    """The audit: static CN findings + runtime CN findings + CN001
    cycles over the merged lock-order graph."""
    static_cn, static_edges, ctor_sites = _static(paths, base_dir)
    snap = locktrace.snapshot() if runtime else dict(_EMPTY_SNAP)
    edges = merged_edges(static_edges, snap, ctor_sites)
    findings: List[Finding] = list(static_cn)

    from deeplearning4j_tpu.analysis.lint import _find_cycles

    loc_map = {k: (v.get("location")
                   or (v.get("witness") or ["<runtime>"])[0])
               for k, v in edges.items()}
    for cycle, loc in _find_cycles(loc_map):
        detail = []
        for a, b in zip(cycle, cycle[1:]):
            rec = edges.get((a, b))
            if rec is None:
                continue
            d = f"{a} -> {b} [{rec['origin']}]"
            t = rec.get("thread")
            if t:
                d += f" (thread {t})"
            w = rec.get("witness")
            if w:
                d += " witness: " + " <- ".join(w[:4])
            detail.append(d)
        findings.append(Finding(
            "CN001", ERROR, loc,
            "lock-order cycle: " + " -> ".join(cycle) + " || "
            + " || ".join(detail),
            "pick one global acquisition order for these locks and "
            "stick to it on every path",
            name="CN001:" + "->".join(sorted(set(cycle)))))

    for b in snap.get("blocking", []):
        rel = b["site"].rsplit(":", 1)[0]
        msg = (f"{b['kind']} while holding {', '.join(b['held'])} "
               f"(x{b['count']}, thread {b['thread']})")
        if b.get("witness"):
            msg += " witness: " + " <- ".join(b["witness"][:4])
        findings.append(Finding(
            "CN002", WARNING, b["site"], msg,
            "snapshot state under the lock, release, THEN block — or "
            "baseline the name in scripts/lock_baseline.txt with a "
            "comment saying why it is safe",
            name=f"CN002:{b['kind']}:{rel}:{b['func']}"))

    for d in snap.get("dispatch", []):
        rel = d["site"].rsplit(":", 1)[0]
        findings.append(Finding(
            "CN003", WARNING, d["site"],
            f"jitted dispatch '{d['what']}' entered while holding "
            f"{', '.join(d['held'])} (x{d['count']}, thread "
            f"{d['thread']})",
            "stage inputs under the lock, dispatch outside it",
            name=f"CN003:{d['what']}:{rel}:{d['func']}"))

    return {
        "runtime": bool(snap.get("enabled", False)),
        "lock_classes": snap.get("locks", {}),
        "edges": sorted((dict(v) for v in edges.values()),
                        key=lambda e: (e["src"], e["dst"])),
        "findings": findings,
        "summary": summarize(findings),
    }


def finding_names(doc: dict) -> List[str]:
    """ALL CN finding names (errors AND warnings): unlike lint.sh the
    lock gate diffs the complete vocabulary — a new blocking-under-lock
    warning is exactly the regression this gate exists to catch."""
    return sorted({f.name for f in doc["findings"]})


def smoke() -> dict:
    """Dedicated sanitizer exercise for the T1 LOCK AUDIT gate: the
    three lock-heaviest tiers — serving (ParallelInference admission +
    dispatch), decode (continuous batching through a weight swap), and
    the sparse/paramserver pipeline (prefetch + coherence + drains) —
    run in-process with the sanitizer armed so their real acquisition
    orders land in one merged graph."""
    import numpy as np

    locktrace.install()
    results = {}

    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import (
        ParallelInference,
        data_parallel_mesh,
    )

    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Updater.SGD).learning_rate(0.05)
            .weight_init("xavier").list()
            .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=8)
    try:
        rng = np.random.default_rng(0)
        for i in range(6):
            pi.output(rng.standard_normal(
                (1 + i % 4, 12)).astype(np.float32))
        results["serving_requests"] = 6
    finally:
        pi.shutdown()

    from deeplearning4j_tpu.serving import decode as _decode

    results["decode_ok"] = bool(_decode.smoke(requests=6)["ok"])

    from deeplearning4j_tpu.parallel import sparse as _sparse

    sv = _sparse.smoke()
    if not sv["ok"]:
        raise AssertionError(f"sparse smoke violated under lockcheck: {sv}")
    results["sparse_ok"] = True
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.analysis.concurrency_audit",
        description="merged static+runtime lock-discipline audit "
                    "(CN001-CN003)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs for the static half (default: the "
                         "repo targets)")
    ap.add_argument("--smoke", action="store_true",
                    help="arm the sanitizer and run the serving + decode "
                         "+ sparse exercise before reporting (the "
                         "T1 LOCK AUDIT gate)")
    ap.add_argument("--json", dest="json_out", default=None, metavar="PATH",
                    help="write the full report as JSON ('-' = stdout)")
    ap.add_argument("--names-out", default=None, metavar="PATH",
                    help="write sorted CN finding names (one per line) — "
                         "the artifact the gate diffs against the "
                         "baseline")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="suppress findings whose names appear in this "
                         "file; exit 1 only on new ones")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.smoke:
        from deeplearning4j_tpu import configure_logging

        if all(isinstance(h, logging.NullHandler)
               for h in logger.handlers):
            configure_logging()
        results = smoke()
        logger.info("lock-audit smoke: %s", json.dumps(results))

    doc = report(runtime=True, paths=args.paths or None)
    names = finding_names(doc)

    if args.names_out:
        with open(args.names_out, "w") as f:
            f.write("".join(n + "\n" for n in names))
    serializable = dict(doc)
    serializable["findings"] = [f.to_dict() for f in doc["findings"]]
    if args.json_out == "-":
        print(json.dumps(serializable, indent=2))
    elif args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(serializable, f, indent=2)
        print(f"wrote {args.json_out}")
    elif not args.quiet:
        print(format_findings(doc["findings"]))
    if args.json_out != "-":  # keep stdout parseable under --json -
        print(f"lock audit: {len(doc['edges'])} order edges "
              f"({sum(1 for e in doc['edges'] if e['origin'] != 'static')} "
              f"runtime-witnessed), {len(names)} CN findings, "
              f"runtime={'armed' if doc['runtime'] else 'off'}")

    if args.baseline:
        try:
            with open(args.baseline) as f:
                allowed = {ln.strip() for ln in f
                           if ln.strip() and not ln.startswith("#")}
        except OSError as e:
            print(f"concurrency_audit: cannot read baseline "
                  f"{args.baseline}: {e}", file=sys.stderr)
            return 2
        new = [n for n in names if n not in allowed]
        if new:
            print(f"LOCK AUDIT REGRESSIONS — CN findings not in "
                  f"{args.baseline}:", file=sys.stderr)
            for n in new:
                print(f"  {n}", file=sys.stderr)
            return 1
        return 0
    return 1 if any(f.severity == ERROR for f in doc["findings"]) else 0


if __name__ == "__main__":
    # `python -m` runs a second copy of this module as __main__; keep
    # all state in the canonical import so snapshot() sees one world
    from deeplearning4j_tpu.analysis import concurrency_audit as _canonical

    sys.exit(_canonical.main())
