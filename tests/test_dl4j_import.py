"""DL4J model-zip import (modelimport/dl4j.py).

Round-trip strategy (the reference's own regressiontest/ approach needs
release-era zip artifacts; none ship in-tree): export writes the exact
reference layouts — f-order flat views per nn/params/*, IFOG gate order
with DL4J's candidate/input-gate block semantics, Graves peephole columns
— and import must reconstruct a network whose forward output matches the
original to float precision. A hand-built coefficients buffer additionally
pins the gate permutation itself (not just invertibility).
"""

import io
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.dl4j import (
    export_dl4j_zip,
    import_dl4j_multilayer,
    read_nd4j_array,
    write_nd4j_array,
    _perm_ifog,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    DenseLayer,
    GravesLSTM,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
    ConvolutionLayer,
)
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_nd4j_binary_round_trip():
    rng = np.random.default_rng(0)
    for arr in (rng.standard_normal(17).astype(np.float32),
                rng.standard_normal((3, 5)).astype(np.float64)):
        buf = io.BytesIO()
        write_nd4j_array(arr, buf)
        buf.seek(0)
        back = read_nd4j_array(buf)
        np.testing.assert_array_equal(back.reshape(-1), arr.reshape(-1))


def test_perm_ifog_blocks():
    """DL4J [I,F,O,G] -> framework [i,f,g,o] means [G,F,I,O]."""
    H = 2
    cols = np.array([[10, 11, 20, 21, 30, 31, 40, 41]], np.float32)
    out = _perm_ifog(cols, H)
    np.testing.assert_array_equal(
        out[0], [40, 41, 20, 21, 10, 11, 30, 31])


def _mlp_net(seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=9, activation="tanh"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def test_mlp_zip_round_trip(tmp_path):
    net = _mlp_net()
    # give BN non-trivial running stats
    x = np.random.default_rng(0).standard_normal((32, 6)).astype(np.float32)
    y = np.zeros((32, 4), np.float32)
    y[np.arange(32), np.random.default_rng(1).integers(0, 4, 32)] = 1.0
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)

    path = str(tmp_path / "mlp.zip")
    export_dl4j_zip(net, path)
    back = import_dl4j_multilayer(path)
    np.testing.assert_allclose(
        np.asarray(back.output(x)), np.asarray(net.output(x)),
        rtol=1e-5, atol=1e-6)


def test_graves_lstm_zip_round_trip_golden_forward(tmp_path):
    """The headline case (VERDICT missing #6): gate permutation + peephole
    column mapping proven by forward equality on a Graves LSTM."""
    conf = (NeuralNetConfiguration.builder().seed(11)
            .weight_init("xavier").list()
            .layer(GravesLSTM(n_out=7, activation="tanh"))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(5)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(2).standard_normal((4, 10, 5)).astype(np.float32)
    golden = np.asarray(net.output(x))

    path = str(tmp_path / "graves.zip")
    export_dl4j_zip(net, path)
    back = import_dl4j_multilayer(path)
    np.testing.assert_allclose(np.asarray(back.output(x)), golden,
                               rtol=1e-5, atol=1e-6)
    # peephole vectors landed in the right slots
    for k in ("pI", "pF", "pO"):
        np.testing.assert_allclose(np.asarray(back.params_list[0][k]),
                                   np.asarray(net.params_list[0][k]),
                                   rtol=1e-6)


def test_vanilla_lstm_zip_round_trip(tmp_path):
    conf = (NeuralNetConfiguration.builder().seed(3)
            .weight_init("xavier").list()
            .layer(LSTM(n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(4)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(4).standard_normal((3, 8, 4)).astype(np.float32)
    path = str(tmp_path / "lstm.zip")
    export_dl4j_zip(net, path)
    back = import_dl4j_multilayer(path)
    np.testing.assert_allclose(np.asarray(back.output(x)),
                               np.asarray(net.output(x)),
                               rtol=1e-5, atol=1e-6)


def test_length_mismatch_detected(tmp_path):
    net = _mlp_net()
    path = str(tmp_path / "bad.zip")
    export_dl4j_zip(net, path)
    import zipfile, json

    with zipfile.ZipFile(path) as zf:
        conf = zf.read("configuration.json")
        coeff = zf.read("coefficients.bin")
    # truncate the flat buffer: drop the final 4 bytes (one float)
    buf = io.BytesIO(coeff)
    arr = read_nd4j_array(buf)
    short = np.asarray(arr).reshape(-1)[:-1]
    out = io.BytesIO()
    write_nd4j_array(short, out)
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("configuration.json", conf)
        zf.writestr("coefficients.bin", out.getvalue())
    with pytest.raises(ValueError, match="too short|mismatch"):
        import_dl4j_multilayer(path)
