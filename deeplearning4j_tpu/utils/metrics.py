"""Process-global metrics registry — the one place every layer's
counters live (Prometheus-style pull model; Dapper-paper sibling
utils/tracing.py covers the span side).

Before this module the framework's telemetry was fragmented: serving
kept private dicts (parallel/inference.py `_stats`), training throughput
lived in listeners, per-net `output_compile_count` was an attribute you
had to know about, and the Helper SPI's auto-disable events existed only
as log lines. Here everything funnels into one thread-safe
MetricsRegistry so a single scrape — `InferenceServer GET
/metrics?format=prometheus`, `cli metrics`, or a bench snapshot — sees
training-side (`fit_step_*`, `compile_total`, `helper_*`) and
serving-side (`serving_*`) series from the same process.

Model (deliberately the Prometheus one, minus the client_library
dependency this container doesn't have):

* a metric NAME identifies a *family* (`Counter`, `Gauge`, `Histogram`)
  with fixed label names; `family.labels("a", "b")` returns the child
  for one label-value tuple (cached — hot paths hold the child, never
  re-look-up the family).
* `Counter.inc()`, `Gauge.set()/set_function()`, `Histogram.observe()`
  are the only write paths; all are lock-protected and safe from any
  thread (serving worker threads, the PS drain thread, SIGTERM
  checkpoint saves).
* `registry.snapshot()` is the JSON view (strictly finite numbers —
  utils/jsonhttp refuses NaN); `registry.to_prometheus()` is the text
  exposition (label escaping, `_total` counter suffix, cumulative
  `_bucket{le=...}` histograms).

Histograms use fixed log-scale buckets (seconds-oriented by default:
100 µs .. 100 s) for the exposition plus a bounded window of raw
observations for p50/p99 readout, reusing utils/latency.py's
nearest-rank percentile — the same numbers an operator already gets
from LatencyTracker, now for every timed phase in the framework.

Exemplars (the Prometheus/OpenMetrics idea, JSON-surfaced): when a
histogram observation lands while a trace is active (utils/tracing), and
it is a new maximum for its bucket — or the bucket's stored exemplar has
gone stale (older than _EXEMPLAR_MAX_AGE) — the (value, trace_id) pair
is kept, bounded at one exemplar per bucket, so a p99 outlier in a
latency histogram links back to a concrete trace an operator can pull
apart with `cli trace`. The staleness refresh matters: the span ring the
trace_id resolves against is bounded, so an all-time bucket maximum
would eventually advertise a trace no export can produce — a recent
slightly-smaller observation beats a permanently unresolvable record.
Exposed through `snapshot()` (and therefore the inference server's
`GET /metrics`); the 0.0.4 text exposition stays exemplar-free
(exemplars are OpenMetrics syntax — emitting them there would break
strict 0.0.4 parsers).
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.utils import tenancy as _tenancy
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.utils.latency import percentile

# default log-scale bucket bounds (seconds): 1e-4 .. 1e2 at 1/2.5/5 per
# decade — wide enough for a 100 µs dispatch and a 90 s checkpoint save
DEFAULT_BUCKETS = tuple(
    m * 10.0 ** e for e in range(-4, 3) for m in (1.0, 2.5, 5.0)
)

# a bucket exemplar older than this is replaced by the NEXT traced
# observation in that bucket even when smaller: its trace has likely
# aged out of the bounded span ring, and a resolvable recent trace
# beats an unresolvable all-time maximum
_EXEMPLAR_MAX_AGE = 60.0


def _check_labels(values: Sequence[str], names: Tuple[str, ...]):
    if len(values) != len(names):
        raise ValueError(
            f"expected {len(names)} label values for {names}, "
            f"got {len(values)}")
    return tuple(str(v) for v in values)


def escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    """Exposition value formatting: integral floats without the trailing
    .0 noise (Prometheus accepts either; diffs read better)."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Child:
    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class GaugeChild(_Child):
    __slots__ = ("_value", "_fn")

    def __init__(self):
        super().__init__()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float):
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]):
        """Evaluate `fn` at read time (queue depths and other
        point-in-time facts — no hot-path writes at all)."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:  # a dead callback must not kill a scrape
            return float("nan")


class HistogramChild(_Child):
    __slots__ = ("_bounds", "_counts", "_count", "_sum", "_window",
                 "_exemplars")

    def __init__(self, bounds: Tuple[float, ...], window: int = 2048):
        super().__init__()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self._count = 0
        self._sum = 0.0
        self._window = deque(maxlen=window)
        # bucket index -> (value, trace_id, ts, tenant): the bucket's
        # max-value exemplar — bounded at len(bounds)+1 entries by
        # construction. `tenant` is the thread-ambient identity
        # (utils/tenancy) at observe time, None when nobody attached one.
        self._exemplars: Dict[int, Tuple[float, str, float,
                                         Optional[str]]] = {}

    def observe(self, value: float, trace_id: Optional[str] = None,
                tenant: Optional[str] = None):
        """Record one observation. `trace_id` links it to a trace for
        exemplar capture; when omitted, the active trace (utils/tracing)
        is used — one flag check when tracing is off, so the hot paths
        that observe with tracing disabled pay nothing. `tenant`
        overrides the thread-ambient identity for exemplar tagging —
        engine loops observing on a shared worker thread (no ambient
        tenant) pass the request's own."""
        v = float(value)
        i = bisect.bisect_left(self._bounds, v)
        if trace_id is None and _tracing.is_enabled():
            trace_id = _tracing.current_trace_id()
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            self._window.append(v)
            if trace_id is not None:
                now = round(time.time(), 3)
                ex = self._exemplars.get(i)
                if ex is None or v > ex[0] \
                        or now - ex[2] > _EXEMPLAR_MAX_AGE:
                    if tenant is None:
                        tenant = _tenancy.current_tenant()
                    self._exemplars[i] = (v, trace_id, now, tenant)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile over the recent-observation window
        (latency.py semantics); None when nothing was observed."""
        with self._lock:
            vals = sorted(self._window)
        return percentile(vals, q) if vals else None

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count)] including (+Inf, count)."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for bound, c in zip(self._bounds, counts):
            acc += c
            out.append((bound, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def exemplars(self) -> List[dict]:
        """Per-bucket max-value exemplars, smallest bucket first — each
        links a concrete observation to the trace that produced it.
        JSON-safe: the +Inf bound renders as the string "+Inf"."""
        with self._lock:
            items = sorted(self._exemplars.items())
        bounds = self._bounds
        out = []
        for i, (v, trace_id, ts, tenant) in items:
            le = bounds[i] if i < len(bounds) else float("inf")
            ex = {"le": "+Inf" if math.isinf(le) else le,
                  "value": v, "trace_id": trace_id, "ts": ts}
            if tenant is not None:
                ex["tenant"] = tenant
            out.append(ex)
        return out


_KINDS = {"counter": CounterChild, "gauge": GaugeChild,
          "histogram": HistogramChild}


class MetricFamily:
    """One named metric + its labeled children. Constructed only via the
    registry's counter()/gauge()/histogram() get-or-create methods."""

    def __init__(self, name: str, kind: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None,
                 window: int = 2048):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._buckets = (tuple(sorted(buckets)) if buckets is not None
                         else DEFAULT_BUCKETS)
        self._window = window
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(kv[n] for n in self.labelnames)
        key = _check_labels(values, self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.kind == "histogram":
                    child = HistogramChild(self._buckets, self._window)
                else:
                    child = _KINDS[self.kind]()
                self._children[key] = child
        return child

    # label-less families proxy the single () child so call sites read
    # `reg.counter("fit_step_total").inc()` without a labels() hop
    def inc(self, amount: float = 1.0):
        self.labels().inc(amount)

    def set(self, value: float):
        self.labels().set(value)

    def dec(self, amount: float = 1.0):
        self.labels().dec(amount)

    def set_function(self, fn: Callable[[], float]):
        self.labels().set_function(fn)

    def observe(self, value: float, trace_id: Optional[str] = None,
                tenant: Optional[str] = None):
        self.labels().observe(value, trace_id, tenant)

    @property
    def value(self):
        return self.labels().value

    @property
    def count(self):
        return self.labels().count

    def percentile(self, q: float):
        return self.labels().percentile(q)

    def children(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Thread-safe name -> MetricFamily map with get-or-create
    registration (re-registering with the same type returns the existing
    family, so modules can resolve their instruments independently)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}

    def _get_or_create(self, name: str, kind: str, help: str,
                       labelnames: Sequence[str],
                       buckets: Optional[Sequence[float]] = None,
                       window: int = 2048) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind}, "
                        f"not {kind}")
                if tuple(labelnames) != fam.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{fam.labelnames}, not {tuple(labelnames)}")
                if (buckets is not None
                        and tuple(sorted(buckets)) != fam._buckets):
                    # an EXPLICIT bucket set that silently lands in the
                    # first registrant's bounds is wrong exposition;
                    # omitting buckets means "whatever exists" (the
                    # percentile window is first-registrant-wins)
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam._buckets}, not "
                        f"{tuple(sorted(buckets))}")
                return fam
            fam = MetricFamily(name, kind, help, labelnames, buckets, window)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> MetricFamily:
        return self._get_or_create(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> MetricFamily:
        return self._get_or_create(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None,
                  window: int = 2048) -> MetricFamily:
        return self._get_or_create(name, "histogram", help, labelnames,
                                   buckets, window)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._families.get(name)

    def unregister(self, name: str):
        with self._lock:
            self._families.pop(name, None)

    def reset(self):
        """Drop every family (tests). Live code that cached children keeps
        incrementing them, but they no longer appear in snapshots — so
        production code never calls this."""
        with self._lock:
            self._families.clear()

    # -- readout -------------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-safe dict view: {name: {"type", "help", "values": [...]}}.
        All numbers are finite or None (percentiles of an empty window) —
        json.dumps(..., allow_nan=False) always succeeds."""
        with self._lock:
            fams = list(self._families.values())
        out = {}
        for fam in sorted(fams, key=lambda f: f.name):
            values = []
            for key, child in sorted(fam.children()):
                labels = dict(zip(fam.labelnames, key))
                if fam.kind == "histogram":
                    count = child.count
                    values.append({
                        "labels": labels,
                        "count": count,
                        "sum": round(child.sum, 9),
                        "p50": child.percentile(50),
                        "p99": child.percentile(99),
                        "buckets": [
                            ["+Inf" if math.isinf(le) else le, c]
                            for le, c in child.cumulative_buckets()
                        ],
                        "exemplars": child.exemplars(),
                    })
                else:
                    v = child.value
                    values.append({
                        "labels": labels,
                        "value": None if (isinstance(v, float)
                                          and not math.isfinite(v)) else v,
                    })
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": values}
        return out

    def scalar_values(self, include_buckets: bool = False) -> Dict[str, float]:
        """Flat {series: value} view of every family — counters/gauges by
        value, histograms by `:count`/`:sum` — with labels rendered into
        the key. Deliberately cheap (no percentile sorting; no bucket
        walk by default): the flight recorder captures deltas of this on
        the fit hot path, and `cli metrics --watch` diffs it per tick.

        `include_buckets=True` additionally emits each histogram's
        cumulative bucket counts as `name{labels}:bucket:<le>` series —
        the run ledger samples with this on so offline SLO burn-rate
        rules (analysis/slo) can recover "requests under threshold"
        from a recorded artifact. The flight recorder and the watch
        loop stay on the cheap default."""
        with self._lock:
            fams = list(self._families.values())
        out: Dict[str, float] = {}
        for fam in fams:
            for key, child in fam.children():
                lab = ""
                if key:
                    pairs = ",".join(
                        f'{n}="{escape_label_value(v)}"'
                        for n, v in zip(fam.labelnames, key))
                    lab = "{" + pairs + "}"
                if fam.kind == "histogram":
                    out[f"{fam.name}{lab}:count"] = float(child.count)
                    out[f"{fam.name}{lab}:sum"] = float(child.sum)
                    if include_buckets:
                        for le, c in child.cumulative_buckets():
                            out[f"{fam.name}{lab}:bucket:{_fmt(le)}"] = \
                                float(c)
                else:
                    v = float(child.value)
                    if math.isfinite(v):
                        out[f"{fam.name}{lab}"] = v
        return out

    def to_prometheus(self) -> str:
        """Text exposition (format 0.0.4). Counters are suffixed `_total`
        when the registered name doesn't already end that way; histograms
        expand to `_bucket{le=...}` / `_sum` / `_count`."""
        with self._lock:
            fams = list(self._families.values())
        lines: List[str] = []
        for fam in sorted(fams, key=lambda f: f.name):
            name = fam.name
            if fam.kind == "counter" and not name.endswith("_total"):
                name += "_total"
            if fam.help:
                lines.append(f"# HELP {name} "
                             + fam.help.replace("\\", "\\\\")
                                       .replace("\n", "\\n"))
            lines.append(f"# TYPE {name} {fam.kind}")
            for key, child in sorted(fam.children()):
                pairs = [f'{n}="{escape_label_value(v)}"'
                         for n, v in zip(fam.labelnames, key)]
                base_lab = ",".join(pairs)
                if fam.kind == "histogram":
                    for le, c in child.cumulative_buckets():
                        lab = base_lab + ("," if base_lab else "") \
                            + f'le="{_fmt(le)}"'
                        lines.append(f"{name}_bucket{{{lab}}} {c}")
                    suffix = f"{{{base_lab}}}" if base_lab else ""
                    lines.append(f"{name}_sum{suffix} {_fmt(child.sum)}")
                    lines.append(f"{name}_count{suffix} {child.count}")
                else:
                    suffix = f"{{{base_lab}}}" if base_lab else ""
                    lines.append(f"{name}{suffix} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"


# -- the process-global registry ---------------------------------------------

_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY
