"""DataSet iterators.

Analog of the reference's iterator framework (datasets/iterator/):
DataSetIterator SPI, ListDataSetIterator, ExistingDataSetIterator,
MultipleEpochsIterator, and AsyncDataSetIterator — the background-prefetch
wrapper MultiLayerNetwork.fit installs automatically
(MultiLayerNetwork.java:1023-1025, prefetch threads feeding a bounded
queue). Here prefetch threads stage host batches while the TPU runs the
previous step, overlapping ETL with compute the same way.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet

# Every background thread the input pipeline spawns (async prefetch,
# multi-worker ETL, device prefetch, streaming pump) carries this name
# prefix so tests/conftest.py can assert none survive a fit — a leaked
# producer blocked on a full queue is a bug, not background noise.
PIPELINE_THREAD_PREFIX = "dl4j-pipeline"

# how often a blocked pipeline thread wakes to re-check its stop flag
_POLL_SECONDS = 0.05


def _put_abortable(q: "queue.Queue", item, stop: threading.Event) -> bool:
    """Bounded put that can be cancelled: never blocks longer than
    _POLL_SECONDS without re-checking `stop`. Returns False when the run
    was aborted (the consumer went away) — the producer must exit, not
    keep filling a queue nobody drains. This is the fix for the classic
    prefetch-thread leak: a consumer that breaks mid-epoch used to leave
    the producer blocked on `q.put` forever."""
    while not stop.is_set():
        try:
            q.put(item, timeout=_POLL_SECONDS)
            return True
        except queue.Full:
            continue
    return False


def _get_abortable(q: "queue.Queue", stop: threading.Event):
    """Consumer counterpart of `_put_abortable`: blocks for the next item
    but re-checks `stop` while the queue is empty, so a `close()` issued
    from another thread ends iteration instead of leaving the consumer
    blocked in `q.get()` forever (the producer cannot deliver its
    end-of-stream sentinel once stop is set). Returns None on abort."""
    while True:
        try:
            return q.get(timeout=_POLL_SECONDS)
        except queue.Empty:
            if stop.is_set():
                return None


def _close_run(q: "queue.Queue", stop: threading.Event,
               threads: List[threading.Thread], timeout: float = 5.0):
    """Tear down one epoch's pipeline machinery: signal stop, drain the
    queue so producers blocked in put() wake immediately instead of at
    the next poll, then join. Idempotent."""
    stop.set()
    try:
        while True:
            q.get_nowait()
    except queue.Empty:
        pass
    for t in threads:
        t.join(timeout=timeout)


class DataSetIterator:
    """SPI: iterable over DataSet minibatches with reset().

    Iterators that own background workers override `close()` (and get
    `with` support for free); for plain host iterators both are no-ops,
    so callers can close any DataSetIterator unconditionally.

    `state()`/`restore_state()` are the mid-epoch resume protocol
    (train/checkpoint.py): `state()` returns a small JSON-safe dict of
    whatever the iterator needs to REPRODUCE an epoch from its start
    (e.g. the shuffle-epoch counter — not a queue position; in-flight
    pipeline batches are replayed, not captured), and `restore_state()`
    primes a fresh iterator with it. The defaults declare the iterator
    stateless: each epoch is identical, so replay needs no priming.
    Pipeline wrappers delegate both to their base iterator."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch_size(self) -> Optional[int]:
        return None

    def total_examples(self) -> Optional[int]:
        return None

    def state(self) -> Optional[dict]:
        """JSON-safe epoch-reproduction state; None = stateless."""
        return None

    def restore_state(self, state: Optional[dict]) -> None:
        """Prime a fresh iterator with a `state()` capture. No-op for
        stateless iterators (and for a None capture)."""

    def close(self) -> None:
        """Release background workers/queues, if any. Safe to call more
        than once and on iterators that have none."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ListDataSetIterator(DataSetIterator):
    """Minibatches from in-memory arrays (reference:
    ListDataSetIterator / ExistingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch: int, shuffle: bool = False, seed: int = 0):
        self.dataset = dataset
        self.batch = batch
        self.shuffle = shuffle
        self._epoch = 0
        self.seed = seed

    def __iter__(self):
        n = self.dataset.num_examples()
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        self._epoch += 1
        d = self.dataset
        for i in range(0, n, self.batch):
            sl = idx[i : i + self.batch]
            yield DataSet(
                d.features[sl],
                d.labels[sl],
                None if d.features_mask is None else d.features_mask[sl],
                None if d.labels_mask is None else d.labels_mask[sl],
            )

    def reset(self):
        pass

    def batch_size(self):
        return self.batch

    def total_examples(self):
        return self.dataset.num_examples()

    def state(self):
        # the epoch counter seeds the shuffle permutation: restoring it
        # makes a fresh iterator deal out the SAME epoch order the
        # checkpointed run saw — the whole point of mid-epoch resume
        return {"epoch": int(self._epoch)}

    def restore_state(self, state):
        if state:
            self._epoch = int(state.get("epoch", 0))


class ExistingDataSetIterator(DataSetIterator):
    """Wraps any iterable of DataSets (reference: ExistingDataSetIterator)."""

    def __init__(self, datasets: Iterable[DataSet]):
        self._list: List[DataSet] = list(datasets)

    def __iter__(self):
        return iter(self._list)

    def total_examples(self):
        return sum(d.num_examples() for d in self._list)


class MultipleEpochsIterator(DataSetIterator):
    """Repeat an underlying iterator n times (reference:
    MultipleEpochsIterator.java)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self.epochs = epochs
        self.base = base

    def __iter__(self):
        for _ in range(self.epochs):
            self.base.reset()
            yield from self.base

    def batch_size(self):
        return self.base.batch_size()

    def state(self):
        return self.base.state()

    def restore_state(self, state):
        self.base.restore_state(state)


class MultiDataSetIterator:
    """SPI: iterable over MultiDataSet minibatches with reset()
    (reference: nd4j MultiDataSetIterator, consumed by
    ComputationGraph.fit)."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch_size(self) -> Optional[int]:
        return None

    def total_examples(self) -> Optional[int]:
        return None

    def state(self) -> Optional[dict]:
        return None

    def restore_state(self, state: Optional[dict]) -> None:
        pass


class StackedDataSetIterator(DataSetIterator):
    """Concatenate k consecutive minibatches into one global batch — how a
    data-parallel trainer turns per-worker batches into one sharded batch
    (reference: ParallelWrapper round-robin dispatch of one minibatch per
    DefaultTrainer, ParallelWrapper.java:389-404)."""

    def __init__(self, base: DataSetIterator, k: int):
        self.base = base
        self.k = max(1, int(k))

    def __iter__(self):
        pending: List[DataSet] = []
        for ds in self.base:
            pending.append(ds)
            if len(pending) == self.k:
                yield DataSet.concat(pending)
                pending = []
        if pending:
            yield DataSet.concat(pending)

    def reset(self):
        self.base.reset()

    def batch_size(self):
        b = self.base.batch_size()
        return None if b is None else b * self.k

    def total_examples(self):
        return self.base.total_examples()

    def state(self):
        return self.base.state()

    def restore_state(self, state):
        self.base.restore_state(state)


_SENTINEL = object()


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue (reference:
    AsyncDataSetIterator, queue capacity = prefetch buffer). The worker
    thread performs ETL while the accelerator computes; exceptions propagate
    to the consumer.

    Shutdown contract: breaking out of iteration mid-epoch (or an
    exception unwinding the consumer) closes the epoch's worker — the
    generator's `finally` signals stop, drains the queue, and joins the
    thread, so no producer is ever left blocked on a full queue. An
    explicit `close()` (or `with` block) tears down any still-live
    epochs; tests/conftest.py's thread-leak guard enforces this for every
    pipeline stage."""

    def __init__(self, base: DataSetIterator, queue_size: int = 4):
        self.base = base
        self.queue_size = max(1, queue_size)
        self._active: List[tuple] = []

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        stop = threading.Event()
        err: List[BaseException] = []

        def worker():
            try:
                for ds in self.base:
                    if not _put_abortable(q, ds, stop):
                        return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                _put_abortable(q, _SENTINEL, stop)

        t = threading.Thread(target=worker, daemon=True,
                             name=f"{PIPELINE_THREAD_PREFIX}-async")
        run = (q, stop, t)
        self._active.append(run)
        t.start()
        try:
            while True:
                item = _get_abortable(q, stop)
                if item is None or item is _SENTINEL:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            _close_run(q, stop, [t])
            if run in self._active:
                self._active.remove(run)

    def close(self):
        for q, stop, t in list(self._active):
            _close_run(q, stop, [t])
        self._active.clear()

    def reset(self):
        self.base.reset()

    def batch_size(self):
        return self.base.batch_size()

    def total_examples(self):
        return self.base.total_examples()

    def state(self):
        return self.base.state()

    def restore_state(self, state):
        self.base.restore_state(state)
