"""Observability-layer tests: the shared MetricsRegistry (thread safety,
histogram math vs numpy, Prometheus exposition format), the span tracer
(nesting, chrome-trace export, disabled-path cost), and the cross-layer
wiring — Helper SPI fallback counters (the PR 2 auto-disable regression),
fit-loop step-phase instruments with the zero-registry-lookups-per-step
overhead guard, and the inference server's strict-JSON /metrics plus the
one-scrape-sees-training-AND-serving Prometheus acceptance criterion."""

import json
import threading

import numpy as np
import pytest

import deeplearning4j_tpu as dl4j
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops import helpers
from deeplearning4j_tpu.utils import metrics as metrics_mod
from deeplearning4j_tpu.utils import tracing
from deeplearning4j_tpu.utils.jsonhttp import json_response
from deeplearning4j_tpu.utils.metrics import MetricsRegistry


@pytest.fixture(autouse=True)
def _tracing_off_after():
    """Tracing is process-global state; never leak an enabled tracer (or
    a dirty span buffer) into other tests."""
    yield
    tracing.enable(False)
    tracing.get_tracer().clear()


def _mlp_conf(seed=7, n_in=12):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.SGD)
        .learning_rate(0.05)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=n_in, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                           loss="mcxent"))
        .build()
    )


def _xy(n=32, n_in=12, n_out=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)]
    return x, y


# -- registry core -----------------------------------------------------------

def test_counter_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("hits_total", "x", ("who",))
    child = c.labels("a")

    def worker():
        for _ in range(1000):
            child.inc()
            c.labels("b").inc(2)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert child.value == 8000
    assert c.labels("b").value == 16000


def test_counter_is_monotonic_and_typed():
    reg = MetricsRegistry()
    c = reg.counter("ops_total")
    with pytest.raises(ValueError):
        c.inc(-1)
    # get-or-create returns the same family; kind conflicts are errors
    assert reg.counter("ops_total") is c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("ops_total")
    with pytest.raises(ValueError, match="labels"):
        reg.counter("ops_total", labelnames=("x",))


def test_gauge_set_function_and_dead_callback():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(3)
    assert g.value == 3
    g.set_function(lambda: 7)
    assert g.value == 7
    g.set_function(lambda: 1 / 0)  # a dying callback must not kill a scrape
    snap = reg.snapshot()
    assert snap["depth"]["values"][0]["value"] is None  # NaN -> null
    json.dumps(snap, allow_nan=False)


def test_histogram_percentiles_vs_numpy():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", window=10_000)
    rng = np.random.default_rng(42)
    vals = rng.lognormal(mean=-5, sigma=1.0, size=2000)
    for v in vals:
        h.observe(float(v))
    child = h.labels()
    assert child.count == 2000
    assert child.sum == pytest.approx(vals.sum(), rel=1e-9)
    # nearest-rank percentile over the full window vs numpy's
    for q in (50, 90, 99):
        got = child.percentile(q)
        lo, hi = np.percentile(vals, max(q - 1, 0)), np.percentile(
            vals, min(q + 1, 100))
        assert lo <= got <= hi


def test_histogram_bucket_counts_exact():
    reg = MetricsRegistry()
    h = reg.histogram("d_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.01, 0.05, 0.5, 5.0):
        h.observe(v)
    cum = h.labels().cumulative_buckets()
    # le semantics: 0.01 counts the exact-boundary observation
    assert cum == [(0.01, 2), (0.1, 3), (1.0, 4), (float("inf"), 5)]


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("requests", "served requests", ("route",)) \
        .labels('with"quote\\and\nnewline').inc(3)
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = reg.to_prometheus()
    # counters get the _total suffix when the name lacks it
    assert "# TYPE requests_total counter" in text
    assert 'requests_total{route="with\\"quote\\\\and\\nnewline"} 3' in text
    assert "# TYPE depth gauge" in text
    assert "depth 2" in text.splitlines()
    # histogram expansion: cumulative buckets incl +Inf, _sum, _count
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 2' in text
    assert "lat_seconds_count 2" in text.splitlines()
    assert any(line.startswith("lat_seconds_sum ")
               for line in text.splitlines())
    assert "# HELP requests_total served requests" in text


def test_snapshot_is_strict_json():
    reg = MetricsRegistry()
    reg.histogram("empty_seconds")  # family with no observations
    reg.histogram("one_seconds").observe(0.25)
    s = json.dumps(reg.snapshot(), allow_nan=False)
    doc = json.loads(s)
    one = doc["one_seconds"]["values"][0]
    assert one["count"] == 1 and one["p50"] == 0.25
    assert doc["empty_seconds"]["values"] == []


# -- tracing -----------------------------------------------------------------

def test_span_disabled_is_free_singleton():
    tracing.enable(False)
    s1, s2 = tracing.span("a"), tracing.span("b", k=1)
    assert s1 is s2 is tracing.NULL_SPAN
    with s1:
        pass
    tracing.instant("nope")
    assert tracing.get_tracer().recent() == []


def test_span_nesting_and_chrome_roundtrip(tmp_path):
    tracer = tracing.get_tracer()
    tracer.clear()
    tracing.enable(True)
    with tracing.span("outer", phase="x"):
        with tracing.span("inner"):
            pass
        tracing.instant("marker", it=3)
    evs = tracer.recent()
    tracing.enable(False)
    by_name = {e["name"]: e for e in evs}
    assert set(by_name) == {"outer", "inner", "marker"}
    # children close (and record) before the parent; parent ids link up
    assert by_name["inner"]["parent"] == by_name["outer"]["id"]
    assert by_name["marker"]["parent"] == by_name["outer"]["id"]
    assert by_name["outer"]["parent"] is None
    assert by_name["outer"]["dur"] >= by_name["inner"]["dur"] >= 0
    # chrome-trace export round-trips through strict JSON
    path = tmp_path / "trace.json"
    tracer.write_chrome_trace(str(path))
    doc = json.loads(path.read_text())
    names = [e["name"] for e in doc["traceEvents"]]
    assert set(names) == {"outer", "inner", "marker"}
    marker = next(e for e in doc["traceEvents"] if e["name"] == "marker")
    assert marker["ph"] == "i" and marker["args"]["it"] == 3
    # JSONL export: one strict-JSON object per line
    for line in tracer.to_jsonl().strip().splitlines():
        json.loads(line)


def test_tracing_listener_writes_artifacts(tmp_path):
    from deeplearning4j_tpu.train.listeners import TracingListener

    tracing.get_tracer().clear()
    net = MultiLayerNetwork(_mlp_conf()).init()
    jsonl = tmp_path / "spans.jsonl"
    chrome = tmp_path / "spans.chrome.json"
    lst = TracingListener(jsonl_path=str(jsonl), chrome_path=str(chrome))
    # construction must NOT flip the process-global flag (that would
    # impose the per-step device sync on every other net in the process)
    assert not tracing.is_enabled()
    net.set_listeners(lst)
    x, y = _xy(n=16)
    net.fit(x, y, epochs=2, batch_size=8, async_prefetch=False)
    assert not tracing.is_enabled()  # restored
    lines = [json.loads(l) for l in jsonl.read_text().strip().splitlines()]
    names = {e["name"] for e in lines}
    assert "fit/step" in names and "iteration" in names
    assert "fit/device_sync" in names  # tracing was on -> sync measured
    # restore_on_epoch_end must NOT leave later epochs untraced: all 4
    # steps (2 epochs x 2 batches) recorded spans
    assert sum(e["name"] == "fit/step" for e in lines) == 4
    iters = {e["args"]["iteration"] for e in lines
             if e["name"] == "iteration"}
    assert iters == {0, 1, 2, 3}
    doc = json.loads(chrome.read_text())
    assert any(e["name"] == "fit/step" for e in doc["traceEvents"])


def test_tracing_listener_restores_when_fit_raises():
    from deeplearning4j_tpu.train.listeners import TracingListener

    class _Boom:
        def __iter__(self):
            raise RuntimeError("iterator died")

        def reset(self):
            pass

    net = MultiLayerNetwork(_mlp_conf()).init()
    net.set_listeners(TracingListener())
    with pytest.raises(RuntimeError, match="iterator died"):
        net._run_fit(_Boom(), epochs=1, async_prefetch=False)
    # the finally-hook restored the process-global flag despite the raise
    assert not tracing.is_enabled()


def test_recent_rejects_nonpositive_and_histogram_bucket_conflict():
    tracer = tracing.Tracer()  # local tracer: no global state
    tracer.enabled = True
    for i in range(5):
        with tracer.span(f"s{i}"):
            pass
    assert len(tracer.recent()) == 5
    assert [e["name"] for e in tracer.recent(2)] == ["s3", "s4"]
    assert tracer.recent(0) == []
    assert tracer.recent(-3) == []  # must not invert into "all but newest"
    reg = MetricsRegistry()
    reg.histogram("x_seconds", buckets=(0.1, 1.0))
    reg.histogram("x_seconds")  # no explicit buckets: existing family ok
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("x_seconds", buckets=(0.001, 0.01))


# -- helper SPI counters (PR 2 auto-disable regression) ----------------------

def _counter_value(name, **labels):
    fam = metrics_mod.get_registry().get(name)
    if fam is None:
        return 0.0
    return fam.labels(**labels).value


def test_helper_fallback_counters_on_auto_disable():
    op = "metrics_test_op"

    def boom(*a, **k):
        raise RuntimeError("kernel exploded at trace time")

    helpers.register_helper(op, boom, name="boomer")
    try:
        # no family= at registration: the kernel-family label defaults
        # to the op name (bounded — one value per op)
        before_dis = _counter_value("helper_auto_disable_total",
                                    op=op, helper="boomer", family=op)
        before_raised = _counter_value("helper_fallback_total",
                                       op=op, helper="boomer", family=op,
                                       reason="raised")
        fn = helpers.get_helper(op)
        assert fn is not None
        assert _counter_value("helper_hit_total",
                              op=op, helper="boomer", family=op) >= 1
        with pytest.raises(helpers.HelperError):
            fn(1, 2)
        assert _counter_value("helper_auto_disable_total", op=op,
                              helper="boomer", family=op) == before_dis + 1
        assert _counter_value("helper_fallback_total", op=op,
                              helper="boomer", family=op,
                              reason="raised") == before_raised + 1
        # the helper is now disabled: the next lookup falls back, counted
        assert helpers.get_helper(op) is None
        assert _counter_value("helper_fallback_total", op=op,
                              helper="boomer", family=op,
                              reason="disabled") >= 1
    finally:
        helpers._HELPERS.pop(op, None)


def test_helper_unsupported_fallback_counted():
    op = "metrics_test_unsup"
    helpers.register_helper(op, lambda: None,
                            supported=lambda **ctx: False, name="picky")
    try:
        before = _counter_value("helper_fallback_total", op=op,
                                helper="picky", family=op,
                                reason="unsupported")
        assert helpers.get_helper(op) is None
        assert _counter_value("helper_fallback_total", op=op,
                              helper="picky", family=op,
                              reason="unsupported") == before + 1
    finally:
        helpers._HELPERS.pop(op, None)


def test_helper_counter_family_label_cardinality_bounded():
    """The kernel-family label on helper_* counters must stay bounded:
    one slug per kernel family, or the op name when the registration
    carries no family fn — never a per-shape or per-instance value
    (which would blow up the scrape cardinality)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops import pallas_conv_bn  # noqa: F401 (registers)

    # exercise several conv contexts so the conv family slugs materialize
    # on the fallback counters (CPU: everything falls back, labeled)
    for kernel, stride in (((1, 1), (1, 1)), ((3, 3), (1, 1)),
                           ((3, 3), (2, 2)), ((7, 7), (2, 2)),
                           ((5, 5), (1, 1))):
        helpers.get_helper(
            "conv2d", kernel=kernel, stride=stride, dilation=(1, 1),
            same=True, has_bias=False, activation="identity",
            dtype=jnp.float32, n_in=64, n_out=64,
            x_shape=(2, 8, 8, 64), training=True)

    allowed_slugs = {"conv1x1", "conv1x1s2", "conv3x3", "conv3x3s2",
                     "conv7x7s2", "conv_other", "bn_apply", "bn_bwd",
                     "lstm_seq", "lstm_step"}
    reg = metrics_mod.get_registry()
    seen = 0
    for name in ("helper_hit_total", "helper_fallback_total",
                 "helper_auto_disable_total"):
        fam = reg.get(name)
        if fam is None:
            continue
        assert "family" in fam.labelnames
        f_idx = fam.labelnames.index("family")
        op_idx = fam.labelnames.index("op")
        for key in list(fam._children):
            seen += 1
            fam_label, op_label = key[f_idx], key[op_idx]
            assert fam_label in allowed_slugs or fam_label == op_label, (
                f"{name}: unbounded family label {fam_label!r} "
                f"(op={op_label!r})")
    assert seen > 0  # the probes above must have produced labeled samples


# -- fit-loop wiring + overhead guard ----------------------------------------

def test_fit_step_metrics_recorded():
    reg = metrics_mod.get_registry()
    steps0 = _counter_value("fit_step_total")
    net = MultiLayerNetwork(_mlp_conf()).init()
    x, y = _xy(n=40)
    net.fit(x, y, epochs=2, batch_size=10, async_prefetch=False)
    assert _counter_value("fit_step_total") == steps0 + 8
    disp = reg.get("fit_dispatch_seconds").labels()
    wait = reg.get("fit_data_wait_seconds").labels()
    assert disp.count >= 8 and wait.count >= 8
    assert _counter_value("compile_total", kind="train_step") >= 1


def test_fit_hot_path_no_registry_lookups_when_disabled(monkeypatch):
    """The overhead guard, asserted structurally (iteration counts, not
    wall clock): with tracing disabled and no listeners, a fit's
    per-step path performs ZERO registry lookups (instruments resolve
    once) and ZERO device syncs beyond the dispatch itself (the sync
    histogram stays empty)."""
    assert not tracing.is_enabled()
    reg = metrics_mod.get_registry()
    lookups = []
    orig = MetricsRegistry._get_or_create

    def counting(self, name, *a, **k):
        lookups.append(name)
        return orig(self, name, *a, **k)

    net = MultiLayerNetwork(_mlp_conf()).init()
    sync_before = reg.histogram("fit_device_sync_seconds").labels().count
    x, y = _xy(n=200)
    monkeypatch.setattr(MetricsRegistry, "_get_or_create", counting)
    net.fit(x, y, epochs=1, batch_size=4, async_prefetch=False)  # 50 steps
    fit_lookups = [n for n in lookups if n.startswith("fit_")]
    # instruments resolved at most once each (6 families as of the
    # input-pipeline round: steps/examples/examples_unknown/data_wait/
    # dispatch/sync), NOT once per 50 steps
    assert len(fit_lookups) <= 6, fit_lookups
    # a second fit reuses the cached children: no new lookups at all
    lookups.clear()
    net.fit(x, y, epochs=1, batch_size=4, async_prefetch=False)
    assert [n for n in lookups if n.startswith("fit_")] == []
    # tracing disabled -> the device-sync probe never ran
    assert reg.histogram(
        "fit_device_sync_seconds").labels().count == sync_before


def test_performance_listener_reports_window_etl():
    from deeplearning4j_tpu.train.listeners import PerformanceListener

    out = []
    lst = PerformanceListener(frequency=3, print_fn=out.append)
    for i in range(7):
        lst.iteration_done(None, i, {"batch_size": 8, "etl_ms": 12.0})
    assert out, "listener never printed"
    # averaged over the window, not the last batch's value
    assert "etl 12.0 ms/iter" in out[0]


# -- satellites: logging + strict JSON ---------------------------------------

def test_library_logger_has_null_handler():
    import logging

    lg = logging.getLogger("deeplearning4j_tpu")
    assert any(isinstance(h, logging.NullHandler) for h in lg.handlers)


def test_configure_logging_json_lines(capsys):
    import io
    import logging

    buf = io.StringIO()
    lg = dl4j.configure_logging(level=logging.INFO, json_lines=True,
                                stream=buf)
    try:
        lg.info("hello %s", "world")
        rec = json.loads(buf.getvalue().strip().splitlines()[-1])
        assert rec["message"] == "hello world"
        assert rec["level"] == "INFO"
        assert rec["logger"] == "deeplearning4j_tpu"
        # reconfiguring replaces, not stacks, the handler
        buf2 = io.StringIO()
        lg = dl4j.configure_logging(json_lines=False, stream=buf2)
        assert sum(getattr(h, "_dl4j_tpu_configured", False)
                   for h in lg.handlers) == 1
    finally:
        for h in list(lg.handlers):
            if getattr(h, "_dl4j_tpu_configured", False):
                lg.removeHandler(h)


def test_json_response_replaces_non_finite():
    code, ctype, payload = json_response(
        {"p50": float("nan"), "p99": float("inf"), "ok": 1.5})
    doc = json.loads(
        payload.decode(),
        parse_constant=lambda c: pytest.fail(f"non-strict token {c}"))
    assert doc == {"p50": None, "p99": None, "ok": 1.5}


# -- inference server: strict JSON with zero traffic + shared scrape ---------

def _http_get(port, path):
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.read().decode()


def test_inference_server_metrics_strict_json_zero_traffic():
    from deeplearning4j_tpu.serving import InferenceServer

    net = MultiLayerNetwork(_mlp_conf()).init()
    server = InferenceServer(net, port=0)
    port = server.start()
    try:
        body = _http_get(port, "/metrics")
        doc = json.loads(
            body,
            parse_constant=lambda c: pytest.fail(
                f"non-strict JSON token {c} in /metrics with zero traffic"))
        assert doc["requests"] == 0
        assert doc["latency_ms"]["p50_ms"] is None
    finally:
        server.stop()


def test_prometheus_scrape_spans_training_and_serving():
    """Acceptance: ONE registry — a /metrics?format=prometheus scrape
    returns training-side (fit_step_*, helper_*, compile_total) and
    serving-side (bucket hits, request latency histogram) series from
    the same process."""
    from deeplearning4j_tpu.serving import InferenceServer

    # training side (same process)
    net = MultiLayerNetwork(_mlp_conf()).init()
    x, y = _xy(n=16)
    net.fit(x, y, epochs=1, batch_size=8, async_prefetch=False)
    # a helper event (any op) so helper_* series exist
    helpers.register_helper("scrape_demo", lambda v: v, name="demo")
    try:
        helpers.get_helper("scrape_demo")("ok")
    finally:
        helpers._HELPERS.pop("scrape_demo", None)

    serve_net = MultiLayerNetwork(_mlp_conf(seed=11)).init()
    server = InferenceServer(serve_net, port=0, max_batch_size=8)
    port = server.start()
    try:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps(
                {"features": np.zeros((3, 12)).tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert json.loads(r.read())["predictions"]
        text = _http_get(port, "/metrics?format=prometheus")
    finally:
        server.stop()
    for family in ("fit_step_total", "compile_total",
                   "helper_hit_total{helper=\"demo\"",
                   "serving_requests_total", "serving_bucket_hits_total",
                   "serving_request_seconds_bucket",
                   "serving_request_seconds_count", "serving_queue_depth"):
        assert family.split("{")[0] in text, f"{family} missing from scrape"
    # and the serving series actually moved
    assert "serving_requests_total " in text
    line = next(l for l in text.splitlines()
                if l.startswith("serving_requests_total"))
    assert float(line.split()[-1]) >= 1


def test_trace_route_serves_recent_spans():
    from deeplearning4j_tpu.serving import InferenceServer

    tracing.get_tracer().clear()
    tracing.enable(True)
    net = MultiLayerNetwork(_mlp_conf()).init()
    server = InferenceServer(net, port=0)
    port = server.start()
    try:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=json.dumps(
                {"features": np.zeros((2, 12)).tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            r.read()
        body = _http_get(port, "/trace")
        names = {json.loads(l)["name"]
                 for l in body.strip().splitlines() if l}
        assert "serve/predict" in names
        chrome = json.loads(_http_get(port, "/trace?format=chrome"))
        assert any(e["name"] == "serve/predict"
                   for e in chrome["traceEvents"])
    finally:
        tracing.enable(False)
        server.stop()


# -- checkpoint + paramserver wiring ----------------------------------------

def test_checkpoint_save_metrics(tmp_path):
    from deeplearning4j_tpu.train.checkpoint import CheckpointListener

    reg = metrics_mod.get_registry()
    before = 0.0
    fam = reg.get("checkpoint_saves_total")
    if fam is not None:
        before = fam.labels(reason="manual").value
    net = MultiLayerNetwork(_mlp_conf()).init()
    lst = CheckpointListener(str(tmp_path), every_n_epochs=None)
    assert lst.save(net, reason="manual") is not None
    assert reg.get("checkpoint_saves_total").labels(
        reason="manual").value == before + 1
    # the save histogram is phase-split: `snapshot` (fit-thread blocking
    # capture) and `write` (serialize + atomic rename)
    assert reg.get("checkpoint_save_seconds").labels("snapshot").count >= 1
    assert reg.get("checkpoint_save_seconds").labels("write").count >= 1


def test_paramserver_rpc_metrics():
    from deeplearning4j_tpu.parallel.paramserver import (
        EmbeddingParameterServer,
        EmbeddingPSClient,
    )

    reg = metrics_mod.get_registry()
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((10, 4), np.float32)})
    port = server.start()
    try:
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"])
        rows = np.array([1, 3])
        got = client.pull("syn0", rows)
        assert got.shape == (2, 4)
        client.push_async("syn0", rows, np.ones((2, 4), np.float32))
        client.flush()
        assert server.pushes_applied == 1
        assert reg.get("paramserver_rpc_total").labels(
            route="pull.bin").value >= 1
        assert reg.get("paramserver_rpc_total").labels(
            route="push.bin").value >= 1
        assert reg.get("paramserver_rpc_seconds").labels(
            route="pull.bin").count >= 1
        assert reg.get("paramserver_client_rpc_total").labels(
            route="pull.bin").value >= 1
    finally:
        server.stop()


# -- cli ---------------------------------------------------------------------

def test_cli_metrics_local_dump(tmp_path, capsys):
    from deeplearning4j_tpu.cli import main

    metrics_mod.get_registry().counter("cli_demo_total").inc(5)
    assert main(["metrics"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["cli_demo_total"]["values"][0]["value"] == 5
    out = tmp_path / "m.prom"
    assert main(["metrics", "--format", "prometheus",
                 "--output", str(out)]) == 0
    assert "cli_demo_total 5" in out.read_text().splitlines()


# -- exposition edge cases (blackbox/health PR satellites) --------------------

def test_exposition_gauge_family_with_zero_samples():
    """A registered family whose labels() was never called must still
    expose a well-formed TYPE (and HELP) block with no sample lines —
    and survive the snapshot path. component_health before the first
    watchdog transition is the live trigger for this shape."""
    reg = MetricsRegistry()
    reg.gauge("empty_gauge", "no children yet", ("component",))
    text = reg.to_prometheus()
    assert "# TYPE empty_gauge gauge" in text
    assert "# HELP empty_gauge no children yet" in text
    assert not [l for l in text.splitlines()
                if l.startswith("empty_gauge") and not l.startswith("#")]
    snap = reg.snapshot()
    assert snap["empty_gauge"]["values"] == []
    # strict-JSON safe even with zero samples
    json.dumps(snap, allow_nan=False)


def test_exposition_label_escaping_roundtrip():
    """Label values with backslashes, quotes, and newlines survive the
    text exposition and parse back to the original strings."""
    import re

    reg = MetricsRegistry()
    nasty = 'a\\b"c\nd'
    reg.counter("esc_total", "", ("component",)).labels(nasty).inc(3)
    text = reg.to_prometheus()
    line = [l for l in text.splitlines() if l.startswith("esc_total{")][0]
    assert "\n" not in line  # the newline was escaped, not emitted
    m = re.match(r'esc_total\{component="((?:[^"\\]|\\.)*)"\} 3', line)
    assert m, line
    unescaped = (m.group(1).replace("\\\\", "\x00").replace('\\"', '"')
                 .replace("\\n", "\n").replace("\x00", "\\"))
    assert unescaped == nasty
    # scalar_values (the --watch / flight-recorder view) uses the same
    # escaping, so the series key is unambiguous too
    assert f'esc_total{{component="{metrics_mod.escape_label_value(nasty)}"}}' \
        in reg.scalar_values()


def test_exposition_under_concurrent_registry_mutation():
    """/metrics must stay well-formed while other threads register new
    families and children mid-scrape (a live serving process does this
    constantly: warmup compiles, first paramserver push, watchdog
    transitions)."""
    reg = MetricsRegistry()
    stop = threading.Event()
    errs = []

    def mutate(k):
        i = 0
        try:
            while not stop.is_set():
                fam = reg.counter(f"mut_{k}_{i % 17}_total", "x", ("l",))
                fam.labels(f"v{i % 5}").inc()
                reg.gauge(f"mutg_{k}_{i % 13}", "x").set(i)
                reg.histogram(f"muth_{k}_{i % 7}_seconds", "x").observe(
                    0.001 * (i % 50))
                i += 1
        except Exception as e:  # pragma: no cover - failure reporting
            errs.append(e)

    threads = [threading.Thread(target=mutate, args=(k,), daemon=True,
                                name=f"dl4j-test-mut-{k}")
               for k in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(30):
            text = reg.to_prometheus()
            # every non-comment line is "name{labels} value" with a
            # parseable numeric value
            for line in text.splitlines():
                if not line or line.startswith("#"):
                    continue
                name_part, _, value = line.rpartition(" ")
                assert name_part, line
                float(value)
            json.dumps(reg.snapshot(), allow_nan=False)
            reg.scalar_values()
    finally:
        stop.set()
        for t in threads:
            t.join(5)
    assert not errs
