"""Server-independent report component DSL — the ui-components analog.

Reference: deeplearning4j-ui-parent/deeplearning4j-ui-components — a
library of chart/table/text components (ChartLine, ChartHistogram,
ChartScatter, ComponentTable, ComponentText, ComponentDiv, StyleChart...)
that serialize to JSON (componentType-discriminated) and render to a
self-contained page with NO running server, used for standalone training
reports.

TPU-era shape: each component is a small dataclass with the same
componentType-tagged JSON wire format (to_json/from_json round-trip) and
a `render_html()` that emits inline SVG/HTML — zero external assets, zero
JavaScript required, so the artifact opens anywhere (the box it was
produced on may have no egress). `render_page` wraps a component list
into one self-contained HTML document.
"""

from __future__ import annotations

import dataclasses
import html as _html
import json
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class StyleChart:
    """Subset of the reference's StyleChart the renderer honors."""

    width: int = 420
    height: int = 180
    stroke_color: str = "#1565c0"

    def to_dict(self):
        return dataclasses.asdict(self)


def _esc(s) -> str:
    return _html.escape(str(s), quote=True)


def _style_from_dict(d: dict, default_width: int = 420,
                     default_height: int = 180) -> StyleChart:
    st = d.get("style", {})
    return StyleChart(st.get("width", default_width),
                      st.get("height", default_height),
                      st.get("stroke_color", "#1565c0"))


def _polyline(points: Sequence[Tuple[float, float]], w: int, h: int,
              color: str) -> str:
    """Scaled SVG path + min/max caption for one series."""
    pts = [(float(x), float(y)) for x, y in points
           if y is not None and y == y]  # drop None/NaN
    if len(pts) < 2:
        return f'<svg width="{w}" height="{h}"></svg>'
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    sx = lambda x: 4 + (w - 8) * (x - x0) / max(x1 - x0, 1e-9)
    sy = lambda y: h - 16 - (h - 24) * (y - y0) / max(y1 - y0, 1e-9)
    d = " ".join(
        f"{'M' if i == 0 else 'L'}{sx(x):.1f},{sy(y):.1f}"
        for i, (x, y) in enumerate(pts))
    return (
        f'<svg width="{w}" height="{h}">'
        f'<path d="{d}" fill="none" stroke="{color}" stroke-width="1.5"/>'
        f'<text x="4" y="{h - 3}" font-size="9" fill="#888">'
        f"x [{x0:g}, {x1:g}]  y [{y0:.5g}, {y1:.5g}]</text></svg>"
    )


class Component:
    """Base: componentType-tagged JSON + HTML rendering."""

    component_type = "Component"

    def to_dict(self) -> dict:
        raise NotImplementedError

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    def render_html(self) -> str:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Component":
        t = d.get("componentType")
        cls = _REGISTRY.get(t)
        if cls is None:
            raise ValueError(f"unknown componentType {t!r}")
        return cls._from_dict(d)

    @staticmethod
    def from_json(s: str) -> "Component":
        return Component.from_dict(json.loads(s))


class ComponentText(Component):
    """Reference: ComponentText — a styled text run."""

    component_type = "ComponentText"

    def __init__(self, text: str, size: float = 13.0, bold: bool = False):
        self.text = text
        self.size = size
        self.bold = bold

    def to_dict(self):
        return {"componentType": self.component_type, "text": self.text,
                "style": {"fontSize": self.size, "bold": self.bold}}

    @classmethod
    def _from_dict(cls, d):
        st = d.get("style", {})
        return cls(d.get("text", ""), st.get("fontSize", 13.0),
                   st.get("bold", False))

    def render_html(self):
        weight = "bold" if self.bold else "normal"
        return (f'<p style="font-size:{self.size}px;'
                f'font-weight:{weight}">{_esc(self.text)}</p>')


class ComponentTable(Component):
    """Reference: ComponentTable — header + string rows."""

    component_type = "ComponentTable"

    def __init__(self, header: Sequence[str], rows: Sequence[Sequence]):
        self.header = [str(h) for h in header]
        self.rows = [[str(c) for c in r] for r in rows]

    def to_dict(self):
        return {"componentType": self.component_type,
                "header": self.header, "content": self.rows}

    @classmethod
    def _from_dict(cls, d):
        return cls(d.get("header", []), d.get("content", []))

    def render_html(self):
        head = "".join(f"<th>{_esc(h)}</th>" for h in self.header)
        body = "".join(
            "<tr>" + "".join(f"<td>{_esc(c)}</td>" for c in r) + "</tr>"
            for r in self.rows)
        return (f'<table><tr>{head}</tr>{body}</table>')


class ChartLine(Component):
    """Reference: ChartLine — named x/y series on one chart."""

    component_type = "ChartLine"

    _PALETTE = ("#1565c0", "#2e7d32", "#c62828", "#6a1b9a", "#ef6c00",
                "#00695c", "#4e342e", "#37474f")

    def __init__(self, title: str,
                 series: Dict[str, Sequence[Tuple[float, float]]],
                 style: Optional[StyleChart] = None):
        self.title = title
        self.series = {k: [(float(x), float(y)) for x, y in v]
                       for k, v in series.items()}
        self.style = style or StyleChart()

    def to_dict(self):
        return {
            "componentType": self.component_type, "title": self.title,
            "x": {k: [p[0] for p in v] for k, v in self.series.items()},
            "y": {k: [p[1] for p in v] for k, v in self.series.items()},
            "seriesNames": list(self.series),
            "style": self.style.to_dict(),
        }

    @classmethod
    def _from_dict(cls, d):
        series = {
            k: list(zip(d["x"][k], d["y"][k]))
            for k in d.get("seriesNames", [])
        }
        return cls(d.get("title", ""), series, _style_from_dict(d))

    def render_html(self):
        w, h = self.style.width, self.style.height
        parts = [f'<div class="chart"><h3>{_esc(self.title)}</h3>']
        legend = []
        for i, (name, pts) in enumerate(self.series.items()):
            color = self._PALETTE[i % len(self._PALETTE)]
            legend.append(
                f'<span style="color:{color}">&#9632; {_esc(name)}</span>')
            parts.append(_polyline(pts, w, h, color))
        parts.append('<div style="font-size:10px">' + " ".join(legend)
                     + "</div></div>")
        return "".join(parts)


class ChartHistogram(Component):
    """Reference: ChartHistogram — bin edges + counts."""

    component_type = "ChartHistogram"

    def __init__(self, title: str, edges: Sequence[float],
                 counts: Sequence[float],
                 style: Optional[StyleChart] = None):
        self.title = title
        self.edges = [float(e) for e in edges]
        self.counts = [float(c) for c in counts]
        self.style = style or StyleChart(height=140)

    def to_dict(self):
        return {"componentType": self.component_type, "title": self.title,
                "lowerBounds": self.edges[:-1], "upperBounds": self.edges[1:],
                "yValues": self.counts, "style": self.style.to_dict()}

    @classmethod
    def _from_dict(cls, d):
        lo = d.get("lowerBounds", [])
        up = d.get("upperBounds", [])
        edges = lo + up[-1:] if lo else []
        return cls(d.get("title", ""), edges, d.get("yValues", []),
                   _style_from_dict(d, default_height=140))

    def render_html(self):
        w, h = self.style.width, self.style.height
        n = len(self.counts)
        if not n:
            return f'<div class="chart"><h3>{_esc(self.title)}</h3></div>'
        mx = max(max(self.counts), 1.0)
        bars = []
        for i, c in enumerate(self.counts):
            bh = (h - 24) * c / mx
            bars.append(
                f'<rect x="{i * w / n:.1f}" y="{h - 16 - bh:.1f}" '
                f'width="{max(w / n - 1, 1):.1f}" height="{bh:.1f}" '
                f'fill="{self.style.stroke_color}"/>')
        caption = (f"[{self.edges[0]:.4g}, {self.edges[-1]:.4g}]"
                   if self.edges else "")
        return (f'<div class="chart"><h3>{_esc(self.title)}</h3>'
                f'<svg width="{w}" height="{h}">{"".join(bars)}'
                f'<text x="4" y="{h - 3}" font-size="9" fill="#888">'
                f"{caption}</text></svg></div>")


class ChartScatter(Component):
    """Reference: ChartScatter — point cloud (t-SNE plots etc.)."""

    component_type = "ChartScatter"

    def __init__(self, title: str,
                 points: Sequence[Tuple[float, float]],
                 labels: Optional[Sequence[str]] = None,
                 style: Optional[StyleChart] = None):
        self.title = title
        self.points = [(float(x), float(y)) for x, y in points]
        self.labels = list(labels) if labels else None
        self.style = style or StyleChart(width=520, height=420)

    def to_dict(self):
        return {"componentType": self.component_type, "title": self.title,
                "x": [p[0] for p in self.points],
                "y": [p[1] for p in self.points],
                "labels": self.labels, "style": self.style.to_dict()}

    @classmethod
    def _from_dict(cls, d):
        return cls(d.get("title", ""),
                   list(zip(d.get("x", []), d.get("y", []))),
                   d.get("labels"),
                   _style_from_dict(d, default_width=520,
                                    default_height=420))

    def render_html(self):
        w, h = self.style.width, self.style.height
        if not self.points:
            return f'<div class="chart"><h3>{_esc(self.title)}</h3></div>'
        xs = [p[0] for p in self.points]
        ys = [p[1] for p in self.points]
        x0, x1, y0, y1 = min(xs), max(xs), min(ys), max(ys)
        sx = lambda x: 10 + (w - 20) * (x - x0) / max(x1 - x0, 1e-9)
        sy = lambda y: h - 10 - (h - 20) * (y - y0) / max(y1 - y0, 1e-9)
        parts = []
        for i, (x, y) in enumerate(self.points):
            parts.append(f'<circle cx="{sx(x):.1f}" cy="{sy(y):.1f}" r="3" '
                         f'fill="{self.style.stroke_color}"/>')
            if self.labels and i < len(self.labels):
                parts.append(f'<text x="{sx(x) + 4:.1f}" y="{sy(y):.1f}" '
                             f'font-size="9">{_esc(self.labels[i])}</text>')
        return (f'<div class="chart"><h3>{_esc(self.title)}</h3>'
                f'<svg width="{w}" height="{h}">{"".join(parts)}</svg></div>')


class ComponentDiv(Component):
    """Reference: ComponentDiv — a container of child components."""

    component_type = "ComponentDiv"

    def __init__(self, children: Sequence[Component], title: str = ""):
        self.children = list(children)
        self.title = title

    def to_dict(self):
        return {"componentType": self.component_type, "title": self.title,
                "components": [c.to_dict() for c in self.children]}

    @classmethod
    def _from_dict(cls, d):
        return cls([Component.from_dict(c)
                    for c in d.get("components", [])], d.get("title", ""))

    def render_html(self):
        head = f"<h2>{_esc(self.title)}</h2>" if self.title else ""
        return ("<div>" + head
                + "".join(c.render_html() for c in self.children) + "</div>")


_REGISTRY = {
    c.component_type: c
    for c in (ComponentText, ComponentTable, ChartLine, ChartHistogram,
              ChartScatter, ComponentDiv)
}


def register_component(cls) -> type:
    """Add a Component subclass to the from_json dispatch (the DSL is
    open, like the reference's Component jackson subtypes)."""
    _REGISTRY[cls.component_type] = cls
    return cls

_CSS = """
 body { font-family: sans-serif; margin: 1.5em; background: #fafafa; }
 h1 { font-size: 1.3em; } h2 { font-size: 1.05em; color: #333;
   border-bottom: 1px solid #ddd; padding-bottom: 2px; }
 h3 { font-size: 0.9em; color: #444; margin: 0.2em 0; }
 .chart { background: #fff; border: 1px solid #ddd; margin: 0.5em;
          padding: 0.5em; display: inline-block; vertical-align: top; }
 table { border-collapse: collapse; background: #fff; }
 td, th { border: 1px solid #ccc; padding: 2px 8px; font-size: 0.85em; }
"""


def render_page(title: str, components: Sequence[Component]) -> str:
    """One fully self-contained HTML document (inline CSS + SVG, no
    scripts, no external assets) — the reference's standalone-report
    rendering path, server-free by construction."""
    body = "".join(c.render_html() for c in components)
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{_esc(title)}</title><style>{_CSS}</style></head>"
        f"<body><h1>{_esc(title)}</h1>{body}</body></html>"
    )
