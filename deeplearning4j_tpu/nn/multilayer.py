"""MultiLayerNetwork — the sequential network.

Analog of the reference's nn/multilayer/MultiLayerNetwork.java (2,853 LoC).
The capability map (SURVEY.md §3.1) translates TPU-first:

- reference: per-minibatch Solver.optimize -> feedForward (per-layer JNI
  ops) -> backprop (hand-written) -> updater -> step.
- here: ONE jitted train step = forward + loss + autodiff backward +
  gradient normalization + updater + parameter update, compiled by XLA into
  a single TPU program with donated buffers. Host code only feeds batches
  and reads back the score when a listener asks.

Parameters are a list of per-layer dicts (pytree); the flattened view
(reference: flattenedParams, MultiLayerNetwork.java:102-104) is provided by
nn/params.py for serialization/averaging APIs. Mutable non-trainable state
(batchnorm running stats; LSTM h/c during TBPTT and rnnTimeStep streaming)
is a parallel list, threaded functionally through the step.

TBPTT (reference: :1074-1076, truncatedBPTTGradient :1333) segments the
time axis host-side and carries RNN state between segment steps.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common.dtypes import policy_from_name
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.network import BackpropType, MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.registry import (
    LayerContext,
    forward_layer,
    init_layer_params,
    init_layer_state,
)
from deeplearning4j_tpu.nn.netbase import NetworkBase
from deeplearning4j_tpu.ops.losses import example_presence, masked_example_mean, loss_value
from deeplearning4j_tpu.train.evaluation import Evaluation, RegressionEvaluation
from deeplearning4j_tpu.train.updaters import (
    normalize_gradients,
    schedule_lr,
    updater_from_conf,
)

logger = logging.getLogger("deeplearning4j_tpu")

_OUTPUT_LAYER_TYPES = (L.OutputLayer, L.RnnOutputLayer, L.LossLayer,
                       L.CenterLossOutputLayer)


def _is_recurrent(conf) -> bool:
    inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
    return isinstance(inner, (L.LSTM, L.GravesLSTM))


def _is_frozen(conf) -> bool:
    return isinstance(conf, L.FrozenLayer)


def _regularizable(name: str) -> bool:
    """Weight-style params get l1/l2; biases and batchnorm affine params do
    not (reference: each ParamInitializer flags regularizable params;
    BatchNormalizationParamInitializer marks gamma/beta non-regularizable)."""
    if name in ("gamma", "beta"):
        return False
    base = name.rsplit("_", 1)[-1]
    return base in ("W", "RW", "pI", "pF", "pO")


def _preout_of_output_layer(conf, params, x):
    """Pre-activation of the final (output) layer — the quantity losses
    consume (reference: BaseOutputLayer.preOutput2d)."""
    if isinstance(conf, L.LossLayer):
        return x
    if isinstance(conf, L.RnnOutputLayer):
        return jnp.einsum("bti,io->bto", x, params["W"]) + params["b"]
    return x @ params["W"] + params["b"]


class MultiLayerNetwork(NetworkBase):
    """Sequential network. API mirrors the reference: init, fit, output,
    score, evaluate, params/set_params, rnn_time_step."""

    def __init__(self, conf: MultiLayerConfiguration):
        super().__init__()
        self.conf = conf
        self.layer_confs: List[L.LayerConf] = list(conf.layers)
        self.net_conf = conf.net_conf
        self.policy = policy_from_name(self.net_conf.precision)
        self.updater_def = updater_from_conf(self.net_conf)
        self._rnn_states = None  # streaming inference state (rnn_time_step)
        self._train_step_fn = None
        self._output_fn = None

    def _ordered_layer_confs(self):
        return self.layer_confs

    # -- init ----------------------------------------------------------------

    def init(self) -> "MultiLayerNetwork":
        key = jax.random.PRNGKey(self.net_conf.seed)
        dtype = self.policy.param_dtype
        self.params_list = []
        self.state_list = []
        for i, conf in enumerate(self.layer_confs):
            self.params_list.append(
                init_layer_params(jax.random.fold_in(key, i), conf, dtype)
            )
            self.state_list.append(init_layer_state(conf, dtype))
        self.upd_state = self.updater_def.init_tree(self.params_list)
        return self

    # -- forward -------------------------------------------------------------

    def _forward(self, params, states, x, *, training, rng, f_mask=None,
                 stateful=False, preout_last=False, to_layer=None):
        """Pure forward. Returns (out, new_states). Used under jit."""
        confs = self.layer_confs
        pps = self.conf.preprocessors
        new_states: List[Optional[dict]] = [None] * len(confs)
        timesteps = x.shape[1] if x.ndim == 3 else None
        n = len(confs) if to_layer is None else to_layer
        for i in range(n):
            conf = confs[i]
            pp = pps.get(str(i))
            if pp is not None:
                x = pp(x, {"timesteps": timesteps})
            if hasattr(x, "ndim") and x.ndim == 3:
                timesteps = x.shape[1]
            st = states[i]
            if stateful and _is_recurrent(conf) and st is None:
                st = {}  # empty dict triggers zero-state seed + state return
            ctx = LayerContext(
                training=training,
                rng=jax.random.fold_in(rng, i) if rng is not None else None,
                mask=f_mask if (hasattr(x, "ndim") and x.ndim == 3) else None,
                timesteps=timesteps,
                state=st,
            )
            is_last = i == len(confs) - 1
            if preout_last and is_last and isinstance(conf, _OUTPUT_LAYER_TYPES):
                # input dropout applies to the output layer too (reference:
                # BaseOutputLayer preOutput applies Dropout to its input)
                from deeplearning4j_tpu.nn.layers.core import apply_dropout

                x = apply_dropout(x, conf.dropout, ctx)
                x = _preout_of_output_layer(conf, params[i], x)
                ns = None
            else:
                x, ns = forward_layer(conf, params[i], x, ctx)
            new_states[i] = ns
        return x, new_states

    def _merge_states(self, old, new):
        return [n if n is not None else o for o, n in zip(old, new)]

    # -- loss ----------------------------------------------------------------

    def _loss(self, params, states, x, y, f_mask, l_mask, rng, training=True):
        last = self.layer_confs[-1]
        if not isinstance(last, _OUTPUT_LAYER_TYPES):
            raise ValueError(
                "the final layer must be an OutputLayer/RnnOutputLayer/"
                "LossLayer to compute a training loss"
            )
        x = self.policy.cast_input(x)
        if isinstance(last, L.CenterLossOutputLayer):
            score, new_states = self._center_loss(
                params, states, x, y, f_mask, l_mask, rng, training
            )
        else:
            preout, new_states = self._forward(
                params, states, x, training=training, rng=rng, f_mask=f_mask,
                preout_last=True,
            )
            preout = self.policy.cast_output(preout)
            per_ex = loss_value(last.loss, y, preout, last.activation, l_mask)
            score = masked_example_mean(per_ex, l_mask)
        # L1/L2 penalties (reference: BaseLayer.calcL1/calcL2 added to score;
        # gradients come from differentiating this same expression)
        reg = 0.0
        for conf, p in zip(self.layer_confs, params):
            inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
            l1 = getattr(inner, "l1", 0.0) or 0.0
            l2 = getattr(inner, "l2", 0.0) or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue
            for name, w in p.items():
                if _regularizable(name):
                    if l1:
                        reg = reg + l1 * jnp.sum(jnp.abs(w))
                    if l2:
                        reg = reg + 0.5 * l2 * jnp.sum(w * w)
        return score + reg, new_states

    def _center_loss(self, params, states, x, y, f_mask, l_mask, rng, training):
        """Center loss (reference: nn/layers/training/CenterLossOutputLayer
        .java): base loss + lambda/2 * ||f - c_y||^2 on the output layer's
        input features, with the per-class centers EMA-updated toward the
        batch class means (alpha) as non-trainable state."""
        from deeplearning4j_tpu.nn.layers.core import apply_dropout

        last: L.CenterLossOutputLayer = self.layer_confs[-1]
        n = len(self.layer_confs)
        feats, new_states = self._forward(
            params, states, x, training=training, rng=rng, f_mask=f_mask,
            to_layer=n - 1,
        )
        ctx_last = LayerContext(
            training=training,
            rng=jax.random.fold_in(rng, n - 1) if rng is not None else None,
        )
        feats = apply_dropout(feats, last.dropout, ctx_last)
        preout = _preout_of_output_layer(last, params[-1], feats)
        preout = self.policy.cast_output(preout)
        per_ex = loss_value(last.loss, y, preout, last.activation, l_mask)

        centers = states[-1]["centers"].astype(feats.dtype)  # [classes, nIn]
        y32 = y.astype(feats.dtype)
        per_example_center = y32 @ centers  # one-hot pick
        diff = feats - per_example_center
        center_per_ex = 0.5 * jnp.sum(diff * diff, axis=-1)
        present = example_presence(per_ex, l_mask)
        score = (masked_example_mean(per_ex, l_mask)
                 + last.lambda_ * jnp.sum(center_per_ex * present)
                 / jnp.maximum(jnp.sum(present), 1.0))

        if training:
            # EMA update: c_k <- (1-alpha) c_k + alpha * mean(f_i : y_i = k),
            # only for classes present in the batch; gradients do not flow
            # into the centers (they are state, not params)
            f_sg = jax.lax.stop_gradient(feats)
            yw = y32 * present[:, None]  # pad rows excluded from the EMA
            counts = jnp.sum(yw, axis=0)[:, None]  # [classes, 1]
            sums = yw.T @ f_sg  # [classes, nIn]
            means = sums / jnp.maximum(counts, 1.0)
            updated = jnp.where(
                counts > 0, (1.0 - last.alpha) * centers + last.alpha * means,
                centers,
            )
            new_states[-1] = {"centers": updated.astype(states[-1]["centers"].dtype)}
        return score, new_states

    # -- train step ----------------------------------------------------------

    def _lr_mult_tree(self):
        """Per-leaf learning-rate multiplier (per-layer learning_rate and
        bias_learning_rate overrides, reference: layer conf learningRate)."""
        base = self.net_conf.learning_rate
        out = []
        for conf, p in zip(self.layer_confs, self.params_list):
            inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
            layer_lr = getattr(inner, "learning_rate", None)
            bias_lr = getattr(inner, "bias_learning_rate", None)
            mult = {}
            for name in p:
                if name == "b" and bias_lr is not None:
                    mult[name] = bias_lr / base
                elif layer_lr is not None:
                    mult[name] = layer_lr / base
                else:
                    mult[name] = 1.0
            out.append(mult)
        return out

    def _trainable_mask(self):
        return [
            {k: (0.0 if _is_frozen(conf) else 1.0) for k in p}
            for conf, p in zip(self.layer_confs, self.params_list)
        ]

    def _make_step_body(self, loss_builder, collect: bool = False):
        """Unjitted optimizer-step body around a loss builder
        (p, states, data, rng) -> (score, new_states). The tail — gradient
        masking/normalization, per-leaf lr, updater, param update — is
        shared by the standard, truncated-backward and fused-TBPTT steps.

        Returns (params, states, upd_state, score, diag[, stats]): `diag`
        is the in-graph divergence diagnostic `[loss, global grad norm]`
        — a 2-vector fused into the same program (a few elementwise
        reductions next to a full backward pass), so the sentinel's
        per-step judgment costs ONE device read that rides the score
        fetch instead of a second sync."""
        gnorm = self.net_conf.gradient_normalization
        gthresh = self.net_conf.gradient_normalization_threshold
        mults = self._lr_mult_tree()
        tmask = self._trainable_mask()
        updater = self.updater_def
        minimize = self.net_conf.minimize
        # mesh-attached nets pin the gradient reduction IN-GRAPH here:
        # constraining the grads to the parameter shardings makes GSPMD
        # insert the cross-device psum/mean at the grad site (replicated
        # params x data-sharded batch), replacing the reference's
        # host-side parameter averaging. The plan emits it BUCKETED
        # (reverse-topo flat payloads, parallel/sharded.CollectivePlan):
        # each bucket's collective depends only on its own leaves, so the
        # scheduler can overlap early buckets with the remaining backward
        plan = self._mesh_plan

        def step(params, states, upd_state, data, lr, t, rng):
            def loss_fn(p):
                return loss_builder(p, states, data, rng)

            (score, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            if plan is not None:
                grads = plan.reduce_grads(self, grads)
            # global grad norm of the RAW gradient (before masking/
            # clipping — clipping would hide exactly the explosion the
            # sentinel watches for), accumulated in f32
            gsq = jnp.float32(0.0)
            for g in jax.tree_util.tree_leaves(grads):
                gsq = gsq + jnp.sum(jnp.square(g.astype(jnp.float32)))
            diag = jnp.stack([score.astype(jnp.float32), jnp.sqrt(gsq)])
            if not minimize:
                grads = jax.tree_util.tree_map(lambda g: -g, grads)
            grads = [
                {k: g[k] * m[k] for k in g} for g, m in zip(grads, tmask)
            ]
            grads = normalize_gradients(grads, gnorm, gthresh)
            lr_tree = [
                {k: lr * m[k] for k in g} for g, m in zip(grads, mults)
            ]
            updates, new_upd = updater.apply_tree(grads, upd_state, lr_tree, t)
            new_params = jax.tree_util.tree_map(jnp.add, params, updates)
            merged = self._merge_states(states, new_states)
            if collect:
                # per-layer mean |x| scalars for the stats pipeline
                # (reference: BaseStatsListener param/grad/update mean
                # magnitudes) — fused into the step; tiny reductions
                mm = lambda tree: [
                    {k: jnp.mean(jnp.abs(v)) for k, v in p.items()}
                    for p in tree
                ]
                stats = {"grad_mm": mm(grads), "update_mm": mm(updates),
                         "param_mm": mm(new_params)}
                return new_params, merged, new_upd, score, diag, stats
            return new_params, merged, new_upd, score, diag

        return step

    def _make_step(self, loss_builder):
        """Jitted single-minibatch optimizer step (donated params/updater
        buffers on device backends; sharded signature under a mesh plan —
        see netbase._jit_step)."""
        step = self._make_step_body(
            loss_builder, collect=bool(getattr(self, "_collect_stats", False))
        )
        return self._jit_step(step)

    def _std_loss_builder(self):
        def loss_builder(p, states, data, rng):
            x, y, f_mask, l_mask = data
            return self._loss(p, states, x, y, f_mask, l_mask, rng)

        return loss_builder

    def _trunc_loss_builder(self):
        """TBPTT loss with tbptt_bwd_length < tbptt_fwd_length: the
        segment's leading (fwd-bwd) timesteps run under stop_gradient
        (state advances, loss counts, but no gradient flows back through
        them), truncating backprop depth to bwd_length (reference:
        tBPTTBackwardLength, MultiLayerNetwork.java:1333; the reference
        zeroes epsilons past bwd steps of the reverse walk — here the cut
        is a stop_gradient on the carried state at the boundary)."""

        def loss_builder(p, states, data, rng):
            xA, yA, fmA, lmA, xB, yB, fmB, lmB = data
            lossA, statesA = self._loss(p, states, xA, yA, fmA, lmA, rng)
            carried = self._merge_states(states, statesA)
            carried = jax.tree_util.tree_map(jax.lax.stop_gradient, carried)
            lossB, statesB = self._loss(
                p, carried, xB, yB, fmB, lmB,
                None if rng is None else jax.random.fold_in(rng, 1),
            )
            nA, nB = xA.shape[1], xB.shape[1]
            # slice A contributes to the reported score but NOT to the
            # gradient (stop_gradient lets XLA prune its whole backward
            # pass) — backprop depth is exactly bwd_length
            score = (
                jax.lax.stop_gradient(lossA) * nA + lossB * nB
            ) / (nA + nB)
            return score, self._merge_states(carried, statesB)

        return loss_builder

    def _build_train_step(self):
        return self._make_step(self._std_loss_builder())

    def _build_truncated_bwd_step(self):
        return self._make_step(self._trunc_loss_builder())

    @staticmethod
    def _make_seg_data(seg: int, bwd: int):
        """TBPTT time-segmentation under jit: returns seg_data(x, y, fm,
        lm, i) -> the step-body data tuple for segment i (the 8-tuple
        A/B split when bwd < seg, the plain 4-tuple otherwise). Uses
        dynamic_slice so `i` may be a traced scan index."""

        def seg_slice(a, start, length):
            return jax.lax.dynamic_slice_in_dim(a, start, length, axis=1)

        def seg_data(x, y, fm, lm, i):
            start = i * seg
            cut_m = lambda m, s0, ln: (
                None if m is None else (m if m.ndim == 1
                                        else seg_slice(m, s0, ln))
            )
            cut_y = lambda s0, ln: (seg_slice(y, s0, ln) if y.ndim == 3 else y)
            if bwd < seg:
                nA = seg - bwd
                return (
                    seg_slice(x, start, nA), cut_y(start, nA),
                    cut_m(fm, start, nA), cut_m(lm, start, nA),
                    seg_slice(x, start + nA, bwd), cut_y(start + nA, bwd),
                    cut_m(fm, start + nA, bwd), cut_m(lm, start + nA, bwd),
                )
            return (seg_slice(x, start, seg), cut_y(start, seg),
                    cut_m(fm, start, seg), cut_m(lm, start, seg))

        return seg_data

    def _build_tbptt_fused_step(self, n_seg: int, seg: int, bwd: int):
        """ALL of a batch's TBPTT segments in ONE jitted dispatch.

        The per-segment loop in `_fit_tbptt` costs several host->device
        dispatches per segment (time-slices + the step); through a
        high-latency device link that overhead dwarfs the compute for
        small recurrent cells (measured: 9.5ms/segment dispatched vs 93us
        of device time on the char-rnn bench). Here segment 0 runs inline
        (populating the RNN-state carry structure) and segments 1..n-1 run
        under `lax.scan`, so the whole fit batch is one dispatch. Exact
        same math as the loop: same per-segment lr/t/rng, same optimizer
        tail (equivalence pinned by tests/test_tbptt_fused.py).

        Callers must guarantee T == n_seg * seg (no ragged tail — the
        fixed-size `dynamic_slice` segmentation cannot express one; the
        loop path handles it) and that per-iteration stats collection is
        off (the body is built without `collect`).
        """
        assert not getattr(self, "_collect_stats", False), (
            "fused TBPTT does not collect per-iteration stats; "
            "_fit_tbptt must use the loop path when collection is on"
        )
        body = self._make_step_body(
            self._trunc_loss_builder() if bwd < seg
            else self._std_loss_builder()
        )
        seed_key_base = self.net_conf.seed ^ 0x5EED
        seg_data = self._make_seg_data(seg, bwd)

        def step(params, states, upd_state, data, lrs, t0, _rng_unused):
            x, y, fm, lm = data
            key = jax.random.PRNGKey(seed_key_base)

            def run_seg(params, states, upd_state, i):
                rng, t = self._step_rng_and_t(key, t0, i)
                return body(params, states, upd_state,
                            seg_data(x, y, fm, lm, i), lrs[i], t, rng)

            # segment 0 inline: its merged states establish the carry
            # pytree (zero-state {} -> populated h/c) for the scan
            params, states, upd_state, s0, d0 = run_seg(
                params, states, upd_state, 0)
            if n_seg == 1:
                return params, states, upd_state, s0[None], s0, d0

            def scan_body(carry, i):
                p, st, us = carry
                p, st, us, score, dg = run_seg(p, st, us, i)
                return (p, st, us), (score, dg)

            (params, states, upd_state), (scores, diags) = jax.lax.scan(
                scan_body, (params, states, upd_state),
                jnp.arange(1, n_seg))
            # the final score returned separately so the host can keep a
            # scalar _score without an extra device-indexing dispatch
            last = scores[-1]
            # whole-batch diagnostic: final score, worst grad norm of
            # any segment (a NaN segment poisons later params, so the
            # final loss carries the non-finite signal regardless)
            diag = jnp.stack([diags[-1, 0],
                              jnp.maximum(d0[1], jnp.max(diags[:, 1]))])
            scores = jnp.concatenate([s0[None], scores])
            return params, states, upd_state, scores, last, diag

        return self._jit_step(step)

    def _run_step(self, step_fn, data, stateful_states=None):
        lr = schedule_lr(self.net_conf, self.iteration)
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.net_conf.seed ^ 0x5EED), self.iteration
        )
        states = stateful_states if stateful_states is not None else self.state_list
        out = step_fn(
            self.params_list, states, self.upd_state,
            tuple(None if a is None else jnp.asarray(a) for a in data),
            jnp.asarray(lr, jnp.float32), jnp.asarray(float(self.iteration)),
            rng,
        )
        params, states, upd, score = out[:4]
        self._step_diag = out[4]
        self._last_stats = out[5] if len(out) > 5 else None
        self.params_list = params
        self.upd_state = upd
        self._score = score
        self.iteration += 1
        return states, score

    def _fit_step(self, x, y, f_mask, l_mask, stateful_states=None):
        """One optimizer step. Returns the (device) score."""
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
            self._note_compile("train_step")
        return self._run_step(
            self._train_step_fn, (x, y, f_mask, l_mask), stateful_states
        )

    def _fit_step_truncated(self, dataA, dataB, stateful_states):
        """One TBPTT segment step with a backward-truncation boundary
        between slice A (state-carry, stop-gradient) and slice B."""
        if getattr(self, "_trunc_step_fn", None) is None:
            self._trunc_step_fn = self._build_truncated_bwd_step()
            self._note_compile("train_step_truncated")
        return self._run_step(
            self._trunc_step_fn, dataA + dataB, stateful_states
        )

    # -- pretraining ---------------------------------------------------------

    _PRETRAINABLE = (L.AutoEncoder, L.VariationalAutoencoder, L.RBM)

    def pretrain(self, data, *, epochs: int = 1, batch_size: int = 32):
        """Layerwise unsupervised pretraining: each pretrainable layer
        (AutoEncoder / VAE / RBM) trains on the activations of the frozen
        stack below it (reference: MultiLayerNetwork.pretrain/pretrainLayer
        :210-287)."""
        self._require_init()
        for i, conf in enumerate(self.layer_confs):
            if isinstance(conf, self._PRETRAINABLE):
                self.pretrain_layer(i, data, epochs=epochs, batch_size=batch_size)
        return self

    def pretrain_layer(self, idx: int, data, *, epochs: int = 1,
                       batch_size: int = 32):
        """Unsupervised fit of one layer. Objectives: AutoEncoder =
        reconstruction loss through tied-weight decode; VAE = negative
        ELBO (special.py vae_elbo); RBM = CD-k (rbm.py rbm_cd_stats)."""
        conf = self.layer_confs[idx]
        if not isinstance(conf, self._PRETRAINABLE):
            raise ValueError(
                f"layer {idx} ({type(conf).__name__}) is not pretrainable"
            )
        if isinstance(data, DataSetIterator):
            iterator = data
        elif isinstance(data, DataSet):
            iterator = ListDataSetIterator(data, batch_size)
        else:  # raw features; labels are unused in unsupervised fit
            x = np.asarray(data)
            iterator = ListDataSetIterator(DataSet(x, x), batch_size)
        feed = jax.jit(
            lambda params, states, x: self._forward(
                params, states, self.policy.cast_input(x),
                training=False, rng=None, to_layer=idx,
            )[0]
        )
        step = self._build_pretrain_step(conf)
        upd_state = self.updater_def.init_tree(self.params_list[idx])
        it_count = 0
        for _ in range(epochs):
            for ds in iterator:
                x_in = feed(self.params_list, self.state_list,
                            jnp.asarray(ds.features))
                lr = schedule_lr(self.net_conf, it_count)
                rng = jax.random.fold_in(
                    jax.random.PRNGKey(self.net_conf.seed ^ (0xBEEF + idx)),
                    it_count,
                )
                new_p, upd_state, score = step(
                    self.params_list[idx], upd_state, x_in,
                    jnp.asarray(lr, jnp.float32),
                    jnp.asarray(float(it_count)), rng,
                )
                self.params_list = (
                    self.params_list[:idx] + [new_p] + self.params_list[idx + 1:]
                )
                self._score = score
                it_count += 1
            iterator.reset()
        return self

    def _build_pretrain_step(self, conf):
        updater = self.updater_def

        def step(layer_params, upd_state, x_in, lr, t, rng):
            if isinstance(conf, L.RBM):
                from deeplearning4j_tpu.nn.layers.rbm import rbm_cd_stats

                grads, per_ex = rbm_cd_stats(conf, layer_params, x_in, rng)
                score = jnp.mean(per_ex)
            else:
                def objective(p):
                    if isinstance(conf, L.VariationalAutoencoder):
                        from deeplearning4j_tpu.nn.layers.special import vae_elbo

                        return jnp.mean(vae_elbo(conf, p, x_in, rng))
                    from deeplearning4j_tpu.nn.layers.core import (
                        autoencoder_reconstruct,
                    )

                    ctx = LayerContext(training=True, rng=rng)
                    recon = autoencoder_reconstruct(conf, p, x_in, ctx)
                    per_ex = loss_value(conf.loss, x_in, recon, "identity", None)
                    return jnp.mean(per_ex)

                score, grads = jax.value_and_grad(objective)(layer_params)
            updates, new_upd = updater.apply_tree(grads, upd_state, lr, t)
            new_params = jax.tree_util.tree_map(jnp.add, layer_params, updates)
            return new_params, new_upd, score

        return jax.jit(step)

    # -- fit -----------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs: int = 1, batch_size: int = 32,
            async_prefetch: bool = True, prefetch_buffer: int = 4,
            hang_timeout: float = None, resume_from: str = None,
            run_ledger=None):
        """Train. Accepts (features, labels) arrays, a DataSet, or a
        DataSetIterator (reference: MultiLayerNetwork.fit overloads
        :1019). If the configuration sets pretrain=True, layerwise
        unsupervised pretraining runs once before the first backprop epoch
        (reference: fit() pretrain dispatch :210). With async_prefetch the
        staged input pipeline (host ETL thread -> device prefetch, see
        nn/netbase._stage_input_pipeline) feeds the loop; prefetch_buffer
        is the host stage's queue depth. `hang_timeout` (seconds) arms the
        hang watchdog: a step making no progress for that long raises
        utils.health.StepHangError carrying a flight-recorder dump path
        instead of blocking forever. Pick it above the worst-case single
        phase — the first step's trace+compile and the longest legitimate
        data wait both count as "no progress" if they exceed it.
        `resume_from` names a checkpoint directory (CheckpointListener):
        the newest checkpoint is loaded into this net, the iterator is
        fast-forwarded to the saved mid-epoch position, and training
        continues to the same loss curve as an uninterrupted run; an
        empty directory starts fresh, so the same command line works on
        first boot and after a preemption. `epochs` stays the TOTAL
        target — already-completed epochs are not re-run. `run_ledger`
        opts this fit into persistent metrics recording + SLO judgment
        (utils/runledger): a path records a per-run ledger artifact
        there, a RunLedger instance is attached for the fit's duration;
        None (the default) keeps the fit-loop ledger hook at one flag
        check per step."""
        self._require_init()
        if self.conf.pretrain and not getattr(self, "_pretrained", False):
            self.pretrain(data, batch_size=batch_size)
            self._pretrained = True
        iterator = self._as_iterator(data, labels, batch_size)
        return self._run_fit(iterator, epochs, async_prefetch,
                             prefetch_buffer, hang_timeout=hang_timeout,
                             resume_from=resume_from,
                             run_ledger=run_ledger)

    def _as_iterator(self, data, labels, batch_size) -> DataSetIterator:
        if isinstance(data, DataSetIterator):
            return data
        if isinstance(data, DataSet):
            return ListDataSetIterator(data, batch_size)
        x = np.asarray(data)
        y = np.asarray(labels)
        return ListDataSetIterator(DataSet(x, y), batch_size)

    def _fit_dataset(self, ds: DataSet):
        algo = self.net_conf.optimization_algo
        if algo != "sgd":
            self._fit_line_search(ds, algo)
            return
        tbptt = (
            self.conf.backprop_type == BackpropType.TRUNCATED_BPTT
            and ds.features.ndim == 3
        )
        if tbptt:
            self._fit_tbptt(ds)
        else:
            states, score = self._fit_step(
                ds.features, ds.labels, ds.features_mask, ds.labels_mask
            )
            self.state_list = states
            self._notify(getattr(ds, "reported_examples", None)
                         or ds.num_examples(), ds)

    def _fit_line_search(self, ds: DataSet, algo: str):
        """Line-search optimizer path (LBFGS/CG/line GD): host-side search
        loop around the compiled value+gradient function (reference:
        BaseOptimizer.optimize :182-230). One optimize() call per batch."""
        from deeplearning4j_tpu.nn.params import flat_to_params, params_to_flat
        from deeplearning4j_tpu.train.solvers import (
            _FlatProblem,
            make_line_search_optimizer,
        )

        if getattr(self, "_solver", None) is None or self._solver.name != algo:
            self._solver = make_line_search_optimizer(algo)
            self._flat_problem = _FlatProblem(self)
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.net_conf.seed ^ 0x5EED), self.iteration
        )
        problem = self._flat_problem.bind(self.state_list, x, y, fm, lm, rng)
        flat = params_to_flat(self.layer_confs, self.params_list)
        step0 = schedule_lr(self.net_conf, self.iteration)
        new_flat, f_new = self._solver.optimize(problem, flat, step0)
        self.params_list = flat_to_params(self.layer_confs, self.params_list, new_flat)
        self._score = jnp.asarray(f_new)
        # no in-graph diagnostic on the line-search path: the sentinel
        # degrades to the finite check on the score alone
        self._step_diag = None
        self.iteration += 1
        self._notify(getattr(ds, "reported_examples", None)
                         or ds.num_examples(), ds)

    def _fit_tbptt(self, ds: DataSet):
        """Truncated BPTT: split time into segments of tbptt_fwd_length and
        carry RNN state across segments (reference:
        MultiLayerNetwork.doTruncatedBPTT :1333). When tbptt_bwd_length <
        tbptt_fwd_length, each segment's gradient is truncated to its last
        bwd_length timesteps (config tBPTTBackwardLength).

        When the batch has no ragged tail (T divisible by seg), no
        listeners are attached, and stats collection is off, all segments
        run in ONE jitted dispatch (`_build_tbptt_fused_step`) — same math,
        ~n_seg fewer host->device round-trips. Listeners keep the loop path
        so per-iteration callbacks observe the params of *their* iteration.
        """
        T = ds.features.shape[1]
        seg = int(self.conf.tbptt_fwd_length)
        bwd = int(self.conf.tbptt_bwd_length)
        n_seg = -(-T // seg)
        if (
            T == n_seg * seg
            and not self.listeners
            and not getattr(self, "_collect_stats", False)
        ):
            self._fit_tbptt_fused(ds, n_seg, seg, bwd)
            return
        # seed zero RNN state for recurrent layers
        states = list(self.state_list)
        for i, conf in enumerate(self.layer_confs):
            if _is_recurrent(conf) and states[i] is None:
                states[i] = {}

        def cut_mask(m, sl):
            if m is None:
                return None
            return m if m.ndim == 1 else m[:, sl]  # 1-D = per-example mask

        def cut(sl):
            fm = cut_mask(ds.features_mask, sl)
            lm = cut_mask(ds.labels_mask, sl)
            labels = ds.labels[:, sl] if ds.labels.ndim == 3 else ds.labels
            return (ds.features[:, sl], labels, fm, lm)

        for start in range(0, T, seg):
            end = min(start + seg, T)
            if bwd < end - start:
                boundary = end - bwd
                states, _ = self._fit_step_truncated(
                    cut(slice(start, boundary)), cut(slice(boundary, end)),
                    stateful_states=states,
                )
            else:
                states, _ = self._fit_step(
                    *cut(slice(start, end)), stateful_states=states
                )
            self._notify(getattr(ds, "reported_examples", None)
                         or ds.num_examples(), ds)
        # persist only non-RNN state (running stats); RNN carry is per-batch
        self.state_list = [
            st if not _is_recurrent(conf) else self.state_list[i]
            for i, (conf, st) in enumerate(zip(self.layer_confs, states))
        ]

    def _fit_tbptt_fused(self, ds: DataSet, n_seg: int, seg: int, bwd: int):
        """Run one TBPTT fit batch through the single-dispatch fused step
        (see `_build_tbptt_fused_step`). Host work: the lr schedule values
        for the n_seg optimizer steps and one call."""
        sig = (n_seg, seg, bwd)
        cached = getattr(self, "_fused_tbptt_fn", None)
        if cached is None or cached[0] != sig:
            self._fused_tbptt_fn = (
                sig, self._build_tbptt_fused_step(n_seg, seg, bwd)
            )
        step_fn = self._fused_tbptt_fn[1]
        states = list(self.state_list)
        for i, conf in enumerate(self.layer_confs):
            if _is_recurrent(conf) and states[i] is None:
                states[i] = {}
        lrs = jnp.asarray(
            [schedule_lr(self.net_conf, self.iteration + i)
             for i in range(n_seg)],
            jnp.float32,
        )
        data = tuple(
            None if a is None else jnp.asarray(a)
            for a in (ds.features, ds.labels, ds.features_mask,
                      ds.labels_mask)
        )
        params, states, upd, _scores, last, diag = step_fn(
            self.params_list, states, self.upd_state, data, lrs,
            jnp.asarray(self.iteration, jnp.uint32), None,
        )
        self.params_list = params
        self.upd_state = upd
        self._score = last
        self._step_diag = diag
        self._last_stats = None
        self.iteration += n_seg
        # persist only non-RNN state (running stats); RNN carry is per-batch
        self.state_list = [
            st if not _is_recurrent(conf) else self.state_list[i]
            for i, (conf, st) in enumerate(zip(self.layer_confs, states))
        ]

    # -- multi-batch fused fit (set_fused_steps) -----------------------------

    def _fused_fit_supported(self) -> bool:
        return self.net_conf.optimization_algo == "sgd"

    def _fit_datasets_fused(self, ds_list):
        """K same-shape minibatches in ONE jitted dispatch (see
        NetworkBase.set_fused_steps). Dispatches to the cross-batch TBPTT
        program for 3-d TBPTT batches, the stacked-scan program otherwise;
        anything ineligible (ragged TBPTT tail) falls back per-batch."""
        d0 = ds_list[0]
        if (
            self.conf.backprop_type == BackpropType.TRUNCATED_BPTT
            and d0.features.ndim == 3
        ):
            T = d0.features.shape[1]
            seg = int(self.conf.tbptt_fwd_length)
            bwd = int(self.conf.tbptt_bwd_length)
            n_seg = -(-T // seg)
            if T != n_seg * seg:
                for d in ds_list:
                    self._fit_dataset(d)
                return
            self._fit_tbptt_batched(ds_list, n_seg, seg, bwd)
            return
        self._fit_std_batched(ds_list)

    @staticmethod
    def _stack_datasets(ds_list):
        stack = lambda vals: (
            None if vals[0] is None
            else jnp.stack([jnp.asarray(v) for v in vals])
        )
        return (
            stack([d.features for d in ds_list]),
            stack([d.labels for d in ds_list]),
            stack([d.features_mask for d in ds_list]),
            stack([d.labels_mask for d in ds_list]),
        )

    def _build_multi_fit_step(self, K: int):
        """K standard optimizer steps as one `lax.scan` over the stacked
        batches — same per-step lr/t/rng derivation as `_run_step`, K-1
        fewer dispatches (equivalence: tests/test_fused_fit.py)."""
        assert not getattr(self, "_collect_stats", False)
        body = self._make_step_body(self._std_loss_builder())
        seed_key_base = self.net_conf.seed ^ 0x5EED

        def step(params, states, upd_state, data_stack, lrs, t0):
            key = jax.random.PRNGKey(seed_key_base)

            def scan_body(carry, inp):
                p, st, us = carry
                data_i, lr, i = inp
                rng, t = self._step_rng_and_t(key, t0, i)
                p, st, us, sc, dg = body(p, st, us, data_i, lr, t, rng)
                return (p, st, us), (sc, dg)

            (params, states, upd_state), (scores, diags) = jax.lax.scan(
                scan_body, (params, states, upd_state),
                (data_stack, lrs, jnp.arange(K, dtype=jnp.uint32)))
            diag = jnp.stack([diags[-1, 0], jnp.max(diags[:, 1])])
            return params, states, upd_state, scores[-1], diag

        # stacked batches: [K, B, ...] — under a mesh plan the batch dim
        # (1, not 0) shards over the data axis
        return self._jit_step(step, stacked_data=True)

    def _fit_std_batched(self, ds_list):
        K = len(ds_list)
        cached = getattr(self, "_multi_fit_fn", None)
        if cached is None or cached[0] != K:
            self._multi_fit_fn = (K, self._build_multi_fit_step(K))
        fn = self._multi_fit_fn[1]
        data = self._stack_datasets(ds_list)
        lrs = jnp.asarray(
            [schedule_lr(self.net_conf, self.iteration + i)
             for i in range(K)], jnp.float32)
        params, states, upd, last, diag = fn(
            self.params_list, self.state_list, self.upd_state, data, lrs,
            jnp.asarray(self.iteration, jnp.uint32))
        self.params_list = params
        self.upd_state = upd
        self.state_list = states
        self._score = last
        self._step_diag = diag
        self._last_stats = None
        self.iteration += K

    def _build_tbptt_batched_step(self, K: int, n_seg: int, seg: int,
                                  bwd: int):
        """K TBPTT fit batches (each n_seg segments, RNN state reset at
        every batch boundary, BN stats carried throughout) in ONE jitted
        dispatch. Batch 0's segment 0 runs inline to bootstrap the RNN
        carry structure ({} -> {"h","c"}); batches 1..K-1 scan with a
        zeros reset — identical math to K calls of `_fit_tbptt` (the
        layer seeds zero state for {} exactly as `reset` writes zeros;
        equivalence: tests/test_fused_fit.py)."""
        assert not getattr(self, "_collect_stats", False)
        body = self._make_step_body(
            self._trunc_loss_builder() if bwd < seg
            else self._std_loss_builder()
        )
        seed_key_base = self.net_conf.seed ^ 0x5EED
        seg_data = self._make_seg_data(seg, bwd)
        rec = [_is_recurrent(c) for c in self.layer_confs]

        def reset_rnn(states):
            return [
                jax.tree_util.tree_map(jnp.zeros_like, st) if is_r else st
                for st, is_r in zip(states, rec)
            ]

        def step(params, states, upd_state, data_stack, lrs, t0,
                 _rng_unused):
            key = jax.random.PRNGKey(seed_key_base)
            pick = lambda b: tuple(
                None if a is None else a[b] for a in data_stack)

            def run_seg(p, st, us, data_b, i_seg, j):
                rng, t = self._step_rng_and_t(key, t0, j)
                x, y, fm, lm = data_b
                return body(p, st, us, seg_data(x, y, fm, lm, i_seg),
                            lrs[j], t, rng)

            # batch 0 / segment 0 inline: bootstraps the carry structure
            data0 = pick(0)
            params, states, upd_state, _, d00 = run_seg(
                params, states, upd_state, data0, 0, 0)
            gmax = d00[1]
            if n_seg > 1:
                def seg_scan0(carry, i):
                    p, st, us = carry
                    p, st, us, sc, dg = run_seg(p, st, us, data0, i, i)
                    return (p, st, us), dg

                (params, states, upd_state), dgs0 = jax.lax.scan(
                    seg_scan0, (params, states, upd_state),
                    jnp.arange(1, n_seg))
                gmax = jnp.maximum(gmax, jnp.max(dgs0[:, 1]))

            def batch_body(carry, b):
                p, st, us = carry
                st = reset_rnn(st)
                data_b = pick(b)

                def seg_scan(c2, s):
                    p2, st2, us2 = c2
                    p2, st2, us2, sc, dg = run_seg(
                        p2, st2, us2, data_b, s, b * n_seg + s)
                    return (p2, st2, us2), (sc, dg)

                (p, st, us), (scs, dgs) = jax.lax.scan(
                    seg_scan, (p, st, us), jnp.arange(n_seg))
                return (p, st, us), (scs[-1], jnp.max(dgs[:, 1]))

            (params, states, upd_state), (lasts, gmaxes) = jax.lax.scan(
                batch_body, (params, states, upd_state),
                jnp.arange(1, K))
            diag = jnp.stack([lasts[-1],
                              jnp.maximum(gmax, jnp.max(gmaxes))])
            return params, states, upd_state, lasts[-1], diag

        return self._jit_step(step, stacked_data=True)

    def _fit_tbptt_batched(self, ds_list, n_seg: int, seg: int, bwd: int):
        K = len(ds_list)
        if K == 1:
            self._fit_tbptt_fused(ds_list[0], n_seg, seg, bwd)
            return
        sig = (K, n_seg, seg, bwd)
        cached = getattr(self, "_tbptt_batched_fn", None)
        if cached is None or cached[0] != sig:
            self._tbptt_batched_fn = (
                sig, self._build_tbptt_batched_step(K, n_seg, seg, bwd))
        fn = self._tbptt_batched_fn[1]
        states = list(self.state_list)
        for i, conf in enumerate(self.layer_confs):
            if _is_recurrent(conf) and states[i] is None:
                states[i] = {}
        data = self._stack_datasets(ds_list)
        lrs = jnp.asarray(
            [schedule_lr(self.net_conf, self.iteration + j)
             for j in range(K * n_seg)], jnp.float32)
        params, states, upd, last, diag = fn(
            self.params_list, states, self.upd_state, data, lrs,
            jnp.asarray(self.iteration, jnp.uint32), None)
        self.params_list = params
        self.upd_state = upd
        self._score = last
        self._step_diag = diag
        self._last_stats = None
        self.iteration += K * n_seg
        self.state_list = [
            st if not _is_recurrent(conf) else self.state_list[i]
            for i, (conf, st) in enumerate(zip(self.layer_confs, states))
        ]

    # -- inference -----------------------------------------------------------

    def output(self, x, training: bool = False):
        """Full forward pass (reference: MultiLayerNetwork.output).
        training=True gives train-mode activations (dropout active, batch
        statistics) with a deterministic per-call rng.

        The jit cache is keyed on (training, input shape, dtype), and every
        insertion bumps `output_compile_count` — serving layers
        (ParallelInference /metrics) read it so that shape-driven compile
        storms show up as a counter instead of mystery tail latency."""
        self._require_init()
        xx = jnp.asarray(x)

        def make_fn():
            def fwd(params, states, xx, rng):
                xx = self.policy.cast_input(xx)
                out, _ = self._forward(params, states, xx,
                                       training=training, rng=rng)
                return self.policy.cast_output(out)

            return jax.jit(fwd)

        fn = self._cached_output_fn(
            (training, xx.shape, str(xx.dtype)), make_fn)
        rng = (
            jax.random.PRNGKey(self.net_conf.seed ^ 0xD0) if training else None
        )
        return fn(self.params_list, self.state_list, xx, rng)

    def feed_forward(self, x):
        """Per-layer activations list (reference: feedForward family
        :725-831). Not jitted — debugging/inspection path."""
        self._require_init()
        acts = []
        xx = jnp.asarray(x)
        timesteps = xx.shape[1] if xx.ndim == 3 else None
        for i, conf in enumerate(self.layer_confs):
            pp = self.conf.preprocessors.get(str(i))
            if pp is not None:
                xx = pp(xx, {"timesteps": timesteps})
            if xx.ndim == 3:
                timesteps = xx.shape[1]
            ctx = LayerContext(training=False, state=self.state_list[i],
                               timesteps=timesteps)
            xx, _ = forward_layer(conf, self.params_list[i], xx, ctx)
            acts.append(xx)
        return acts

    def score(self, data, labels=None) -> float:
        """Loss on a dataset without updating (reference:
        MultiLayerNetwork.score(DataSet))."""
        self._require_init()
        if isinstance(data, DataSet):
            ds = data
        else:
            ds = DataSet(np.asarray(data), np.asarray(labels))
        s, _ = self._loss(
            self.params_list, self.state_list,
            jnp.asarray(ds.features), jnp.asarray(ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
            rng=None, training=False,
        )
        return float(s)

    def evaluate(self, data, labels=None, batch_size: int = 256) -> Evaluation:
        """Classification evaluation (reference: evaluate/doEvaluation
        :2605-2646)."""
        ev = Evaluation()
        for ds in self._eval_batches(data, labels, batch_size):
            out = self.output(ds.features)
            ev.eval_batch(ds.labels, out, ds.labels_mask)
        return ev

    def evaluate_regression(self, data, labels=None, batch_size: int = 256):
        ev = RegressionEvaluation()
        for ds in self._eval_batches(data, labels, batch_size):
            out = self.output(ds.features)
            ev.eval_batch(ds.labels, out, ds.labels_mask)
        return ev

    def _eval_batches(self, data, labels, batch_size):
        if isinstance(data, DataSetIterator):
            yield from data
        elif isinstance(data, DataSet):
            yield from data.split_batches(batch_size)
        else:
            yield from DataSet(np.asarray(data), np.asarray(labels)).split_batches(batch_size)

    # -- rnn streaming inference ---------------------------------------------

    def _rnn_layer_size(self, i: int) -> int:
        conf = self.layer_confs[i]
        inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
        return int(inner.n_out)

    def rnn_zero_carry(self, batch: int) -> dict:
        """Zero recurrent carry for a `batch`-wide stream: {layer index
        -> {"h", "c"} [batch, H]} for every recurrent layer — the state
        a fresh rnn_time_step stream (or a freshly admitted decode slot)
        starts from. Dtype is the compute dtype, matching the zeros the
        scan itself would seed."""
        self._require_init()
        dt = self.policy.compute_dtype
        return {
            i: {"h": jnp.zeros((batch, self._rnn_layer_size(i)), dt),
                "c": jnp.zeros((batch, self._rnn_layer_size(i)), dt)}
            for i, c in enumerate(self.layer_confs) if _is_recurrent(c)
        }

    def _rnn_seed_states(self, carry: dict, batch: int):
        """Full state list for a streaming step: recurrent layers from
        `carry` (zero-seeded when absent — host-side, so the jitted
        program's state STRUCTURE is constant and the first call shares
        the steady-state trace), everything else fresh from state_list
        (BN running stats must match output() even after an interleaved
        fit())."""
        dt = self.policy.compute_dtype
        states = []
        for i, c in enumerate(self.layer_confs):
            if _is_recurrent(c):
                st = carry.get(i)
                if st is None:
                    H = self._rnn_layer_size(i)
                    st = {"h": jnp.zeros((batch, H), dt),
                          "c": jnp.zeros((batch, H), dt)}
                states.append(st)
            else:
                states.append(self.state_list[i])
        return states

    def rnn_time_step(self, x):
        """Stateful streaming inference (reference:
        MultiLayerNetwork.rnnTimeStep). x: [batch, time, nIn] (or
        [batch, nIn] for a single step).

        The streaming step is jitted with a shape-keyed cache (the same
        discipline as `output()`: keyed on (batch, time, nIn, dtype),
        each insertion bumps `output_compile_count`) — a mixed-size
        stream costs one trace per shape, not one per call. A call whose
        batch size differs from the carried state starts a NEW stream:
        the stale carry is dropped (loudly) instead of leaking a
        previous caller's hidden state into this one."""
        self._require_init()
        xx = jnp.asarray(x)
        single = xx.ndim == 2
        if single:
            xx = xx[:, None, :]
        bsz = xx.shape[0]
        # only the recurrent carry persists between calls
        carry = self._rnn_states or {}
        if carry and any(v.shape[0] != bsz
                         for st in carry.values() for v in st.values()):
            logger.warning(
                "rnn_time_step batch size changed (carried %d, got %d): "
                "dropping the previous stream's state — call "
                "clear_rnn_state() between streams to silence this",
                next(iter(carry.values()))["h"].shape[0], bsz)
            carry = {}
            self._rnn_states = None
        states = self._rnn_seed_states(carry, bsz)

        def make_fn():
            def fwd(params, states, xx):
                out, new_states = self._forward(
                    params, states, self.policy.cast_input(xx),
                    training=False, rng=None, stateful=True,
                )
                return self.policy.cast_output(out), new_states

            return jax.jit(fwd)

        fn = self._cached_output_fn(
            ("rnn_step", xx.shape, str(xx.dtype)), make_fn)
        out, new_states = fn(self.params_list, states, xx)
        merged = self._merge_states(states, new_states)
        self._rnn_states = {
            i: merged[i]
            for i, c in enumerate(self.layer_confs) if _is_recurrent(c)
        }
        return out[:, 0] if single else out

    def rnn_clear_previous_state(self):
        self._rnn_states = None

    def clear_rnn_state(self):
        """Reset the streaming-inference state — the next rnn_time_step
        call starts a fresh stream (alias of rnn_clear_previous_state)."""
        self.rnn_clear_previous_state()

    def rnn_decode_step_fn(self):
        """Pure single-step decode function for the continuous-batching
        serving tier (serving/decode.py):

            (params, states, carry, x) -> (new_carry, out)

        `x` is ONE timestep [batch, nIn]; `carry` maps recurrent layer
        index -> {"h", "c"} [batch, H] (see `rnn_zero_carry`); `states`
        is the net's state_list (recurrent entries ignored in favor of
        `carry`); `out` is the post-activation output row [batch, nOut].
        Closed over the configuration only — params/states/carry are
        ARGUMENTS, which is what makes the decode engine's zero-downtime
        weight swap compile-free: the jitted program is keyed on shapes,
        not parameter values. jit-safe; the caller owns the jit and its
        cache."""
        self._require_init()
        rec = frozenset(
            i for i, c in enumerate(self.layer_confs) if _is_recurrent(c))

        def step(params, states, carry, x):
            xx = self.policy.cast_input(x)[:, None, :]
            st = [carry[i] if i in rec else states[i]
                  for i in range(len(self.layer_confs))]
            out, new_states = self._forward(
                params, st, xx, training=False, rng=None, stateful=True,
            )
            new_carry = {
                i: (new_states[i] if new_states[i] is not None else st[i])
                for i in rec
            }
            return new_carry, self.policy.cast_output(out[:, 0])

        return step

    def clone(self) -> "MultiLayerNetwork":
        import copy

        other = MultiLayerNetwork(copy.deepcopy(self.conf))
        if self.params_list is not None:
            other.init()
            other.params_list = jax.tree_util.tree_map(lambda a: a, self.params_list)
            other.state_list = [
                None if s is None else dict(s) for s in self.state_list
            ]
            # the clone resumes training equivalently: updater state
            # (momentum/Adam moments) + counters (LR schedule position)
            # travel with it (reference: MultiLayerNetwork.clone carries
            # the updater)
            other.upd_state = jax.tree_util.tree_map(lambda a: a, self.upd_state)
            other.iteration = self.iteration
            other.epoch = self.epoch
        return other
