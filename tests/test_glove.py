"""GloVe (nlp/glove.py) — co-occurrence semantics, AdaGrad weighted-lsq
training, native/Python accumulation parity, serializer round-trip.

Mirrors the reference's GloveTest strategy (small corpus, similarity
sanity) against the two-topic synthetic corpus used by the word2vec
tests; co-occurrence values are additionally pinned by hand against the
AbstractCoOccurrences.java:322-374 semantics (forward window, 1/distance
weights, symmetric mirroring)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    Glove,
    VectorsConfiguration,
    WordVectorSerializer,
)
from deeplearning4j_tpu.nlp.glove import cooccurrences_indexed

ANIMALS = ["cat", "dog", "horse", "cow", "sheep"]
TECH = ["cpu", "gpu", "ram", "disk", "cache"]


def _corpus(n=400, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        group = ANIMALS if rng.random() < 0.5 else TECH
        out.append([str(w) for w in rng.choice(group, size=8)])
    return out


def _to_dense(rows, cols, vals, V):
    X = np.zeros((V, V), np.float64)
    for r, c, v in zip(rows, cols, vals):
        X[r, c] += v
    return X


def test_cooccurrence_hand_computed():
    # sentence [0, 1, 2], window 2, symmetric:
    #   (0,1): 1/1   (0,2): 1/2   (1,2): 1/1   + mirrors
    rows, cols, vals = cooccurrences_indexed(
        [np.array([0, 1, 2])], window=2, symmetric=True)
    X = _to_dense(rows, cols, vals, 3)
    expect = np.array([[0, 1.0, 0.5],
                       [1.0, 0, 1.0],
                       [0.5, 1.0, 0]])
    np.testing.assert_allclose(X, expect)
    # asymmetric keeps only the forward direction
    rows, cols, vals = cooccurrences_indexed(
        [np.array([0, 1, 2])], window=2, symmetric=False)
    X = _to_dense(rows, cols, vals, 3)
    np.testing.assert_allclose(X, np.triu(expect))
    # window clips at sentence end; repeated pairs accumulate
    rows, cols, vals = cooccurrences_indexed(
        [np.array([0, 1, 0, 1])], window=1, symmetric=False)
    X = _to_dense(rows, cols, vals, 2)
    np.testing.assert_allclose(X, [[0, 2.0], [1.0, 0]])


def test_native_matches_python_accumulation(tmp_path):
    native_mod = pytest.importorskip("deeplearning4j_tpu.native")
    if not native_mod.native_available():
        pytest.skip("no C++ toolchain")
    corpus = _corpus(60)
    path = tmp_path / "corpus.txt"
    path.write_text("\n".join(" ".join(s) for s in corpus) + "\n")
    with native_mod.NativeCorpus(str(path)) as nc:
        words, _counts = nc.vocab(1)
        n_rows, n_cols, n_vals = nc.cooccurrences(1, window=4,
                                                  symmetric=True)
        indexed = nc.indexed_sentences(1)
    rows, cols, vals = cooccurrences_indexed(indexed, window=4,
                                             symmetric=True)
    V = len(words)
    np.testing.assert_allclose(_to_dense(n_rows, n_cols, n_vals, V),
                               _to_dense(rows, cols, vals, V), rtol=1e-6)


def test_glove_learns_clusters():
    conf = VectorsConfiguration(
        layer_size=24, window=4, min_word_frequency=1, epochs=25,
        learning_rate=0.05, batch_size=1024, seed=7, x_max=10.0)
    glove = Glove(conf, _corpus())
    glove.fit()
    near = [w for w, _ in glove.words_nearest("cat", 4)]
    assert set(near) == set(ANIMALS) - {"cat"}, near
    assert glove.similarity("cat", "dog") > glove.similarity("cat", "gpu")
    assert np.isfinite(glove.last_loss)


def test_glove_fit_file_native_path(tmp_path):
    corpus = _corpus(200)
    path = tmp_path / "corpus.txt"
    path.write_text("\n".join(" ".join(s) for s in corpus) + "\n")
    conf = VectorsConfiguration(
        layer_size=16, window=4, min_word_frequency=1, epochs=20,
        learning_rate=0.05, batch_size=1024, seed=3, x_max=10.0)
    glove = Glove(conf)
    glove.fit_file(str(path))
    assert glove.similarity("cat", "dog") > glove.similarity("cat", "gpu")


def test_glove_serializer_round_trip(tmp_path):
    conf = VectorsConfiguration(
        layer_size=12, window=4, min_word_frequency=1, epochs=5,
        learning_rate=0.05, batch_size=512, seed=1, x_max=10.0)
    glove = Glove(conf, _corpus(80))
    glove.fit()
    txt = tmp_path / "glove.txt"
    WordVectorSerializer.write_word_vectors(glove, str(txt))
    back = WordVectorSerializer.read_word_vectors(str(txt))
    for w in ("cat", "gpu"):
        np.testing.assert_allclose(back.word_vector(w),
                                   glove.word_vector(w), atol=1e-5)
    binp = tmp_path / "glove.bin"
    WordVectorSerializer.write_google_binary(glove, str(binp))
    back2 = WordVectorSerializer.read_google_binary(str(binp))
    np.testing.assert_allclose(back2.word_vector("dog"),
                               glove.word_vector("dog"), atol=1e-6)
