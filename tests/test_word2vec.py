"""Word2Vec / ParagraphVectors / serializer behavior tests.

Mirrors the reference's Word2VecTests / ParagraphVectorsTest strategy
(small corpora, similarity/ranking sanity — SURVEY.md §4) with a
deterministic synthetic two-topic corpus instead of raw text files."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    ParagraphVectors,
    VectorsConfiguration,
    Word2Vec,
    WordVectorSerializer,
)

ANIMALS = ["cat", "dog", "horse", "cow", "sheep"]
TECH = ["cpu", "gpu", "ram", "disk", "cache"]


def _corpus(n=400, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        group = ANIMALS if rng.random() < 0.5 else TECH
        out.append(" ".join(rng.choice(group, size=8)))
    return out


def _cluster_check(model):
    """Nearest neighbors of a word are its topic cluster."""
    near = [w for w, _ in model.words_nearest("cat", 4)]
    assert set(near) == set(ANIMALS) - {"cat"}, near
    assert model.similarity("cat", "dog") > model.similarity("cat", "gpu")


def _build(corpus, **kw):
    b = (
        Word2Vec.Builder().min_word_frequency(1).layer_size(24)
        .window_size(4).epochs(10).learning_rate(0.05).seed(7)
        .batch_size(1024).iterate(corpus)
    )
    for k, v in kw.items():
        getattr(b, k)(v)
    return b.build()


def test_skipgram_hs_learns_clusters():
    w2v = _build(_corpus(), use_hierarchic_softmax=True, negative_sample=0)
    w2v.fit()
    _cluster_check(w2v)


def test_skipgram_negative_sampling_learns_clusters():
    w2v = _build(_corpus(), use_hierarchic_softmax=False, negative_sample=5)
    w2v.fit()
    _cluster_check(w2v)


def test_cbow_learns_clusters():
    w2v = _build(
        _corpus(), use_hierarchic_softmax=True, negative_sample=5,
        elements_learning_algorithm="cbow",
    )
    w2v.fit()
    _cluster_check(w2v)


def test_unknown_word_and_has_word():
    w2v = _build(_corpus(100), negative_sample=5)
    w2v.fit()
    assert w2v.has_word("cat") and not w2v.has_word("zebra")
    assert w2v.word_vector("zebra") is None
    assert np.isnan(w2v.similarity("cat", "zebra"))


def test_serializer_round_trips(tmp_path):
    w2v = _build(_corpus(100))
    w2v.fit()
    # text
    p = str(tmp_path / "vecs.txt")
    WordVectorSerializer.write_word_vectors(w2v, p)
    back = WordVectorSerializer.read_word_vectors(p)
    np.testing.assert_allclose(
        back.word_vector("cat"), w2v.word_vector("cat"), atol=1e-5
    )
    # google binary
    p = str(tmp_path / "vecs.bin")
    WordVectorSerializer.write_google_binary(w2v, p)
    back = WordVectorSerializer.read_google_binary(p)
    assert back.vocab.words() == w2v.vocab.words()
    np.testing.assert_allclose(
        back.word_vector("dog"), w2v.word_vector("dog"), atol=1e-6
    )
    # full model (resume-capable: tables + counts round-trip)
    p = str(tmp_path / "full.zip")
    WordVectorSerializer.write_full_model(w2v, p)
    full = WordVectorSerializer.read_full_model(p)
    assert full.vocab.word_frequency("cat") == w2v.vocab.word_frequency("cat")
    np.testing.assert_allclose(
        np.asarray(full.lookup.syn1), np.asarray(w2v.lookup.syn1), atol=1e-6
    )
    _cluster_check(full)


def _pv_conf():
    return VectorsConfiguration(
        layer_size=24, min_word_frequency=1, epochs=12, learning_rate=0.05,
        negative=5, use_hierarchic_softmax=False, window=4, batch_size=256,
        seed=11,
    )


def _docs(seed=3):
    rng = np.random.default_rng(seed)
    docs = [" ".join(rng.choice(ANIMALS, 10)) for _ in range(20)] + [
        " ".join(rng.choice(TECH, 10)) for _ in range(20)
    ]
    return docs, [f"doc_{i}" for i in range(40)]


@pytest.mark.parametrize("algo", ["dm", "dbow"])
def test_paragraph_vectors(algo):
    docs, labels = _docs()
    pv = ParagraphVectors(_pv_conf(), docs, labels,
                          sequence_learning_algorithm=algo)
    pv.fit()
    # doc vectors cluster by topic
    dv = np.asarray(pv.doc_vectors)
    dvn = dv / np.linalg.norm(dv, axis=1, keepdims=True)
    sims = dvn @ dvn.T
    within = (sims[:20, :20].mean() + sims[20:, 20:].mean()) / 2
    across = sims[:20, 20:].mean()
    assert within > across + 0.1, (within, across)
    # inference places an unseen doc in the right cluster
    v = pv.infer_vector(" ".join(["cat", "dog", "cow"] * 3), steps=10)
    near = pv.nearest_labels(v, top_n=5)
    hits = sum(1 for l, _ in near if int(l.split("_")[1]) < 20)
    assert hits >= 4, near
