"""End-to-end milestone test: LeNet on MNIST (SURVEY.md §7 stage 6).
Uses the synthetic fallback when no cached/downloadable MNIST (CI has no
egress); the pipeline, model and training path are identical either way."""

import numpy as np

from deeplearning4j_tpu.data.mnist import (
    MnistDataFetcher,
    MnistDataSetIterator,
    synthetic_mnist,
)
from deeplearning4j_tpu.models import lenet_network


def test_synthetic_mnist_deterministic():
    x1, y1 = synthetic_mnist(64, seed=3)
    x2, y2 = synthetic_mnist(64, seed=3)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    assert x1.shape == (64, 28, 28) and x1.dtype == np.uint8
    assert set(np.unique(y1)) <= set(range(10))


def test_iterator_shapes_and_normalization():
    it = MnistDataSetIterator(32, train=True, num_examples=128,
                              fetcher=MnistDataFetcher(allow_download=False))
    batches = list(it)
    assert len(batches) == 4
    b = batches[0]
    assert b.features.shape == (32, 784)
    assert b.labels.shape == (32, 10)
    assert 0.0 <= b.features.min() and b.features.max() <= 1.0
    np.testing.assert_allclose(b.labels.sum(axis=1), np.ones(32))


def test_lenet_trains_to_high_accuracy():
    train_it = MnistDataSetIterator(64, train=True, num_examples=2048,
                                    fetcher=MnistDataFetcher(allow_download=False))
    test_it = MnistDataSetIterator(256, train=False, num_examples=512,
                                   fetcher=MnistDataFetcher(allow_download=False))
    net = lenet_network(learning_rate=0.02)
    net.fit(train_it, epochs=2)
    ev = net.evaluate(test_it)
    # reference exit criterion: Evaluation accuracy >= reference's LeNet
    # (~0.98 on real MNIST after an epoch); synthetic digits are easier
    assert ev.accuracy() > 0.95, ev.stats()
