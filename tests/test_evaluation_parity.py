"""Evaluation output parity — golden values computed BY HAND from the
reference's definitions (eval/Evaluation.java):

- confusion[actual][predicted] counts over argmax'd rows, masked
  timesteps excluded (evalTimeSeries semantics)
- precision(i) = tp_i / colsum_i, recall(i) = tp_i / rowsum_i
- macro precision/recall exclude 0/0 classes (Evaluation.java:572-590)
- macro F1 = MEAN of per-class F1 over classes where both precision and
  recall are defined (fBeta Macro, :954-965); for exactly 2 classes,
  f1() is class 1's binary F1 (:949-952)
"""

import numpy as np

from deeplearning4j_tpu.train.evaluation import Evaluation


def _onehot(idx, k):
    y = np.zeros((len(idx), k), np.float32)
    y[np.arange(len(idx)), idx] = 1.0
    return y


def test_masked_multiclass_golden():
    """4-class time-series with a mask; every metric pinned to values
    computed from the reference's formulas (comments show the sums)."""
    # [batch=2, time=4] actual / predicted class ids; mask kills 3 steps
    actual = np.array([[0, 1, 2, 3],
                       [1, 1, 2, 0]])
    pred = np.array([[0, 2, 2, 3],
                     [1, 0, 2, 3]])
    mask = np.array([[1, 1, 1, 0],      # (0,3): actual 3/pred 3 dropped
                     [1, 1, 0, 1]])     # (1,2): actual 2/pred 2 dropped
    labels = _onehot(actual.reshape(-1), 4).reshape(2, 4, 4)
    # probabilities: put 0.7 at predicted, spread the rest — argmax == pred
    probs = np.full((8, 4), 0.1, np.float32)
    probs[np.arange(8), pred.reshape(-1)] = 0.7
    probs = probs.reshape(2, 4, 4)

    ev = Evaluation()
    ev.eval_batch(labels, probs, mask=mask)

    # surviving (actual, pred) pairs:
    # (0,0) (1,2) (2,2) | (1,1) (1,0) (0,3)
    want_conf = np.zeros((4, 4), np.int64)
    for a, p in [(0, 0), (1, 2), (2, 2), (1, 1), (1, 0), (0, 3)]:
        want_conf[a, p] += 1
    np.testing.assert_array_equal(ev.confusion, want_conf)

    # accuracy = (tp0+tp1+tp2+tp3)/6 = (1+1+1+0)/6
    assert abs(ev.accuracy() - 3 / 6) < 1e-9

    # per-class precision: tp/colsum -> 1/2, 1/1, 1/2, 0/1
    assert abs(ev.precision(0) - 0.5) < 1e-9
    assert abs(ev.precision(1) - 1.0) < 1e-9
    assert abs(ev.precision(2) - 0.5) < 1e-9
    assert abs(ev.precision(3) - 0.0) < 1e-9
    # macro precision: all four classes have predictions -> mean
    assert abs(ev.precision() - (0.5 + 1.0 + 0.5 + 0.0) / 4) < 1e-9

    # per-class recall: tp/rowsum -> 1/2, 1/3, 1/1, 0/0(excluded)
    assert abs(ev.recall(0) - 0.5) < 1e-9
    assert abs(ev.recall(1) - 1 / 3) < 1e-9
    assert abs(ev.recall(2) - 1.0) < 1e-9
    # class 3 has rowsum 0 -> excluded from the macro (reference NOTE)
    want_macro_recall = (0.5 + 1 / 3 + 1.0) / 3
    assert abs(ev.recall() - want_macro_recall) < 1e-9

    # macro F1: class 3 excluded (recall undefined); per-class
    # f1_0 = 2*.5*.5/1 = .5 ; f1_1 = 2*1*(1/3)/(4/3) = .5 ;
    # f1_2 = 2*.5*1/1.5 = 2/3
    want_f1 = (0.5 + 0.5 + 2 / 3) / 3
    assert abs(ev.f1() - want_f1) < 1e-9

    # stats() carries exactly these numbers
    s = ev.stats()
    assert f"{ev.accuracy():.4f}" in s and f"{ev.f1():.4f}" in s


def test_two_class_f1_is_binary_class1():
    """nClasses == 2: f1() is the class-1 binary F1 (Evaluation.java:949),
    not a macro average."""
    ev = Evaluation()
    actual = [1, 1, 1, 0, 0, 1]
    pred = [1, 0, 1, 1, 0, 1]
    ev.eval_batch(_onehot(actual, 2), _onehot(pred, 2))
    # tp=3 (1->1), fp=1 (0->1), fn=1 (1->0)
    want = 2 * 3 / (2 * 3 + 1 + 1)
    assert abs(ev.f1() - want) < 1e-9


def test_merge_preserves_golden_values():
    """Map-side merge (the Spark evaluation property): two partial
    evaluations merge to the same numbers as one pass."""
    rng = np.random.default_rng(0)
    actual = rng.integers(0, 3, 60)
    pred = rng.integers(0, 3, 60)
    full = Evaluation()
    full.eval_batch(_onehot(actual, 3), _onehot(pred, 3))
    a, b = Evaluation(), Evaluation()
    a.eval_batch(_onehot(actual[:25], 3), _onehot(pred[:25], 3))
    b.eval_batch(_onehot(actual[25:], 3), _onehot(pred[25:], 3))
    a.merge(b)
    np.testing.assert_array_equal(a.confusion, full.confusion)
    for m in ("accuracy", "precision", "recall", "f1"):
        assert abs(getattr(a, m)() - getattr(full, m)()) < 1e-12
