"""Embedding lookup table — the device-side buffers.

Analog of the reference's InMemoryLookupTable
(models/embeddings/inmemory/InMemoryLookupTable.java:55-97): syn0 (word
vectors), syn1 (hierarchical-softmax inner-node weights), syn1neg
(negative-sampling output weights), and the unigram sampling table. The
reference keeps these as heap INDArrays plus a precomputed sigmoid
expTable; here syn* live as jax device arrays updated in place by the
jitted training steps (donation), and sigmoid is computed on the fly —
a transcendental on TPU is cheaper than a gather.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class InMemoryLookupTable:
    def __init__(self, vocab: VocabCache, vector_length: int, *, seed: int = 12345,
                 use_hs: bool = True, negative: int = 0, dtype=jnp.float32):
        self.vocab = vocab
        self.vector_length = int(vector_length)
        self.use_hs = bool(use_hs)
        self.negative = int(negative)
        V, D = vocab.num_words(), self.vector_length
        key = jax.random.PRNGKey(seed)
        # word2vec init: syn0 ~ U(-0.5/D, 0.5/D), outputs zero
        self.syn0 = (
            (jax.random.uniform(key, (max(V, 1), D), dtype) - 0.5) / D
        )
        self.syn1 = (
            jnp.zeros((max(V - 1, 1), D), dtype) if use_hs else None
        )
        self.syn1neg = (
            jnp.zeros((max(V, 1), D), dtype) if negative > 0 else None
        )
        self._unigram: Optional[np.ndarray] = None

    # -- unigram table for negative sampling ---------------------------------

    def unigram_table(self, table_size: int = 1_000_000, power: float = 0.75) -> np.ndarray:
        """Sampling table: word index repeated proportionally to
        count^0.75 (reference: InMemoryLookupTable.makeTable)."""
        if self._unigram is None or self._unigram.size != table_size:
            counts = self.vocab.counts().astype(np.float64)
            if counts.size == 0:
                raise ValueError("empty vocab")
            p = counts**power
            p /= p.sum()
            bounds = np.cumsum(p)
            self._unigram = np.searchsorted(
                bounds, (np.arange(table_size) + 0.5) / table_size
            ).astype(np.int64)
        return self._unigram

    # -- vector access -------------------------------------------------------

    def vector(self, word: str) -> Optional[np.ndarray]:
        idx = self.vocab.index_of(word)
        if idx < 0:
            return None
        return np.asarray(self.syn0[idx])

    def vectors(self) -> np.ndarray:
        return np.asarray(self.syn0[: self.vocab.num_words()])

    def set_vectors(self, arr: np.ndarray):
        self.syn0 = jnp.asarray(arr)

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.vector(a), self.vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / denom) if denom else 0.0

    def words_nearest(self, word_or_vec, top_n: int = 10):
        """Cosine-nearest words — one device matmul over the whole table
        (reference: WordVectors.wordsNearest)."""
        if isinstance(word_or_vec, str):
            v = self.vector(word_or_vec)
            if v is None:
                return []
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        table = self.vectors()
        norms = np.linalg.norm(table, axis=1) * (np.linalg.norm(v) + 1e-12)
        sims = (table @ v) / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w in exclude:
                continue
            out.append((w, float(sims[i])))
            if len(out) >= top_n:
                break
        return out
