"""Flight recorder + crash forensics — the black-box half of the
liveness layer (utils/health.py is the watchdog half).

utils/tracing.py records spans only while tracing is ON, because spans
cost a clock read and a ring append per section; a crashed process that
never enabled tracing leaves nothing. The flight recorder borrows the
same bounded-ring design but is ALWAYS on at fixed cost: the fit loop
appends one small step record per dispatch (step index, score reference,
per-phase timings), interesting events (compiles, helper fallbacks,
health transitions) append markers, and every `metrics_every` steps a
cheap scalar delta of the metrics registry is captured. Memory bound:
three bounded deques, regardless of run length.

Forensics surfaces:

* `install_crash_hooks(path)` — SIGTERM gets a Python-level handler that
  writes the structured JSON dump (last steps + events + metrics deltas
  + health status + every thread's Python stack) before the process
  dies; `faulthandler` covers the fatal-signal set (SIGSEGV/SIGFPE/
  SIGABRT/SIGBUS) AND SIGTERM with an async-signal-safe plain-text
  all-thread traceback to `<path>.stacks.txt`, so even a process wedged
  inside a C call leaves the wedged thread's name behind; `sys.excepthook`
  and `atexit` chain in, so an unhandled exception or plain exit also
  leaves the artifact.
* `dump(path, reason)` — the same snapshot on demand (the watchdog's
  hang action calls this before raising StepHangError).
* `render_dump(doc)` — the human view `cli blackbox <dump>` prints: the
  final-steps timeline, events, component health, and thread stacks.

Score handling: the fit loop must never sync the device to feed the
recorder, so step records hold the score *array reference*; at snapshot
time a score is resolved to a float only when the device says it is
ready (`is_ready()`), else reported as "pending" — which is itself
forensic signal (the last dispatched step never completed).
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import logging
import math
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.utils import metrics as _metrics

logger = logging.getLogger("deeplearning4j_tpu")


def _resolve_score(score) -> object:
    """Float value of a recorded score WITHOUT blocking: a device array
    still in flight reports "pending" (the step never finished — that is
    the finding, not an error); anything unreadable reports None."""
    if score is None:
        return None
    try:
        is_ready = getattr(score, "is_ready", None)
        if is_ready is not None and not is_ready():
            return "pending"
        v = float(score)
        return v if math.isfinite(v) else None
    except Exception:
        return None


def thread_stacks() -> List[dict]:
    """Python stacks of every live thread, dl4j-* threads first — the
    "which thread wedged" half of a crash dump."""
    frames = sys._current_frames()
    threads = sorted(
        threading.enumerate(),
        key=lambda t: (not t.name.startswith("dl4j-"), t.name))
    out = []
    for t in threads:
        frame = frames.get(t.ident)
        stack = ([f"{fr.filename}:{fr.lineno} {fr.name}: {fr.line or ''}"
                  .rstrip()
                  for fr in traceback.extract_stack(frame)]
                 if frame is not None else [])
        out.append({"name": t.name, "ident": t.ident,
                    "daemon": t.daemon, "alive": t.is_alive(),
                    "stack": stack})
    return out


class FlightRecorder:
    """Always-on bounded ring of step records + event markers + periodic
    metrics deltas. `enabled=False` exists only for the overhead A/B
    guard in tests — production never turns the black box off."""

    def __init__(self, capacity: int = 256, events_capacity: int = 256,
                 metrics_every: int = 64):
        self.enabled = True
        self.metrics_every = max(1, int(metrics_every))
        # RLock, deliberately: the SIGTERM dump runs as a Python signal
        # handler on the main thread, which may be interrupted INSIDE a
        # record_step() holding this lock — a plain Lock would deadlock
        # the crash path at exactly the moment it exists for
        self._lock = threading.RLock()
        self._steps: deque = deque(maxlen=int(capacity))
        self._events: deque = deque(maxlen=int(events_capacity))
        self._metrics_deltas: deque = deque(maxlen=32)
        self._step_count = 0
        self._last_scalars: Optional[Dict[str, float]] = None
        self._dump_path: Optional[str] = None  # install_crash_hooks target
        self._dumping = False
        # a signal/unhandled-exception dump was written: the atexit hook
        # must not overwrite the crash-time forensics with a shutdown-
        # time view (threads unwound, reason lost)
        self._crash_dumped = False
        self.last_degradation: Optional[dict] = None
        self.last_dump_path: Optional[str] = None

    # -- recording (hot path) ------------------------------------------------

    def record_step(self, step: int, score=None, **phases):
        """One fit dispatch: a deque append of a small dict; every
        `metrics_every`-th call also captures a registry scalar delta
        (counter/gauge values only — no histogram percentile work)."""
        if not self.enabled:
            return
        rec = {"ts": round(time.time(), 3), "step": int(step),
               "score": score}
        for k, v in phases.items():
            if v is not None:
                rec[k] = round(float(v), 6)
        with self._lock:
            self._steps.append(rec)
            self._step_count += 1
            snap_due = self._step_count % self.metrics_every == 0
        if snap_due:
            self.record_metrics_delta()

    def record_event(self, kind: str, **fields):
        if not self.enabled:
            return
        ev = {"ts": round(time.time(), 3), "kind": kind}
        ev.update(fields)
        # cross-reference into the distributed-tracing layer: an event
        # recorded while a span is active carries its trace id, so a
        # crash dump names the trace of the request that was in flight
        # (one flag check when tracing is off; never fatal — the black
        # box must record even if tracing misbehaves)
        if "trace_id" not in ev:
            try:
                from deeplearning4j_tpu.utils import tracing as _tracing

                tid = _tracing.current_trace_id()
                if tid is not None:
                    ev["trace_id"] = tid
            except Exception:
                pass
        with self._lock:
            self._events.append(ev)

    def record_metrics_delta(self):
        """Scalar registry delta since the previous capture — cheap
        (value reads, no histogram sorting), so counters' recent movement
        rides along in a crash dump. The `device_memory_bytes{...}`
        watermark gauges (utils/devprof) additionally ride along as
        ABSOLUTE values per capture: a delta view of a watermark hides
        the level, and the level trajectory is exactly what a post-OOM
        dump needs to show."""
        now = _metrics.get_registry().scalar_values()
        memory = {k: v for k, v in now.items()
                  if k.startswith("device_memory_bytes")}
        with self._lock:
            prev = self._last_scalars
            self._last_scalars = now
            if prev is None:
                return
            delta = {}
            for k, v in now.items():
                dv = v - prev.get(k, 0.0)
                if dv:
                    delta[k] = round(dv, 9)
            if delta or memory:
                entry = {"ts": round(time.time(), 3),
                         "step": self._step_count, "delta": delta}
                if memory:
                    entry["memory"] = memory
                self._metrics_deltas.append(entry)

    def on_degradation(self, component: str, stalled_for: float,
                       threads: List[str]):
        """The watchdog's first-stall hook: record the event and keep an
        in-memory snapshot of the moment (the state most useful for
        diagnosing what led INTO the stall); with crash hooks installed
        the snapshot is also written next to the crash artifact."""
        self.record_event("degraded", component=component,
                          stalled_for_seconds=round(stalled_for, 3),
                          threads=threads)
        snap = self.snapshot(reason=f"component {component!r} degraded")
        self.last_degradation = snap
        if self._dump_path:
            try:
                self._write(self._dump_path + ".degraded.json", snap)
            except OSError:
                logger.warning("degradation snapshot write failed",
                               exc_info=True)

    # -- readout / forensics -------------------------------------------------

    def snapshot(self, reason: str = "") -> dict:
        """JSON-safe dict of everything the black box knows right now:
        steps (scores resolved non-blockingly), events, metrics deltas,
        component health, and all thread stacks."""
        with self._lock:
            steps = [dict(r) for r in self._steps]
            events = [dict(e) for e in self._events]
            deltas = [dict(d) for d in self._metrics_deltas]
            step_count = self._step_count
        for r in steps:
            r["score"] = _resolve_score(r.get("score"))
        try:
            from deeplearning4j_tpu.utils.health import get_health

            health = get_health().status()
        except Exception:
            health = None
        try:
            # the chip-budget view at crash time: who was spending what
            # when the process died (books always; spend when metered)
            from deeplearning4j_tpu.utils import resourcemeter

            tenants = resourcemeter.snapshot()
        except Exception:
            tenants = None
        try:
            # who holds what and who waits on whom (None unless the
            # DL4J_LOCKCHECK sanitizer is armed): a watchdog-caught hang
            # dumps as a NAMED wait-graph cycle, not a stack soup
            from deeplearning4j_tpu.utils import locktrace

            locks = locktrace.forensics()
        except Exception:
            locks = None
        return {
            "reason": reason,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "steps_recorded_total": step_count,
            "last_step": steps[-1]["step"] if steps else None,
            "steps": steps,
            "events": events,
            "metrics_deltas": deltas,
            "health": health,
            "tenants": tenants,
            "locks": locks,
            "threads": thread_stacks(),
        }

    @staticmethod
    def _write(path: str, doc: dict) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, path)  # a reader never sees a half-written dump
        return path

    def dump(self, path: Optional[str] = None, reason: str = "") \
            -> Optional[str]:
        """Write the snapshot to `path` (default: the crash-hook path,
        else dl4j_blackbox_<pid>.json in the tmp dir). Reentrancy-guarded
        — a crash during a dump must not recurse — and never raises: the
        black box is the last thing standing, an exception here would
        shadow the original failure."""
        with self._lock:
            if self._dumping:
                return self.last_dump_path
            self._dumping = True
        try:
            if path is None:
                path = self._dump_path
            if path is None:
                import tempfile

                path = os.path.join(tempfile.gettempdir(),
                                    f"dl4j_blackbox_{os.getpid()}.json")
            out = self._write(path, self.snapshot(reason=reason))
            self.last_dump_path = out
            return out
        except Exception:
            logger.exception("flight-recorder dump failed")
            return None
        finally:
            with self._lock:
                self._dumping = False


# -- the process-global recorder ---------------------------------------------

_RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return _RECORDER


# -- crash hooks --------------------------------------------------------------

_hooks_installed = False
_fault_file = None


def install_crash_hooks(path: str, recorder: Optional[FlightRecorder] = None,
                        dump_on_exit: bool = True) -> str:
    """Arm the black box: on SIGTERM, unhandled exception, or interpreter
    exit the recorder dumps to `path`; the fatal-signal set (and SIGTERM)
    additionally get faulthandler's async-signal-safe all-thread
    traceback in `<path>.stacks.txt` (the only layer that still works
    when the interpreter itself is wedged in native code). Idempotent;
    returns `path`. Signal handlers require the main thread — from a
    worker thread only the faulthandler/atexit/excepthook layers arm."""
    global _hooks_installed, _fault_file
    rec = recorder or _RECORDER
    rec._dump_path = path
    if _hooks_installed:
        return path
    _hooks_installed = True

    def _on_sigterm(signum, frame):
        rec.record_event("signal", signum=int(signum))
        rec._crash_dumped = True
        rec.dump(reason=f"signal {signum}")

    # The dump rides the shared SIGTERM chain (utils/sigchain) at
    # PRIORITY_DUMP: a checkpoint listener's preemption save (PRIORITY_
    # SAVE) always runs first and the chain's tail restores die-with-
    # SIGTERM semantics — installation order between the two subsystems
    # no longer decides anything. The chain handler must be installed
    # BEFORE faulthandler.register(chain=True) — last sigaction wins, so
    # the reverse order would displace faulthandler's async-signal-safe
    # C-level dump (the only layer that still fires when the interpreter
    # is wedged inside native code). This way SIGTERM first writes the
    # native stacks.txt, then chains into the JSON dump when the main
    # thread reaches a bytecode boundary.
    from deeplearning4j_tpu.utils import sigchain

    sigchain.register("blackbox-dump", _on_sigterm,
                      priority=sigchain.PRIORITY_DUMP)

    try:
        _fault_file = open(path + ".stacks.txt", "w")
        faulthandler.enable(file=_fault_file)
        faulthandler.register(signal.SIGTERM, file=_fault_file,
                              all_threads=True, chain=True)
    except (OSError, ValueError, AttributeError):
        logger.warning("faulthandler arming failed", exc_info=True)

    prev_excepthook = sys.excepthook

    def _on_unhandled(exc_type, exc, tb):
        rec.record_event("unhandled_exception", type=exc_type.__name__,
                         message=str(exc))
        rec._crash_dumped = True
        rec.dump(reason=f"unhandled {exc_type.__name__}: {exc}")
        prev_excepthook(exc_type, exc, tb)

    sys.excepthook = _on_unhandled

    if dump_on_exit:
        def _on_exit():
            # a normal exit refreshes the artifact with the final state
            # (for a test-session artifact that IS the content wanted) —
            # but never clobbers a crash-time dump with a shutdown-time
            # view whose threads have already unwound
            if not rec._crash_dumped:
                rec.dump(reason="atexit")

        atexit.register(_on_exit)
    return path


# -- rendering (cli blackbox) -------------------------------------------------

def _fmt_ms(rec: dict, key: str) -> str:
    v = rec.get(key)
    return f"{v * 1e3:9.3f}" if isinstance(v, (int, float)) else " " * 9


def render_dump(doc: dict, max_steps: int = 32,
                max_stack_lines: int = 12) -> str:
    """Human-readable view of a dump: final-steps timeline, events,
    health, thread stacks (dl4j-* threads lead — they are the framework's
    own workers, the usual suspects in a wedge)."""
    lines = []
    lines.append(f"blackbox dump — reason: {doc.get('reason') or '?'}  "
                 f"pid {doc.get('pid')}  ts {doc.get('ts')}")
    lines.append(f"steps recorded: {doc.get('steps_recorded_total', 0)}  "
                 f"last step index: {doc.get('last_step')}")
    steps = doc.get("steps") or []
    if steps:
        lines.append("")
        lines.append(f"final {min(len(steps), max_steps)} steps "
                     "(ms; score 'pending' = dispatched, never completed):")
        lines.append("      step       score  data_wait   dispatch"
                     "       sync")
        for rec in steps[-max_steps:]:
            score = rec.get("score")
            s = (f"{score:11.6g}" if isinstance(score, (int, float))
                 else f"{score or '':>11}")
            lines.append(
                f"  {rec.get('step', '?'):>8} {s} "
                f"{_fmt_ms(rec, 'data_wait')}  {_fmt_ms(rec, 'dispatch')}  "
                f"{_fmt_ms(rec, 'sync')}")
    events = doc.get("events") or []
    if events:
        lines.append("")
        lines.append(f"events (newest last, {len(events)}):")
        for ev in events[-max_steps:]:
            # the trace id renders as its own column: it is the grep key
            # into span exports / logs, not just another payload field
            tid = ev.get("trace_id")
            trace_note = f"  [trace {tid}]" if tid else ""
            if ev.get("kind") == "oom":
                lines.append(f"  {ev.get('ts')}  oom  "
                             f"where={ev.get('where')}{trace_note} "
                             "(see OOM forensics below)")
                continue
            extra = {k: v for k, v in ev.items()
                     if k not in ("ts", "kind", "trace_id")}
            lines.append(f"  {ev.get('ts')}  {ev.get('kind')}"
                         + (f"  {extra}" if extra else "")
                         + trace_note)
    oom = next((ev for ev in reversed(events)
                if ev.get("kind") == "oom"), None)
    if oom is not None:
        lines.append("")
        lines.append(f"OOM forensics — where: {oom.get('where')}")
        lines.append(f"  error: {oom.get('error')}")
        static = oom.get("static") or {}
        for key in ("params_bytes", "updater_bytes",
                    "activation_peak_bytes", "live_bytes"):
            v = static.get(key)
            if isinstance(v, (int, float)):
                lines.append(f"  {key}: {v / 2**20:.2f} MiB")
        la = static.get("largest_activation")
        if la:
            lines.append(f"  largest static activation: shape "
                         f"{la.get('shape')} {la.get('dtype')} "
                         f"({la.get('bytes', 0) / 2**20:.2f} MiB)")
        top = oom.get("top_buffers") or []
        if top:
            lines.append(f"  largest live buffers ({len(top)}):")
            for b in top:
                lines.append(
                    f"    {b.get('nbytes', 0) / 2**20:9.2f} MiB  "
                    f"{b.get('dtype')}{list(b.get('shape') or ())}")
    # numerical-resilience trail (train/sentinel + checkpoint
    # integrity): one summary block so a dump answers "did this run
    # fight divergence / corruption, and how did that end" at a glance
    # — the individual events stay in the timeline above
    _RESIL = ("train_anomaly", "batch_quarantined",
              "quarantined_batch_skipped", "train_rollback",
              "training_diverged", "checkpoint_corrupt")
    resil = [ev for ev in events if ev.get("kind") in _RESIL]
    if resil:
        lines.append("")
        lines.append("numerical resilience:")
        counts: Dict[str, int] = {}
        for ev in resil:
            counts[ev["kind"]] = counts.get(ev["kind"], 0) + 1
        lines.append("  " + "  ".join(
            f"{k}={counts[k]}" for k in _RESIL if k in counts))
        for ev in resil:
            if ev.get("kind") == "batch_quarantined":
                lines.append(
                    f"  quarantined: epoch {ev.get('epoch')} batch "
                    f"{ev.get('batch_in_epoch')} ({ev.get('anomaly')}, "
                    f"iteration {ev.get('iteration')})")
            elif ev.get("kind") == "train_rollback":
                lines.append(
                    f"  rollback #{ev.get('attempt')} -> "
                    f"{ev.get('directory')} (lr {ev.get('lr')})")
            elif ev.get("kind") == "checkpoint_corrupt":
                lines.append(f"  corrupt checkpoint skipped: "
                             f"{ev.get('checkpoint')} — {ev.get('why')}")
            elif ev.get("kind") == "training_diverged":
                lines.append(f"  DIVERGED: {ev.get('why')} "
                             f"(dump {ev.get('dump')})")
    deltas = doc.get("metrics_deltas") or []
    if deltas:
        lines.append("")
        lines.append("last metrics delta:")
        for k, v in sorted((deltas[-1].get("delta") or {}).items()):
            lines.append(f"  {k}: {v:+g}")
        trajectory = [d for d in deltas if d.get("memory")]
        if trajectory:
            lines.append("")
            lines.append("device memory trajectory "
                         f"({len(trajectory)} captures, MiB):")
            for d in trajectory[-8:]:
                parts = []
                for k, v in sorted(d["memory"].items()):
                    kind = k.split("kind=")[-1].strip('"}')
                    parts.append(f"{kind}={v / 2**20:.1f}")
                lines.append(f"  step {d.get('step')}: {', '.join(parts)}")
    health = doc.get("health")
    if health:
        lines.append("")
        lines.append(f"component health: {health.get('status')}")
        for name, d in sorted((health.get("components") or {}).items()):
            note = ""
            if d.get("status") != "ok":
                note = (f"  stalled {d.get('stalled_for_seconds')}s"
                        f" threads={d.get('stalled_threads')}")
            lines.append(f"  {name}: {d.get('status')}{note}")
    tenants_doc = doc.get("tenants") or {}
    tenant_rows = tenants_doc.get("tenants") or {}
    if tenant_rows:
        cons = tenants_doc.get("conservation") or {}
        lines.append("")
        lines.append(f"tenant chip budget (books_ok={cons.get('books_ok')} "
                     f"spend_ok={cons.get('spend_ok')}):")
        for t in sorted(tenant_rows):
            rec = tenant_rows[t] or {}
            dev = rec.get("device_seconds") or {}
            parts = []
            if dev:
                parts.append("dev[s] " + " ".join(
                    f"{tier}={s:.4g}" for tier, s in sorted(dev.items())))
            b = rec.get("books")
            if b:
                parts.append(f"adm={b.get('admitted', 0)} "
                             f"done={b.get('completed', 0)} "
                             f"shed={b.get('shed', 0)} "
                             f"fail={b.get('failed', 0)}")
            lines.append(f"  {t}: " + ("  ".join(parts) if parts
                                       else "(idle)"))
    locks_doc = doc.get("locks") or {}
    if locks_doc.get("enabled"):
        lines.append("")
        held = locks_doc.get("held") or {}
        waiting = locks_doc.get("waiting") or []
        cycles = locks_doc.get("deadlock_cycles") or []
        lines.append(f"lock forensics (DL4J_LOCKCHECK): "
                     f"{sum(len(v) for v in held.values())} held, "
                     f"{len(waiting)} waiting, {len(cycles)} deadlock "
                     f"cycle(s)")
        for tname in sorted(held):
            locks_held = ", ".join(
                f"{h['site']}" + (f" x{h['depth']}" if h.get("depth", 1) > 1
                                  else "")
                for h in held[tname])
            lines.append(f"  {tname} holds: {locks_held}")
        for w in waiting:
            lines.append(f"  {w['thread']} waiting {w['waited_s']}s "
                         f"for {w['waits_for']}")
        for cyc in cycles:
            lines.append("  DEADLOCK CYCLE:")
            for e in cyc:
                lines.append(f"    {e['thread']} waits for "
                             f"{e['waits_for']} held by {e['held_by']}")
    threads = doc.get("threads") or []
    if threads:
        lines.append("")
        lines.append(f"threads ({len(threads)}):")
        for t in threads:
            flags = "daemon" if t.get("daemon") else "      "
            lines.append(f"  -- {t.get('name')} ({flags})")
            for fr in (t.get("stack") or [])[-max_stack_lines:]:
                lines.append(f"       {fr}")
    return "\n".join(lines)
