"""NLP / embeddings stack.

Analog of the reference's deeplearning4j-nlp-parent (~46k LoC, SURVEY.md
§2.7): a generic SequenceVectors trainer over sequence elements with
pluggable learning algorithms (SkipGram, CBOW, DM, DBOW), Word2Vec /
ParagraphVectors facades, vocab construction + Huffman coding for
hierarchical softmax, tokenization SPI, and WordVectorSerializer interop.

TPU-first redesign of the hot path: the reference batches skip-gram
updates into native AggregateSkipGram ops executed by libnd4j
(models/embeddings/learning/impl/elements/SkipGram.java:271); here the
same batching feeds ONE jitted XLA step that gathers embedding rows,
computes the sigmoid losses for hierarchical-softmax nodes and/or negative
samples, and scatter-adds the updates in place (donated buffers).
"""

from deeplearning4j_tpu.nlp.tokenization import (
    CJKTokenizerFactory,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import Huffman, VocabCache, VocabConstructor
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    VectorsConfiguration,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraphvectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
from deeplearning4j_tpu.nlp.vectorizers import (
    BagOfWordsVectorizer,
    LabelsSource,
    TfidfVectorizer,
)

__all__ = [
    "BagOfWordsVectorizer",
    "CJKTokenizerFactory",
    "Glove",
    "LabelsSource",
    "TfidfVectorizer",
    "CommonPreprocessor",
    "DefaultTokenizerFactory",
    "NGramTokenizerFactory",
    "TokenizerFactory",
    "Huffman",
    "VocabCache",
    "VocabConstructor",
    "InMemoryLookupTable",
    "SequenceVectors",
    "VectorsConfiguration",
    "Word2Vec",
    "ParagraphVectors",
    "WordVectorSerializer",
]
