"""Recurrent layers: LSTM, GravesLSTM (peepholes), GravesBidirectionalLSTM.

Reference impl: nn/layers/recurrent/LSTMHelpers.java (574 LoC — fwd
activateHelper :62, bwd backpropGradientHelper :291, a hand-written
per-timestep Java loop). TPU-first redesign:

- the input projection for ALL timesteps and ALL four gates is ONE batched
  matmul ([b,t,nIn] x [nIn,4H]) that saturates the MXU;
- the sequential part is a lax.scan whose body holds only the [H,4H]
  recurrent matmul + element-wise gate math, so XLA compiles a single
  fused loop body instead of per-op dispatch per timestep;
- the backward pass is autodiff through the scan (no hand-written BPTT).

Gate block layout in the fused [*, 4H] matrices: [i | f | g | o]
(input gate, forget gate, cell candidate, output gate).
NOTE: the reference's flattened layout is IFOG (input, forget, output,
modulation — LSTMParamInitializer.java:108) with peepholes packed as extra
recurrent-weight columns; the flat params()/set_params() view here is
therefore NOT reference-checkpoint-compatible for recurrent layers. DL4J
checkpoint import must permute gate blocks at the boundary (the planned
dl4j-zip reader's job), exactly as the Keras importer transposes conv
kernels.

Masking (variable-length sequences): at masked steps the carried (h, c)
pass through unchanged and the emitted output is zero, which reproduces the
reference's masked-timestep semantics (TestVariableLengthTS).

Stateful inference / TBPTT: pass ctx.state = {"h": ..., "c": ...} to seed
the scan; the final state is returned so callers implement rnnTimeStep and
truncated-BPTT segment carry (reference: MultiLayerNetwork.rnnTimeStep,
updateRnnStateWithTBPTTState :1321).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.core import apply_dropout
from deeplearning4j_tpu.nn.layers.registry import LayerContext, register_layer
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import apply_activation


def _lstm_param_set(key, n_in, n_out, conf, dtype, prefix=""):
    k1, k2 = jax.random.split(key)
    W = init_weights(k1, (n_in, 4 * n_out), n_in, n_out, conf.weight_init, conf.dist, dtype)
    RW = init_weights(k2, (n_out, 4 * n_out), n_out, n_out, conf.weight_init, conf.dist, dtype)
    b = jnp.zeros((4 * n_out,), dtype)
    # forget-gate bias init (reference: LSTMParamInitializer sets the forget
    # block of the bias to forgetGateBiasInit, default 1.0)
    b = b.at[n_out : 2 * n_out].set(conf.forget_gate_bias_init)
    return {prefix + "W": W, prefix + "RW": RW, prefix + "b": b}


def _peephole_params(key, n_out, dtype, prefix=""):
    ks = jax.random.split(key, 3)
    scale = 1.0 / jnp.sqrt(jnp.asarray(float(n_out)))
    return {
        prefix + "pI": scale * jax.random.normal(ks[0], (n_out,), dtype),
        prefix + "pF": scale * jax.random.normal(ks[1], (n_out,), dtype),
        prefix + "pO": scale * jax.random.normal(ks[2], (n_out,), dtype),
    }


def _scan_lstm(conf, params, x, ctx, peephole: bool, prefix: str = "", reverse: bool = False):
    """Core scan. x: [batch, time, nIn] -> y [batch, time, H], final (h, c)."""
    H = int(conf.n_out)
    W = params[prefix + "W"]
    RW = params[prefix + "RW"]
    b = params[prefix + "b"]
    gate_act = conf.gate_activation
    cell_act = conf.activation

    bsz = x.shape[0]
    xg = jnp.einsum("bti,ih->bth", x, W.astype(x.dtype)) + b.astype(x.dtype)  # all-timestep MXU matmul
    xg_t = jnp.swapaxes(xg, 0, 1)  # time-major for scan

    state = ctx.state or {}
    h0 = state.get("h")
    c0 = state.get("c")
    if h0 is None:
        h0 = jnp.zeros((bsz, H), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((bsz, H), x.dtype)

    # vendor-kernel plugin point (the CudnnHelper analog): a registered
    # fused-sequence kernel takes over when it supports this configuration;
    # a kernel that raises at trace time is disabled by the SPI
    # (HelperError) and the scan path below runs instead
    from deeplearning4j_tpu.ops.helpers import HelperError, get_helper

    if (x.shape[1] == 1 and ctx.mask is None and not reverse
            and not ctx.training and ctx.state is not None):
        # decode fast path: a [b, 1, nIn] STATEFUL inference step — the
        # serving decode engine's / rnn_time_step's shape — consults the
        # single-step kernel first. It skips the sequence kernel's VJP
        # stashes (acts/hprev/cprev) entirely; gated on inference +
        # streaming state because lstm_step defines no VJP
        # (ops/pallas_lstm.lstm_step)
        step_helper = get_helper(
            "lstm_decode_step", peephole=peephole,
            gate_act=conf.gate_activation, cell_act=conf.activation,
        )
        if step_helper is not None:
            if peephole:
                pv = tuple(params[prefix + k].astype(x.dtype)
                           for k in ("pI", "pF", "pO"))
            else:
                zero = jnp.zeros((H,), x.dtype)
                pv = (zero, zero, zero)
            try:
                hF, cF = step_helper(xg[:, 0, :], RW.astype(x.dtype),
                                     *pv, h0, c0)
            except HelperError:
                pass  # fall through to the sequence helper / scan
            else:
                return hF[:, None, :], (hF, cF)

    helper = get_helper(
        "lstm_sequence", peephole=peephole, mask=ctx.mask,
        gate_act=conf.gate_activation, cell_act=conf.activation,
        reverse=reverse,
    )
    if helper is not None:
        if peephole:
            pv = tuple(params[prefix + k].astype(x.dtype)
                       for k in ("pI", "pF", "pO"))
        else:
            zero = jnp.zeros((H,), x.dtype)
            pv = (zero, zero, zero)
        try:
            ys, hF, cF = helper(xg_t, RW.astype(x.dtype), *pv, h0, c0)
        except HelperError:
            pass  # fall through to the built-in scan
        else:
            return jnp.swapaxes(ys, 0, 1), (hF, cF)

    mask = ctx.mask
    if mask is not None:
        mask_t = jnp.swapaxes(mask.astype(x.dtype), 0, 1)[..., None]  # [t,b,1]
    else:
        mask_t = None

    if peephole:
        pI = params[prefix + "pI"].astype(x.dtype)
        pF = params[prefix + "pF"].astype(x.dtype)
        pO = params[prefix + "pO"].astype(x.dtype)

    def step(carry, inp):
        h, c = carry
        if mask_t is not None:
            g_in, m = inp
        else:
            g_in, m = inp, None
        g = g_in + h @ RW.astype(h.dtype)  # [b, 4H]
        gi, gf, gg, go = g[:, :H], g[:, H : 2 * H], g[:, 2 * H : 3 * H], g[:, 3 * H :]
        if peephole:
            gi = gi + c * pI
            gf = gf + c * pF
        i = apply_activation(gate_act, gi)
        f = apply_activation(gate_act, gf)
        gg = apply_activation(cell_act, gg)
        c_new = f * c + i * gg
        if peephole:
            go = go + c_new * pO
        o = apply_activation(gate_act, go)
        h_new = o * apply_activation(cell_act, c_new)
        if m is not None:
            h_new = m * h_new + (1 - m) * h
            c_new = m * c_new + (1 - m) * c
            y = h_new * m
        else:
            y = h_new
        return (h_new, c_new), y

    xs = (xg_t, mask_t) if mask_t is not None else xg_t
    (hF, cF), ys = lax.scan(step, (h0, c0), xs, reverse=reverse)
    y = jnp.swapaxes(ys, 0, 1)  # back to [b, t, H]
    return y, (hF, cF)


def _make_lstm_forward(peephole: bool):
    def fwd(conf, params, x, ctx: LayerContext):
        x = apply_dropout(x, conf.dropout, ctx)
        y, (h, c) = _scan_lstm(conf, params, x, ctx, peephole)
        new_state = {"h": h, "c": c} if ctx.state is not None else None
        return y, new_state

    return fwd


def lstm_init(key, conf: L.LSTM, dtype):
    return _lstm_param_set(key, int(conf.n_in), int(conf.n_out), conf, dtype)


register_layer(L.LSTM, lstm_init, _make_lstm_forward(peephole=False),
               order_fn=lambda c: ("W", "RW", "b"))


def graves_lstm_init(key, conf: L.GravesLSTM, dtype):
    k1, k2 = jax.random.split(key)
    p = _lstm_param_set(k1, int(conf.n_in), int(conf.n_out), conf, dtype)
    p.update(_peephole_params(k2, int(conf.n_out), dtype))
    return p


register_layer(L.GravesLSTM, graves_lstm_init, _make_lstm_forward(peephole=True),
               order_fn=lambda c: ("W", "RW", "b", "pI", "pF", "pO"))


# -- bidirectional -----------------------------------------------------------

def graves_bidirectional_init(key, conf: L.GravesBidirectionalLSTM, dtype):
    kf, kb = jax.random.split(key)
    k1, k2 = jax.random.split(kf)
    k3, k4 = jax.random.split(kb)
    p = _lstm_param_set(k1, int(conf.n_in), int(conf.n_out), conf, dtype, prefix="f_")
    p.update(_peephole_params(k2, int(conf.n_out), dtype, prefix="f_"))
    p.update(_lstm_param_set(k3, int(conf.n_in), int(conf.n_out), conf, dtype, prefix="b_"))
    p.update(_peephole_params(k4, int(conf.n_out), dtype, prefix="b_"))
    return p


def graves_bidirectional_forward(conf, params, x, ctx: LayerContext):
    x = apply_dropout(x, conf.dropout, ctx)
    # Bidirectional layers are never stateful (no streaming inference over a
    # future-dependent pass) — same restriction as the reference.
    fwd_ctx = LayerContext(training=ctx.training, rng=ctx.rng, mask=ctx.mask,
                           timesteps=ctx.timesteps, state=None)
    yf, _ = _scan_lstm(conf, params, x, fwd_ctx, peephole=True, prefix="f_")
    yb, _ = _scan_lstm(conf, params, x, fwd_ctx, peephole=True, prefix="b_", reverse=True)
    # element-wise ADD of directions (GravesBidirectionalLSTM.java:205)
    return yf + yb, None


register_layer(
    L.GravesBidirectionalLSTM, graves_bidirectional_init, graves_bidirectional_forward,
    order_fn=lambda c: ("f_W", "f_RW", "f_b", "f_pI", "f_pF", "f_pO",
                        "b_W", "b_RW", "b_b", "b_pI", "b_pF", "b_pO"),
)
