"""Async embedding parameter server (parallel/paramserver.py) — the
Aeron-PS analog: row-sharded tables, synchronous pulls, fire-and-forget
pushes, two concurrent workers training one skip-gram model."""

import threading

import numpy as np

from deeplearning4j_tpu.parallel.paramserver import (
    EmbeddingParameterServer,
    EmbeddingPSClient,
)


def test_pull_push_round_trip_sharded():
    rng = np.random.default_rng(0)
    t0 = rng.standard_normal((10, 4)).astype(np.float32)
    s1 = EmbeddingParameterServer({"syn0": t0.copy()})
    s2 = EmbeddingParameterServer({"syn0": t0.copy()})
    p1, p2 = s1.start(), s2.start()
    try:
        client = EmbeddingPSClient(
            [f"http://127.0.0.1:{p1}", f"http://127.0.0.1:{p2}"])
        rows = np.array([3, 0, 7, 2])
        got = client.pull("syn0", rows)
        np.testing.assert_allclose(got, t0[rows], rtol=1e-6)

        deltas = np.ones((4, 4), np.float32)
        client.push_async("syn0", rows, deltas)
        client.flush()
        got2 = client.pull("syn0", rows)
        np.testing.assert_allclose(got2, t0[rows] + 1.0, rtol=1e-6)
        # each row landed only on its modulo-owner
        assert s1.pushes_applied >= 1 and s2.pushes_applied >= 1
    finally:
        s1.stop()
        s2.stop()


def test_two_workers_async_sgd_converges():
    """Two workers doing Hogwild-style pulls/pushes against one server
    drive a toy embedding objective down (the reference's async-SGD
    semantics incl. acknowledged nondeterminism, DeepWalk.java:223)."""
    rng = np.random.default_rng(1)
    vocab, dim = 30, 8
    server = EmbeddingParameterServer({
        "syn0": (rng.standard_normal((vocab, dim)) * 0.1).astype(np.float32)})
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    # target: push word vectors of even ids toward +e0, odd toward -e0
    target = np.zeros((vocab, dim), np.float32)
    target[::2, 0] = 1.0
    target[1::2, 0] = -1.0

    def worker(seed):
        client = EmbeddingPSClient([url])
        w_rng = np.random.default_rng(seed)
        for _ in range(60):
            rows = w_rng.choice(vocab, size=8, replace=False)
            vecs = client.pull("syn0", rows)
            grad = vecs - target[rows]
            client.push_async("syn0", rows, -0.3 * grad)
        client.flush()

    threads = [threading.Thread(target=worker, args=(s,)) for s in (7, 8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = server.tables["syn0"]
    err = float(np.mean((final - target) ** 2))
    assert err < 0.02, err
    assert server.pushes_applied > 100


def test_empty_pull_returns_well_formed_array():
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((6, 5), np.float32)})
    port = server.start()
    try:
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"])
        out = client.pull("syn0", np.array([], np.int64))
        assert out.shape == (0, 5) and out.dtype == np.float32
    finally:
        server.stop()


def test_dead_endpoint_drops_push_and_counts_it():
    """A dead shard must not kill the drain thread (which would wedge
    push_async once the queue fills) — the push is dropped, counted, and
    later pushes to live endpoints still apply."""
    server = EmbeddingParameterServer({"syn0": np.zeros((4, 3), np.float32)})
    port = server.start()
    try:
        # two "shards": the second URL is a closed port. replay_capacity=0
        # disables the failover replay buffer — this test pins the
        # degrade-by-dropping path (test_paramserver_failover.py covers
        # park-and-replay)
        client = EmbeddingPSClient(
            [f"http://127.0.0.1:{port}", "http://127.0.0.1:1"],
            timeout=2.0, max_retries=1, retry_backoff=0.01,
            replay_capacity=0)
        rows = np.array([1, 3])  # odd rows -> owner 1 (the dead one)
        client.push_async("syn0", rows, np.ones((2, 3), np.float32))
        client.flush()
        assert client.dropped_pushes == 1
        # drain thread is still alive: a push owned by the live shard lands
        client.push_async("syn0", np.array([0, 2]),
                          np.ones((2, 3), np.float32))
        client.flush()
        assert server.tables["syn0"][0, 0] == 1.0
        assert server.tables["syn0"][2, 0] == 1.0
    finally:
        server.stop()


def test_binary_payload_throughput():
    """The hot path is raw bytes, not JSON — measure pushes/sec for a
    realistic [1024, 128] f32 row batch and assert a sane floor (the old
    JSON path measured ~10x slower at this size)."""
    import time

    dim, n_rows, n_pushes = 128, 1024, 50
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((65536, dim), np.float32)})
    port = server.start()
    try:
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"],
                                   queue_size=8)
        rng = np.random.default_rng(0)
        rows = rng.choice(65536, size=n_rows, replace=False)
        deltas = rng.standard_normal((n_rows, dim)).astype(np.float32)
        client.push_async("syn0", rows, deltas)  # warm the connection
        client.flush()
        t0 = time.perf_counter()
        for _ in range(n_pushes):
            client.push_async("syn0", rows, deltas)
        client.flush()
        dt = time.perf_counter() - t0
        rate = n_pushes / dt
        mb_s = n_pushes * deltas.nbytes / dt / 1e6
        print(f"PS binary push rate: {rate:.0f}/s ({mb_s:.0f} MB/s)")
        assert client.dropped_pushes == 0
        assert rate > 20, rate  # raw-bytes floor; JSON path was ~an order under
    finally:
        server.stop()


def test_three_endpoint_pull_runs_shards_concurrently():
    """A pull spanning 3 endpoints with an injected per-RPC latency must
    take ~max(latencies), not their sum — the per-shard sub-pulls run on
    concurrent threads (serial would be >= 3x the injected latency)."""
    import time

    from deeplearning4j_tpu.utils import faultpoints as fp

    rng = np.random.default_rng(2)
    t0 = rng.standard_normal((9, 4)).astype(np.float32)
    servers = [EmbeddingParameterServer({"syn0": t0.copy()})
               for _ in range(3)]
    ports = [s.start() for s in servers]
    try:
        client = EmbeddingPSClient(
            [f"http://127.0.0.1:{p}" for p in ports])
        rows = np.arange(9)  # 3 rows per modulo-owner
        client.pull("syn0", rows)  # warm connections / interpreter
        lat_ms = 150.0
        plan = fp.FaultPlan(seed=0)
        plan.add("paramserver_rpc", "latency", p=1.0, latency_ms=lat_ms)
        with fp.active(plan):
            start = time.perf_counter()
            got = client.pull("syn0", rows)
            wall = time.perf_counter() - start
        np.testing.assert_allclose(got, t0[rows], rtol=1e-6)
        # serial sub-pulls would take >= 3 * 150ms = 450ms
        assert wall < 2.0 * lat_ms / 1e3, \
            f"3-shard pull took {wall * 1e3:.0f}ms — shards ran serially?"
        assert wall >= 0.9 * lat_ms / 1e3, \
            f"pull took {wall * 1e3:.0f}ms — latency fault did not fire?"
    finally:
        for s in servers:
            s.stop()


def test_flush_waits_for_inflight_post():
    """Regression: flush() must not return once the queue LOOKS empty —
    the drain thread dequeues an item before POSTing it, so there is a
    window where qsize()==0 but the delta has not landed. Inject a slow
    network and read the server's table directly (no RPC) the moment
    flush returns True."""
    server = EmbeddingParameterServer({"syn0": np.zeros((4, 3), np.float32)})
    port = server.start()
    try:
        from deeplearning4j_tpu.utils import faultpoints as fp

        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"])
        plan = fp.FaultPlan(seed=0)
        plan.add("paramserver_rpc", "latency", p=1.0, latency_ms=400.0)
        with fp.active(plan):
            client.push_async("syn0", np.array([1]),
                              np.ones((1, 3), np.float32))
            assert client.flush(timeout=10.0) is True
            # no flush/pull between: the POST must ALREADY be applied
            assert server.tables["syn0"][1, 0] == 1.0
        assert client.dropped_pushes == 0
    finally:
        server.stop()


def test_flush_timeout_returns_false_on_wedged_endpoint():
    """flush(timeout=) is a bounded wait, not a hang: a wedged endpoint
    (socket that accepts and never answers) makes flush return False
    within ~the timeout; once the endpoint recovers the queued push
    still drains and a later flush returns True."""
    import time

    from deeplearning4j_tpu.utils import faultpoints as fp

    server = EmbeddingParameterServer({"syn0": np.zeros((4, 3), np.float32)})
    port = server.start()
    try:
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"])
        plan = fp.FaultPlan(seed=0)
        plan.add("paramserver_rpc", "hang", p=1.0, hang_seconds=3.0,
                 max_fires=1)
        with fp.active(plan):
            client.push_async("syn0", np.array([0]),
                              np.ones((1, 3), np.float32))
            start = time.perf_counter()
            ok = client.flush(timeout=0.5)
            wall = time.perf_counter() - start
        assert ok is False
        assert wall < 2.5, f"flush(timeout=0.5) blocked {wall:.1f}s"
        # exiting the fault context releases the hang — the drain thread
        # finishes the POST and a real flush succeeds
        assert client.flush(timeout=10.0) is True
        assert server.tables["syn0"][0, 0] == 1.0
    finally:
        server.stop()


def test_bf16_wire_is_opt_in_halves_bytes_and_round_trips():
    """wire_dtype='bf16' halves row-block wire bytes (counter-verified),
    round-trips within bf16 tolerance, and accumulates in f32 on the
    server (a bf16-exact delta lands exactly). Never default-on."""
    import pytest

    from deeplearning4j_tpu.utils.metrics import get_registry

    rng = np.random.default_rng(3)
    dim, n = 32, 64
    t0 = rng.standard_normal((n, dim)).astype(np.float32)
    server = EmbeddingParameterServer({"syn0": t0.copy()})
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    try:
        with pytest.raises(ValueError):
            EmbeddingPSClient([url], wire_dtype="fp8")
        c32 = EmbeddingPSClient([url])
        assert c32.wire_dtype == "f32"  # bf16 is strictly opt-in
        c16 = EmbeddingPSClient([url], wire_dtype="bf16")

        def pull_wire_bytes():
            vals = get_registry().scalar_values()
            return sum(v for k, v in vals.items()
                       if k.startswith("paramserver_wire_bytes_total")
                       and 'route="pull.bin"' in k)

        rows = np.arange(n)
        b0 = pull_wire_bytes()
        exact = c32.pull("syn0", rows)
        b1 = pull_wire_bytes()
        approx = c16.pull("syn0", rows)
        b2 = pull_wire_bytes()
        np.testing.assert_allclose(exact, t0, rtol=1e-6)
        np.testing.assert_allclose(approx, t0, rtol=1e-2, atol=1e-2)
        # response payload is 2 bytes/element vs 4; requests are equal
        f32_bytes, bf16_bytes = b1 - b0, b2 - b1
        assert 0 < bf16_bytes < 0.65 * f32_bytes, (f32_bytes, bf16_bytes)

        # server-side accumulation is f32: a delta exactly representable
        # in bf16 (0.5) applies exactly even over the narrow wire
        c16.push_async("syn0", rows, np.full((n, dim), 0.5, np.float32))
        assert c16.flush(timeout=10.0) is True
        np.testing.assert_allclose(server.tables["syn0"], t0 + 0.5,
                                   rtol=1e-6)
    finally:
        server.stop()
