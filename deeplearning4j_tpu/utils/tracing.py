"""Host-side distributed tracing — the Dapper-style request/step half of
the observability layer (counters live in utils/metrics.py).

A span is a named, timed section of host code. Every span belongs to a
**trace**: the root span of a causal chain mints a 128-bit `trace_id`
(W3C trace-context format), and children inherit it — through the
thread-local parent stack on one thread, through an explicit
`SpanContext` handed across a queue to another thread (`attach()` /
`detach()` / `attached_ctx`), or through a W3C `traceparent` header
across a process boundary (`format_traceparent` / `parse_traceparent`;
utils/jsonhttp joins incoming headers on the server side and
`traced_headers()` injects them on the client side). A shed 429 or a
p99 outlier is therefore attributable: grep one `trace_id` across span
exports, JSON logs (`configure_logging(json_lines=True)`), flight-
recorder events, and histogram exemplars (utils/metrics.py), then feed
the export to `cli trace` (analysis/tracecrit.py) for the span tree and
its critical path.

Completed spans land in a bounded ring buffer (old traffic ages out; a
serving process never grows without bound) and export two ways:

* JSONL — one span per line, newest last (`InferenceServer GET /trace`,
  `TracingListener(jsonl_path=...)`); greppable, tail-able.
* Chrome trace event JSON — load the dict from `to_chrome_trace()` into
  chrome://tracing / Perfetto and the host timeline sits next to the
  device xplane timeline captured by utils/profiler.py.

Device correlation: when enabled, each span also enters
`jax.profiler.TraceAnnotation(name)`, so the SAME names show up inside a
`jax.profiler.trace()` capture — `cli profile` op tables and host spans
line up by name.

Overhead contract: tracing is OFF by default and every propagation entry
point — `span()`, `instant()`, `attach()`/`detach()`,
`current_context()`, `current_traceparent()`, `record_complete()` —
degrades to one flag check on the disabled path: no allocation, no lock,
no clock read, no id minting. The fit loop's phase timers and the
serving/jsonhttp hot paths depend on this (the <10µs-per-call guard in
tests covers span creation AND the context hooks).
"""

from __future__ import annotations

import json
import itertools
import os
import threading
import time
from collections import deque
from typing import List, Optional

from deeplearning4j_tpu.utils import tenancy as _tenancy

# span ids are ints, unique within a process and unlikely to collide
# across processes: the counter starts at a random 60-bit offset so two
# processes exporting into one trace don't both hand out 1, 2, 3...
# (traceparent masks to the W3C 64-bit field; parse restores the int)
_counter = itertools.count(
    (int.from_bytes(os.urandom(5), "big") << 20) + 1)
_tls = threading.local()

_SPAN_ID_MASK = (1 << 64) - 1

# attach() on the disabled path returns this token; detach() recognizes
# it and does nothing — the pair stays one flag check when tracing is off
_DISABLED_TOKEN = object()


def _mint_trace_id() -> str:
    """128-bit random trace id, 32 lowercase hex chars (W3C format)."""
    return os.urandom(16).hex()


class SpanContext:
    """The thread/process-portable identity of a span: which trace it
    belongs to and which span is the parent of anything recorded under
    it. Hand one across a queue (`attach()`) or a process boundary
    (`traceparent()`) and parentage survives the hop."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: int):
        self.trace_id = trace_id
        self.span_id = int(span_id)

    def traceparent(self) -> str:
        """W3C trace-context header value: 00-<trace>-<span>-01."""
        return (f"00-{self.trace_id}"
                f"-{self.span_id & _SPAN_ID_MASK:016x}-01")

    def __repr__(self):  # debugging / assertion messages
        return f"SpanContext({self.trace_id!r}, {self.span_id})"


def format_traceparent(ctx: SpanContext) -> str:
    return ctx.traceparent()


_HEX_DIGITS = frozenset("0123456789abcdefABCDEF")


def _is_hex(s: str) -> bool:
    # NOT int(s, 16): that tolerates '+'/'-' signs and '_' separators, so
    # a malformed header would join the trace and be re-emitted outbound
    # as a W3C-invalid traceparent strict downstream tracers drop
    return not set(s) - _HEX_DIGITS


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Parse a W3C traceparent header into a SpanContext, or None when
    the header is absent or malformed — a bad header must yield a fresh
    root downstream, never a half-empty context."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    ver, tid, sid = parts[0], parts[1], parts[2]
    if len(ver) != 2 or len(tid) != 32 or len(sid) != 16:
        return None
    if not (_is_hex(ver) and _is_hex(tid) and _is_hex(sid)):
        return None
    if ver.lower() == "ff":
        return None
    if ver == "00" and len(parts) != 4:
        # version 00 is exactly 4 fields; FUTURE versions may append more
        return None
    span_id = int(sid, 16)
    if span_id == 0 or set(tid) == {"0"}:
        return None
    return SpanContext(tid.lower(), span_id)


class _NullSpan:
    """Shared disabled-path context manager: truthy checks, enter/exit
    no-ops, one instance for the whole process."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @property
    def context(self):
        return None


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "args", "id", "parent", "trace",
                 "t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.id = next(_counter)
        self.parent = None
        self.trace = None
        self.t0 = 0.0
        self._ann = None

    @property
    def context(self) -> SpanContext:
        """This span's identity — valid during AND after the span (the
        exemplar/latency record after a `with` block still needs it)."""
        return SpanContext(self.trace, self.id)

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        if stack:
            top = stack[-1]
            self.parent = top.id
            self.trace = top.trace
        else:
            # thread-root span: an attach()ed context (the explicit
            # cross-thread / cross-process handoff) parents it; with
            # nothing attached this span is a trace root and mints the id
            att = getattr(_tls, "attached", None)
            if att is not None:
                self.parent = att.span_id
                self.trace = att.trace_id
            else:
                self.trace = _mint_trace_id()
        stack.append(self)
        if self.tracer.annotate_device:
            ann = _trace_annotation(self.name)
            if ann is not None:
                self._ann = ann
                ann.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        self.tracer._record(self.name, self.t0, t1 - self.t0, self.id,
                            self.parent, self.args, trace=self.trace)
        return False


def _trace_annotation(name: str):
    """jax.profiler.TraceAnnotation(name) or None when jax (or the
    profiler module) is unavailable — tracing must work in a stub
    environment."""
    try:
        from jax.profiler import TraceAnnotation
    except Exception:
        return None
    try:
        return TraceAnnotation(name)
    except Exception:
        return None


class Tracer:
    """Bounded ring buffer of completed spans + the enable switch."""

    def __init__(self, capacity: int = 8192, annotate_device: bool = True):
        self.enabled = False
        self.annotate_device = annotate_device
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(capacity))
        # perf_counter origin so exported timestamps are relative to
        # tracer creation (chrome trace wants microseconds, any epoch)
        self._epoch = time.perf_counter()

    # -- recording -----------------------------------------------------------

    def span(self, name: str, **args):
        """Context manager timing a section. Disabled -> shared no-op.
        With a thread-ambient tenant attached (utils/tenancy — REST
        handlers attach it from X-Tenant), spans carry it as a `tenant`
        attribute; an explicit tenant= arg wins."""
        if not self.enabled:
            return NULL_SPAN
        if "tenant" not in args:
            t = _tenancy.current_tenant()
            if t is not None:
                args["tenant"] = t
        return _Span(self, name, args or None)

    def instant(self, name: str, **args):
        """Zero-duration marker event (compile-cache insertions, helper
        auto-disables, injected faults, ...). Parents to the innermost
        active span — or the attach()ed context on a worker thread — so
        markers land inside the trace that caused them."""
        if not self.enabled:
            return
        stack = getattr(_tls, "stack", None)
        if stack:
            parent, trace = stack[-1].id, stack[-1].trace
        else:
            att = getattr(_tls, "attached", None)
            if att is not None:
                parent, trace = att.span_id, att.trace_id
            else:
                parent, trace = None, _mint_trace_id()
        self._record(name, time.perf_counter(), 0.0, next(_counter),
                     parent, args or None, phase="i", trace=trace)

    def record_complete(self, name: str, t0: float, t1: float,
                        parent: Optional[SpanContext] = None,
                        **args) -> Optional[SpanContext]:
        """Record an already-finished span from explicit timestamps
        (time.perf_counter() domain) under an explicit parent context —
        the retroactive form the serving pipeline uses for per-request
        lifecycle spans measured across thread handoffs (a queued-time
        span is only known when the collector picks the request up).
        Returns the recorded span's context (chain children off it), or
        None when tracing is disabled."""
        if not self.enabled:
            return None
        sid = next(_counter)
        trace = parent.trace_id if parent is not None else _mint_trace_id()
        self._record(name, t0, t1 - t0, sid,
                     parent.span_id if parent is not None else None,
                     args or None, trace=trace)
        return SpanContext(trace, sid)

    def _record(self, name, t0, dur, span_id, parent, args, phase="X",
                trace=None):
        ev = {
            "name": name,
            "ph": phase,
            "ts": round((t0 - self._epoch) * 1e6, 3),  # microseconds
            "dur": round(dur * 1e6, 3),
            "id": span_id,
            "parent": parent,
            "trace": trace,
            "tid": threading.get_ident(),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    # -- readout -------------------------------------------------------------

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """The n newest events (all when n is None, none when n <= 0 —
        a negative slice must never invert into 'everything BUT the
        newest n')."""
        with self._lock:
            evs = list(self._events)
        if n is None:
            return evs
        n = int(n)
        return evs[-n:] if n > 0 else []

    def clear(self):
        with self._lock:
            self._events.clear()

    def to_jsonl(self, n: Optional[int] = None) -> str:
        return "\n".join(json.dumps(ev) for ev in self.recent(n)) + "\n"

    def to_chrome_trace(self) -> dict:
        """chrome://tracing / Perfetto "trace event format" document."""
        events = []
        for ev in self.recent():
            ce = {
                "name": ev["name"],
                "ph": ev["ph"],
                "ts": ev["ts"],
                "pid": 1,
                "tid": ev["tid"],
            }
            if ev["ph"] == "X":
                ce["dur"] = ev["dur"]
            else:
                ce["s"] = "t"  # instant scope: thread
            args = dict(ev.get("args") or {})
            args["span_id"] = ev["id"]
            if ev.get("parent") is not None:
                args["parent_span_id"] = ev["parent"]
            if ev.get("trace"):
                args["trace_id"] = ev["trace"]
            ce["args"] = args
            events.append(ce)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def write_jsonl(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_jsonl())
        return path


# -- the process-global tracer ------------------------------------------------

_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def enable(flag: bool = True):
    """Turn span recording on/off process-wide."""
    _TRACER.enabled = bool(flag)


def is_enabled() -> bool:
    return _TRACER.enabled


def span(name: str, **args):
    """Module-level shortcut: `with tracing.span("fit/step"): ...`."""
    if not _TRACER.enabled:
        return NULL_SPAN
    return _TRACER.span(name, **args)


def instant(name: str, **args):
    _TRACER.instant(name, **args)


def record_complete(name: str, t0: float, t1: float,
                    parent: Optional[SpanContext] = None,
                    **args) -> Optional[SpanContext]:
    return _TRACER.record_complete(name, t0, t1, parent, **args)


# -- context propagation ------------------------------------------------------

def current_context() -> Optional[SpanContext]:
    """The active span context on this thread: the innermost open span,
    else the attach()ed handoff context, else None. Disabled -> None
    after one flag check."""
    if not _TRACER.enabled:
        return None
    stack = getattr(_tls, "stack", None)
    if stack:
        top = stack[-1]
        return SpanContext(top.trace, top.id)
    return getattr(_tls, "attached", None)


def current_trace_id() -> Optional[str]:
    """Just the active trace id (log records, flight-recorder events)."""
    if not _TRACER.enabled:
        return None
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1].trace
    att = getattr(_tls, "attached", None)
    return att.trace_id if att is not None else None


def current_traceparent() -> Optional[str]:
    """The active context as a W3C traceparent header value, or None —
    what an outbound HTTP client attaches so the remote server joins
    this trace."""
    ctx = current_context()
    return ctx.traceparent() if ctx is not None else None


def attach(ctx: Optional[SpanContext]):
    """Make `ctx` the ambient parent for root spans (and instants) on
    THIS thread — the explicit handoff that keeps parentage across a
    queue hop (collector -> dispatcher, prefetch workers, push drains)
    instead of silently starting new roots. Returns a token for
    detach(); always pair them (or use `attached_ctx`). attach(None)
    deliberately clears the ambient context (a worker starting an item
    that carried no context must not inherit the previous item's)."""
    if not _TRACER.enabled:
        return _DISABLED_TOKEN
    prev = getattr(_tls, "attached", None)
    _tls.attached = ctx
    return prev


def detach(token):
    """Restore the ambient context saved by the paired attach()."""
    if token is _DISABLED_TOKEN:
        return
    _tls.attached = token


class attached_ctx:
    """`with tracing.attached_ctx(ctx): ...` — scope-bound attach/detach."""

    __slots__ = ("ctx", "_tok")

    def __init__(self, ctx: Optional[SpanContext]):
        self.ctx = ctx

    def __enter__(self):
        self._tok = attach(self.ctx)
        return self.ctx

    def __exit__(self, *exc):
        detach(self._tok)
        return False
