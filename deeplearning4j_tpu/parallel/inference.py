"""ParallelInference — multi-request serving over the device mesh.

Reference: deeplearning4j-scaleout/.../parallelism/ParallelInference.java
(:33-126) — a pool of model replicas fed from a queue, with
InferenceMode.SEQUENTIAL (one request per replica call) vs BATCHED (dynamic
batching via BatchedInferenceObservable, inference/observers/).

TPU-native design: one set of replicated parameters on the mesh; the
"replica pool" is replaced by batch sharding — a dynamically-batched
request group is sharded across the data axis and executed once. Dynamic
batching (the BATCHED mode) carries over from the reference; two
serving-specific mechanisms go beyond it:

* **Shape buckets** — every forward runs at one of a small fixed set of
  batch sizes (powers of two up to `max_batch_size` by default): a fused
  group of n examples is padded up to the smallest bucket >= n by
  cyclically wrapping rows (`mesh.pad_wrap`) and the pad rows sliced off
  the result. Only ~log2(max_batch_size) forward traces ever compile no
  matter how request sizes vary; without bucketing every distinct group
  size is a fresh `jax.jit` trace of `model.output` — a compile storm.
  `warmup()` precompiles all buckets before traffic, and `metrics()`
  exposes per-bucket hit counts plus the model's `output_compile_count`
  so retraces are a visible number, not mystery tail latency.

* **Pipelined collect → dispatch** — the BATCHED collector is split into
  two stages joined by a bounded handoff queue: the *collect* thread
  drains the request queue, concatenates and bucket-pads on the host, and
  hands the prepared group off; the *dispatch* thread runs the device
  forward and scatters results to the waiting callers. Host batch
  assembly of group k+1 overlaps device execution of group k (double
  buffering — same idea as the training-side async prefetch,
  data/iterators.AsyncDataSetIterator).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import List, Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.parallel.mesh import (
    batch_sharded,
    data_parallel_mesh,
    data_shards,
    pad_wrap,
    replicated,
)
from deeplearning4j_tpu.utils import blackbox as _blackbox
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.utils.concurrency import (
    QueueAborted,
    get_abortable,
    put_abortable,
)

logger = logging.getLogger("deeplearning4j_tpu")


class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class RequestValidationError(ValueError):
    """The REQUEST was malformed (empty, or feature shape mismatching the
    endpoint's) — distinguishes client faults from server-side ValueErrors
    so REST layers can map 400 vs 500 correctly."""


class ReplicaUnavailable(RuntimeError):
    """This replica could not take — or had to give back — the request
    BEFORE its device forward ran: admission after shutdown/abort, or a
    queued future failed by an eviction sweep. The request never touched
    the model, so it is safe to resubmit verbatim; ReplicaPool does
    exactly that on a healthy sibling. Contrast the plain RuntimeError an
    abort() puts on IN-FLIGHT futures (the group inside the device
    forward): those may have side effects in flight and are genuinely
    lost — the only failures the eviction contract lets callers see."""


def _queue_depth(ref) -> int:
    pi = ref()
    if pi is None:
        return 0
    return pi._q.qsize() + pi._handoff.qsize()


def power_of_two_buckets(max_batch_size: int) -> List[int]:
    """Default bucket set: 1, 2, 4, ... up to and including
    `max_batch_size` (appended as-is when not itself a power of two)."""
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(int(max_batch_size))
    return out


class ParallelInference:
    def __init__(
        self,
        model,
        mesh=None,
        inference_mode: str = InferenceMode.BATCHED,
        max_batch_size: int = 64,
        batch_timeout_ms: float = 2.0,
        buckets: Optional[Sequence[int]] = None,
        handoff_capacity: int = 2,
        health_stall_after: float = 30.0,
        component_prefix: str = "serving",
    ):
        self.model = model
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.mode = inference_mode
        self.max_batch_size = int(max_batch_size)
        if self.max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {max_batch_size}")
        self.batch_timeout = batch_timeout_ms / 1e3
        self.n_shards = data_shards(self.mesh)
        if buckets is None:
            self.buckets = power_of_two_buckets(self.max_batch_size)
        else:
            self.buckets = sorted({int(b) for b in buckets})
            if not self.buckets or self.buckets[0] < 1:
                raise ValueError(f"invalid bucket set {buckets}")
            if self.buckets[-1] < self.max_batch_size:
                raise ValueError(
                    f"largest bucket {self.buckets[-1]} < max_batch_size "
                    f"{self.max_batch_size}: a full fused group would have "
                    f"no bucket to land in"
                )
        model._require_init()
        rep = replicated(self.mesh)
        model.params_list = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, rep), model.params_list
        )
        # one lock guards admission (shutdown flag + expected shape) and
        # the stats counters; device work happens outside it
        self._lock = threading.Lock()
        self._q: "queue.Queue" = queue.Queue()
        self._handoff: "queue.Queue" = queue.Queue(maxsize=handoff_capacity)
        self._expected_shape = None  # set by the first request (under lock)
        # flipped by the first SUCCESSFUL forward: until then the pinned
        # shape is provisional and a failed forward unpins it, so one
        # malformed first request cannot poison the endpoint forever
        self._shape_confirmed = False
        self._shutdown = False
        # hard-stop flag (abort(), the ReplicaPool eviction path): the
        # pipeline threads exit at their next queue poll instead of
        # draining; queued + in-flight futures fail explicitly
        self._abort = threading.Event()
        # futures of the group the dispatcher currently holds (set just
        # before the device forward): the only requests abort() cannot
        # re-route — they fail, everything else is retriable upstream
        self._inflight: List[Future] = []
        # _stats is PER-INSTANCE (the JSON /metrics schema: this
        # endpoint's traffic); the registry counters below are
        # process-global aggregates across every ParallelInference in the
        # process — deriving either from the other would conflate the two
        # scopes, so both are maintained
        self._stats = {
            "requests": 0,
            "examples": 0,
            "batches": 0,
            "oversized": 0,
            "bucket_hits": {b: 0 for b in self.buckets},
        }
        # shared-registry serving instruments (same registry as training's
        # fit_step_* / compile_total — ONE scrape sees both). Children are
        # resolved here once; the request path only touches the cached
        # handles. The queue-depth gauge reads through a weakref so a
        # shut-down ParallelInference is not kept alive by the registry
        # (the newest instance owns the gauge).
        reg = _metrics.get_registry()
        self._m_requests = reg.counter(
            "serving_requests_total", "inference requests admitted").labels()
        self._m_examples = reg.counter(
            "serving_examples_total", "inference examples admitted").labels()
        self._m_bucket = reg.counter(
            "serving_bucket_hits_total",
            "fused groups served, by landing bucket", ("bucket",))
        self._m_oversized = reg.counter(
            "serving_oversized_total",
            "requests larger than every bucket (ran unfused)").labels()
        self._m_handoff = reg.histogram(
            "serving_handoff_stall_seconds",
            "collector time blocked handing a prepared group to the "
            "dispatcher (device a full group behind = backpressure)"
        ).labels()
        ref = weakref.ref(self)
        reg.gauge(
            "serving_queue_depth",
            "requests + prepared groups waiting for the device"
        ).set_function(lambda: _queue_depth(ref))
        self._collect_t: Optional[threading.Thread] = None
        self._dispatch_t: Optional[threading.Thread] = None
        # liveness (utils/health): each pipeline stage holds a busy slot
        # only while it OWNS work — waiting on an empty request queue is
        # idle, but a dispatcher wedged inside a device forward (or a
        # collector blocked handing off to a dead device) goes stale and
        # the watchdog flips `component_health{component=...}`. GET
        # /health on the serving layer aggregates exactly this.
        self._hb_collect: Optional[_health.Heartbeat] = None
        self._hb_dispatch: Optional[_health.Heartbeat] = None
        self.component_prefix = component_prefix
        if self.mode == InferenceMode.BATCHED:
            hreg = _health.get_health()
            self._hb_collect = hreg.register(
                f"{component_prefix}_collector",
                stall_after=health_stall_after)
            self._hb_dispatch = hreg.register(
                f"{component_prefix}_dispatcher",
                stall_after=health_stall_after)
            self._collect_t = threading.Thread(
                target=self._collector, daemon=True,
                name="dl4j-serving-collector")
            self._dispatch_t = threading.Thread(
                target=self._dispatcher, daemon=True,
                name="dl4j-serving-dispatch")
            self._collect_t.start()
            self._dispatch_t.start()

    # -- public --------------------------------------------------------------

    def output(self, x):
        """Thread-safe inference. In BATCHED mode the call may be fused
        with concurrent callers' batches (reference:
        BatchedInferenceObservable)."""
        xx = np.asarray(x)
        with self._lock:
            # shutdown check and enqueue under ONE lock: a request admitted
            # here is visible to shutdown()'s drain, so its Future always
            # resolves (result or explicit shutdown error) — never hangs
            if self._shutdown:
                raise ReplicaUnavailable(
                    "ParallelInference has been shut down")
            if xx.shape[0] == 0:
                # 0 is a multiple of every bucket, so an empty request
                # would sail through _pad at 0 rows and compile a fresh
                # 0-shape trace — reject it at admission instead
                raise RequestValidationError("empty request (0 examples)")
            if self._expected_shape is None:
                # under the lock: two concurrent FIRST callers must not both
                # see None and admit mismatched shapes into one fused group
                self._expected_shape = xx.shape[1:]
            elif xx.shape[1:] != self._expected_shape:
                # validate HERE, not deep inside the collector where a bad
                # request would fail the whole fused group
                raise RequestValidationError(
                    f"request feature shape {xx.shape[1:]} does not match "
                    f"this ParallelInference's {self._expected_shape}"
                )
            self._stats["requests"] += 1
            self._stats["examples"] += xx.shape[0]
            self._m_requests.inc()
            self._m_examples.inc(xx.shape[0])
            fut: Optional[Future] = None
            if (self.mode == InferenceMode.BATCHED
                    and xx.shape[0] <= self.max_batch_size):
                fut = Future()
                # put_nowait: the request queue is unbounded, so this is
                # exactly `put` — minus the lint-rejected blocking form
                self._q.put_nowait((xx, fut))
        if fut is not None:
            return fut.result()
        # SEQUENTIAL mode, or an oversized request: run it alone instead of
        # overshooting a fused group arbitrarily (device work off-lock)
        return self._run(xx)

    def warmup(self, feature_shape: Optional[Sequence[int]] = None,
               dtype=np.float32):
        """Precompile the forward for every bucket before traffic, so the
        first requests never pay a trace+compile. Fixes the expected
        feature shape (or uses the one already fixed by a request)."""
        with self._lock:
            if feature_shape is not None:
                fs = tuple(feature_shape)
                if self._expected_shape is None:
                    self._expected_shape = fs
                elif fs != self._expected_shape:
                    raise ValueError(
                        f"warmup shape {fs} does not match this "
                        f"ParallelInference's {self._expected_shape}"
                    )
            fs = self._expected_shape
        if fs is None:
            raise ValueError(
                "warmup() needs a feature shape: pass feature_shape= or "
                "serve one request first"
            )
        for b in self.buckets:
            self._run(np.zeros((b,) + fs, dtype), count=False)
        return self

    def metrics(self) -> dict:
        """Point-in-time serving counters. `forward_compiles` is the
        model's trace count — in steady state it equals the number of
        distinct post-padding shapes (≤ len(buckets)); growth under
        traffic means something is defeating the buckets."""
        with self._lock:
            m = {
                "mode": self.mode,
                "requests": self._stats["requests"],
                "examples": self._stats["examples"],
                "batches": self._stats["batches"],
                "oversized": self._stats["oversized"],
                "bucket_hits": dict(self._stats["bucket_hits"]),
            }
        m["buckets"] = list(self.buckets)
        m["max_batch_size"] = self.max_batch_size
        m["batch_timeout_ms"] = self.batch_timeout * 1e3
        m["queue_depth"] = self._q.qsize() + self._handoff.qsize()
        m["forward_compiles"] = int(
            getattr(self.model, "output_compile_count", 0))
        return m

    def shutdown(self):
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        workers_exited = True
        if self._collect_t is not None:
            # the admission lock above guarantees the sentinel is the LAST
            # item: everything already queued drains normally (served),
            # then the pipeline exits stage by stage (unbounded queue:
            # put_nowait is exact)
            self._q.put_nowait(None)
            self._collect_t.join(timeout=10)
            self._dispatch_t.join(timeout=10)
            workers_exited = (not self._collect_t.is_alive()
                              and not self._dispatch_t.is_alive())
        for hb in (self._hb_collect, self._hb_dispatch):
            if hb is not None:
                _health.get_health().unregister(hb)
        if not workers_exited:
            # a slow in-flight forward (e.g. first compile) outlived the
            # join timeout: the pipeline is still draining and will resolve
            # every Future itself — sweeping now would steal its sentinel
            # and fail work it was about to serve
            return
        # post-drain sweep: if a worker died abnormally, fail any stranded
        # Future explicitly instead of hanging its caller forever
        self._sweep_futures(RuntimeError("ParallelInference shut down"))

    def abort(self, reason: str = "aborted"):
        """Hard stop — the ReplicaPool eviction path. Unlike shutdown()
        (which drains: everything queued is still served), abort() stops
        the pipeline at its next poll and FAILS queued and in-flight
        futures with a RuntimeError naming `reason`. Callers routing
        through a ReplicaPool never see those failures — the pool
        retries admission-level RuntimeErrors on a healthy replica;
        only requests already inside the device forward are lost, which
        is exactly the eviction contract (fail only in-flight)."""
        with self._lock:
            already = self._shutdown and self._abort.is_set()
            self._shutdown = True
        if already:
            return
        self._abort.set()
        for t in (self._collect_t, self._dispatch_t):
            if t is not None:
                # a healthy thread exits within one queue poll; a WEDGED
                # one (the reason for the eviction) is left behind as a
                # daemon — its heartbeat is unregistered below, so it
                # cannot re-trip the watchdog
                t.join(timeout=2.0)
        # in-flight futures (inside the device forward) are genuinely
        # lost — non-retryable; everything still QUEUED never ran and
        # fails retryable, so a pool re-routes it with zero caller-visible
        # errors
        err = RuntimeError(f"ParallelInference {reason} (in flight)")
        for fut in list(self._inflight):
            if not fut.done():
                try:
                    fut.set_exception(err)
                except Exception:
                    pass  # lost the race against a completing forward
        self._sweep_futures(ReplicaUnavailable(f"ParallelInference {reason}"))
        for hb in (self._hb_collect, self._hb_dispatch):
            if hb is not None:
                _health.get_health().unregister(hb)

    def _sweep_futures(self, err: Exception):
        for q in (self._q, self._handoff):
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                futs = ([item[1]] if q is self._q else item[3]) \
                    if item is not None else []
                for fut in futs:
                    if not fut.done():
                        try:
                            fut.set_exception(err)
                        except Exception:
                            pass

    # -- internals -----------------------------------------------------------

    def _bucket_for(self, n: int) -> Optional[int]:
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _pad(self, batch: np.ndarray):
        """Bucket-pad then shard-pad. Returns (padded, n, bucket). The
        post-padding shape is what the jit trace sees, so the distinct
        trace count is len({shard-padded bucket sizes}), not the number of
        distinct request/group sizes."""
        n = batch.shape[0]
        b = self._bucket_for(n)
        if b is not None:
            batch = pad_wrap(batch, b)
        # non-divisible sizes are padded by wrapping and sliced — sharded
        # execution with a stable trace shape instead of a replicated
        # fallback
        batch = pad_wrap(batch, self.n_shards)
        return batch, n, b

    def _count_batch(self, b: Optional[int]):
        with self._lock:
            self._stats["batches"] += 1
            if b is None:
                self._stats["oversized"] += 1
            else:
                self._stats["bucket_hits"][b] += 1
        if b is None:
            self._m_oversized.inc()
        else:
            self._m_bucket.labels(str(b)).inc()

    def _forward_padded(self, padded: np.ndarray, n: int,
                        b: Optional[int], count: bool = True):
        """The ONE device forward both paths (caller-thread `_run` and the
        BATCHED dispatcher) go through: sharded dispatch, host readback,
        pad rows sliced off. A multi-output ComputationGraph returns a
        list; the batch slice applies per output, not to the list."""
        try:
            with _tracing.span("serve/forward", bucket=b, rows=n):
                out = self.model.output(
                    jax.device_put(padded, batch_sharded(self.mesh)))
            if isinstance(out, (list, tuple)):
                out = [np.asarray(o)[:n] for o in out]
            else:
                out = np.asarray(out)[:n]
        except BaseException:
            with self._lock:
                if (not self._shape_confirmed
                        and self._expected_shape == padded.shape[1:]):
                    # the shape that pinned _expected_shape never ran
                    # successfully (e.g. a feature width the model
                    # rejects): unpin, so later well-formed requests can
                    # re-pin instead of being rejected forever. The
                    # equality guard keeps a stale failing group from
                    # clobbering a NEWER pin by a different shape
                    self._expected_shape = None
            raise
        with self._lock:
            self._shape_confirmed = True
        if count:  # after the forward: a failed batch is not a served one
            self._count_batch(b)
        return out

    @staticmethod
    def _rows(out, start: int, stop: int):
        if isinstance(out, list):
            return [o[start:stop] for o in out]
        return out[start:stop]

    def _run(self, xx: np.ndarray, count: bool = True):
        padded, n, b = self._pad(xx)
        return self._forward_padded(padded, n, b, count)

    def _put_handoff(self, item, futs=()) -> bool:
        """Backpressured put toward the dispatcher. Blocks while the
        device is a full group behind (that IS the backpressure), but
        aborts — failing the group's futures instead of wedging the
        collector forever — if the dispatcher thread died or the
        pipeline was abort()ed."""
        try:
            put_abortable(
                self._handoff, item,
                abort=lambda: (self._abort.is_set()
                               or (self._dispatch_t is not None
                                   and not self._dispatch_t.is_alive())))
            return True
        except QueueAborted:
            for fut in futs:
                if not fut.done():
                    try:
                        # never dispatched — retryable on another replica
                        fut.set_exception(ReplicaUnavailable(
                            "ParallelInference dispatcher unavailable "
                            "(died or aborted)"))
                    except Exception:
                        pass
            return False

    # BATCHED pipeline, stage 1: drain + concatenate + pad on the host
    def _collector(self):
        pending = None  # request that would overflow the current group
        hb = self._hb_collect
        while True:
            if pending is not None:
                item, pending = pending, None
            else:
                # poll-loop get (abort predicate: only the hard-stop
                # flag — the graceful-shutdown sentinel must drain the
                # queue in order, so the collector never exits ahead of
                # it). No busy slot while waiting here: an EMPTY request
                # queue is idle, not a stall.
                try:
                    item = get_abortable(self._q, abort=self._abort)
                except QueueAborted:
                    return  # abort(): sweep fails whatever is queued
            if item is None:
                self._put_handoff(None)
                return
            # work in hand: from here until the handoff completes this
            # thread owes progress (a block inside _emit's handoff put
            # means the device is wedged — exactly what should degrade)
            with hb.busy():
                group = [item]
                count = item[0].shape[0]
                # drain more requests until batch limit or short timeout
                while count < self.max_batch_size:
                    try:
                        nxt = self._q.get(timeout=self.batch_timeout)
                    except queue.Empty:
                        break
                    if nxt is None:
                        self._emit(group)
                        self._put_handoff(None)
                        return
                    if (count + nxt[0].shape[0] > self.max_batch_size
                            or nxt[0].shape[1:] != item[0].shape[1:]):
                        # would overflow max_batch_size (and possibly fall
                        # off the bucket set) — or, during an unpin/re-pin
                        # window before the first successful forward, has
                        # a different feature shape (admission normally
                        # guarantees uniformity; this makes mixed-shape
                        # fusion structurally impossible) — start the
                        # next group
                        pending = nxt
                        break
                    group.append(nxt)
                    count += nxt[0].shape[0]
                self._emit(group)

    def _emit(self, group):
        """Host-side batch assembly; blocks on the bounded handoff queue
        when the device is a full group behind (backpressure)."""
        try:
            batch = (np.concatenate([g[0] for g in group], axis=0)
                     if len(group) > 1 else group[0][0])
            padded, n, b = self._pad(batch)
        except BaseException as e:  # propagate to all waiting callers
            for _, fut in group:
                if not fut.done():
                    fut.set_exception(e)
            return
        t0 = time.perf_counter()
        futs = [fut for _, fut in group]
        self._put_handoff(
            (padded, n, b, futs, [g[0].shape[0] for g in group]), futs)
        self._m_handoff.observe(time.perf_counter() - t0)

    # BATCHED pipeline, stage 2: device forward + scatter results
    def _dispatcher(self):
        while True:
            try:
                # exits on the collector's sentinel; the abort predicate
                # covers the hard stop and a collector that died WITHOUT
                # delivering one, so the dispatcher cannot outlive its
                # feeder
                work = get_abortable(
                    self._handoff,
                    abort=lambda: (self._abort.is_set()
                                   or (self._collect_t is not None
                                       and not self._collect_t.is_alive()
                                       and self._handoff.empty())))
            except QueueAborted:
                return
            if work is None:
                return
            padded, n, b, futs, sizes = work
            # busy only while a group is in hand: a forward that never
            # returns (device wedge) leaves this slot stale and the
            # watchdog flips serving_dispatcher to degraded/unhealthy
            with self._hb_dispatch.busy():
                self._inflight = futs
                try:
                    out = self._forward_padded(padded, n, b)
                    off = 0
                    for fut, k in zip(futs, sizes):
                        try:  # abort() may fail the future concurrently
                            if not fut.done():
                                fut.set_result(
                                    self._rows(out, off, off + k))
                        except Exception:
                            pass
                        off += k
                except BaseException as e:  # propagate to waiting callers
                    for fut in futs:
                        if not fut.done():
                            try:
                                fut.set_exception(e)
                            except Exception:
                                pass
                finally:
                    self._inflight = []


class ReplicaPool:
    """Self-healing pool of N ParallelInference replicas — the recovery
    half of the PR 6 health model (reference: ParallelInference.java's
    worker pool, grown an immune system).

    Each replica registers its collector/dispatcher heartbeats under
    `<prefix>_r<i>_*`, so the watchdog sees every replica separately. The
    pool subscribes to health transitions: when any component of replica
    i flips UNHEALTHY (a dispatcher wedged inside a device forward, a
    collector blocked against a dead handoff — the PR 6 stall model), a
    supervisor thread EVICTS the replica (abort(): queued work fails
    retryable and is re-routed here; only the group already inside the
    device forward is lost) and RESPAWNS a fresh one under the same
    component names. Requests route round-robin over in-rotation
    replicas; a request that lands on a replica mid-eviction comes back
    as ReplicaUnavailable and is resubmitted on a healthy sibling, so
    callers never see an error for work that never ran.

    Observable by construction: `serving_replica_evictions_total` /
    `serving_replica_respawns_total{replica}` counters and the
    `serving_replicas_in_rotation` gauge live in the shared registry
    (one /metrics scrape shows the self-healing happening), each
    eviction/respawn lands in the flight recorder, and the
    `component_health{component=<prefix>_r<i>_*}` transition history
    shows the unhealthy→ok cycle.

    `model_factory` (optional) builds a fresh model per spawn — without
    it every replica shares `model` (one set of replicated params, the
    TPU-native reading of a "replica": what multiplies is the serving
    pipeline, not the weights)."""

    def __init__(
        self,
        model=None,
        n_replicas: int = 2,
        mesh=None,
        inference_mode: str = InferenceMode.BATCHED,
        max_batch_size: int = 64,
        batch_timeout_ms: float = 2.0,
        buckets: Optional[Sequence[int]] = None,
        handoff_capacity: int = 2,
        health_stall_after: float = 30.0,
        component_prefix: str = "serving",
        model_factory=None,
        auto_heal: bool = True,
        retry_window: float = 5.0,
    ):
        if model is None and model_factory is None:
            raise ValueError("ReplicaPool needs a model or a model_factory")
        if int(n_replicas) < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.n_replicas = int(n_replicas)
        self.component_prefix = component_prefix
        self.auto_heal = bool(auto_heal)
        self.retry_window = float(retry_window)
        self._factory = (model_factory if model_factory is not None
                         else (lambda: model))
        self._pi_kwargs = dict(
            mesh=mesh, inference_mode=inference_mode,
            max_batch_size=int(max_batch_size),
            batch_timeout_ms=float(batch_timeout_ms), buckets=buckets,
            handoff_capacity=handoff_capacity,
            health_stall_after=health_stall_after)
        self._lock = threading.Lock()
        self._rr = 0
        self._gen = [0] * self.n_replicas
        self._warmup_shape = None
        self._shutdown = False
        # THIS pool's lifecycle counts (the registry counters below are
        # process-global across every pool the process ever built)
        self._evictions = 0
        self._respawns = 0
        reg = _metrics.get_registry()
        self._m_evict = reg.counter(
            "serving_replica_evictions_total",
            "replicas evicted from the pool (unhealthy or explicit)",
            ("replica",))
        self._m_respawn = reg.counter(
            "serving_replica_respawns_total",
            "replicas respawned into the pool after an eviction",
            ("replica",))
        self._m_rerouted = reg.counter(
            "serving_replica_rerouted_total",
            "requests retried on a sibling after a retryable replica "
            "failure (never user-visible)").labels()
        self._gauge = reg.gauge(
            "serving_replicas_in_rotation",
            "replicas currently taking traffic").labels()
        # slots hold None while a replica is mid-respawn (out of rotation)
        self._replicas: List[Optional[ParallelInference]] = [None] * \
            self.n_replicas
        for i in range(self.n_replicas):
            self._replicas[i] = self._spawn(i)
        self._gauge.set(self.n_replicas)
        # eviction requests flow through a queue to the supervisor: the
        # health listener fires on the dl4j-watchdog thread, which must
        # never block on an abort()'s thread joins
        self._evict_q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True,
            name=f"dl4j-replica-supervisor-{component_prefix}")
        self._supervisor.start()
        _health.get_health().add_listener(self._on_health_transition)

    # -- spawning / routing ---------------------------------------------------

    def _prefix(self, idx: int) -> str:
        return f"{self.component_prefix}_r{idx}"

    def _spawn(self, idx: int) -> ParallelInference:
        pi = ParallelInference(self._factory(),
                               component_prefix=self._prefix(idx),
                               **self._pi_kwargs)
        if self._warmup_shape is not None:
            try:
                pi.warmup(self._warmup_shape)
            except Exception:
                logger.exception("replica %d warmup failed (serving "
                                 "anyway; first requests pay the compile)",
                                 idx)
        return pi

    def _pick(self) -> Optional[ParallelInference]:
        with self._lock:
            if self._shutdown:
                raise RuntimeError("ReplicaPool has been shut down")
            for _ in range(self.n_replicas):
                idx = self._rr % self.n_replicas
                self._rr += 1
                pi = self._replicas[idx]
                if pi is not None:
                    return pi
        return None

    def output(self, x):
        """Thread-safe inference with failover: retryable replica
        failures (eviction races, mid-respawn gaps) are resubmitted on a
        healthy sibling inside `retry_window`; only non-retryable
        failures — a group already inside a device forward at eviction
        time, or a genuine model error — reach the caller."""
        deadline = time.monotonic() + self.retry_window
        last: Optional[Exception] = None
        while True:
            pi = self._pick()
            if pi is None:
                last = last or RuntimeError("no replica in rotation")
            else:
                try:
                    return pi.output(x)
                except RequestValidationError:
                    raise  # the client's fault on ANY replica
                except ReplicaUnavailable as e:
                    last = e
                    self._m_rerouted.inc()
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"no healthy replica within {self.retry_window:.1f}s"
                ) from last
            # a respawn is at most an abort-join + constructor away;
            # breathe instead of spinning the admission lock
            time.sleep(0.005)

    def warmup(self, feature_shape: Optional[Sequence[int]] = None,
               dtype=np.float32):
        """Precompile every bucket on every replica; the shape is kept so
        respawned replicas warm themselves before re-entering rotation."""
        with self._lock:
            replicas = [pi for pi in self._replicas if pi is not None]
        for pi in replicas:
            pi.warmup(feature_shape, dtype)
        if feature_shape is not None:
            self._warmup_shape = tuple(feature_shape)
        elif replicas and replicas[0]._expected_shape is not None:
            self._warmup_shape = replicas[0]._expected_shape
        return self

    # -- self-healing ---------------------------------------------------------

    def _on_health_transition(self, tr: dict):
        if tr.get("to") != _health.UNHEALTHY or self._shutdown:
            return
        comp = tr.get("component", "")
        for idx in range(self.n_replicas):
            if comp.startswith(self._prefix(idx) + "_"):
                self.request_eviction(
                    idx, reason=f"{comp} unhealthy "
                    f"({tr.get('stalled_for_seconds')}s stall)")
                return

    def request_eviction(self, idx: int, reason: str):
        """Queue an eviction for the supervisor thread (safe from any
        thread, including the watchdog's transition callback). The
        replica's CURRENT generation rides along: two components of one
        wedged replica both flipping UNHEALTHY queue two requests, and
        the stale second one must not evict the healthy respawn the
        first one produced."""
        idx = int(idx)
        with self._lock:
            gen = self._gen[idx]
        self._evict_q.put_nowait((idx, gen, reason))

    def _supervise(self):
        while True:
            try:
                idx, gen, reason = get_abortable(self._evict_q, self._stop)
            except QueueAborted:
                return
            try:
                self.evict(idx, reason, if_generation=gen)
            except Exception:
                logger.exception("replica %d eviction failed", idx)

    def evict(self, idx: int, reason: str = "evicted",
              if_generation: Optional[int] = None):
        """Take replica `idx` out of rotation, abort it (queued work
        fails retryable and re-routes; only in-flight work is lost), and
        — under auto_heal — respawn a fresh replica into the slot.
        `if_generation` makes the eviction conditional: a no-op when the
        slot has already been respawned past that generation."""
        with self._lock:
            pi = self._replicas[idx]
            if pi is None or self._shutdown:
                return  # already mid-respawn, or shutting down
            if if_generation is not None and self._gen[idx] != if_generation:
                logger.info(
                    "replica %d eviction request for gen %d is stale "
                    "(slot is at gen %d) — skipping", idx, if_generation,
                    self._gen[idx])
                return
            self._replicas[idx] = None
            self._gen[idx] += 1
            gen = self._gen[idx]
        self._gauge.set(self._in_rotation())
        with self._lock:
            self._evictions += 1
        self._m_evict.labels(str(idx)).inc()
        _blackbox.get_recorder().record_event(
            "replica_evicted", replica=idx, generation=gen, reason=reason)
        logger.warning("replica %d evicted (gen %d): %s", idx, gen, reason)
        pi.abort(f"replica {idx} evicted: {reason}")
        if not self.auto_heal or self._shutdown:
            return
        fresh = self._spawn(idx)
        with self._lock:
            if self._shutdown:
                fresh.abort("pool shut down during respawn")
                return
            self._replicas[idx] = fresh
        self._gauge.set(self._in_rotation())
        with self._lock:
            self._respawns += 1
        self._m_respawn.labels(str(idx)).inc()
        _blackbox.get_recorder().record_event(
            "replica_respawned", replica=idx, generation=gen)
        logger.info("replica %d respawned (gen %d)", idx, gen)

    def _in_rotation(self) -> int:
        with self._lock:
            return sum(1 for pi in self._replicas if pi is not None)

    # -- introspection / lifecycle -------------------------------------------

    @property
    def model(self):
        with self._lock:
            for pi in self._replicas:
                if pi is not None:
                    return pi.model
        return None

    @property
    def buckets(self) -> List[int]:
        with self._lock:
            for pi in self._replicas:
                if pi is not None:
                    return list(pi.buckets)
        return []

    @property
    def _expected_shape(self):
        # duck-typing for InferenceServer's /health feature_shape field
        with self._lock:
            for pi in self._replicas:
                if pi is not None and pi._expected_shape is not None:
                    return pi._expected_shape
        return self._warmup_shape

    def metrics(self) -> dict:
        """Pool-aggregated serving counters in the ParallelInference
        schema (requests/examples/batches/bucket_hits summed over live
        replicas), plus the pool's own lifecycle numbers and a
        per-replica breakdown."""
        with self._lock:
            replicas = list(self._replicas)
            gens = list(self._gen)
        per, agg = [], None
        for idx, pi in enumerate(replicas):
            if pi is None:
                per.append({"replica": idx, "generation": gens[idx],
                            "in_rotation": False})
                continue
            m = pi.metrics()
            per.append({"replica": idx, "generation": gens[idx],
                        "in_rotation": True, "requests": m["requests"],
                        "examples": m["examples"], "batches": m["batches"],
                        "queue_depth": m["queue_depth"]})
            if agg is None:
                agg = m
            else:
                for k in ("requests", "examples", "batches", "oversized"):
                    agg[k] += m[k]
                for b, v in m["bucket_hits"].items():
                    agg["bucket_hits"][b] = agg["bucket_hits"].get(b, 0) + v
                agg["queue_depth"] += m["queue_depth"]
                agg["forward_compiles"] = max(agg["forward_compiles"],
                                              m["forward_compiles"])
        if agg is None:  # every slot mid-respawn: still a valid scrape
            agg = {"mode": self._pi_kwargs["inference_mode"], "requests": 0,
                   "examples": 0, "batches": 0, "oversized": 0,
                   "bucket_hits": {}, "buckets": [],
                   "max_batch_size": self._pi_kwargs["max_batch_size"],
                   "batch_timeout_ms":
                       self._pi_kwargs["batch_timeout_ms"],
                   "queue_depth": 0, "forward_compiles": 0}
        agg["replicas"] = per
        agg["n_replicas"] = self.n_replicas
        agg["in_rotation"] = sum(1 for pi in replicas if pi is not None)
        with self._lock:
            agg["evictions"] = self._evictions
            agg["respawns"] = self._respawns
        return agg

    def shutdown(self):
        """Graceful: drain every replica (queued work is served), stop
        the supervisor, unsubscribe from health transitions."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            replicas = list(self._replicas)
            self._replicas = [None] * self.n_replicas
        _health.get_health().remove_listener(self._on_health_transition)
        self._stop.set()
        self._supervisor.join(timeout=10)
        for pi in replicas:
            if pi is not None:
                pi.shutdown()
        self._gauge.set(0)
