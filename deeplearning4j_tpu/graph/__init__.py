"""Graph embeddings (reference: deeplearning4j-graph, 3,363 LoC —
IGraph/Graph, random-walk iterators, DeepWalk + GraphHuffman +
InMemoryGraphLookupTable, GraphVectors serving API)."""

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.deepwalk import DeepWalk, GraphVectors, Node2Vec
from deeplearning4j_tpu.graph.walkers import (RandomWalkIterator,
    Node2VecWalkIterator)

__all__ = ["Graph", "DeepWalk", "GraphVectors", "Node2Vec",
           "RandomWalkIterator", "Node2VecWalkIterator"]
