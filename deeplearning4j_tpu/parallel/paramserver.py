"""Asynchronous parameter server for embedding training (DCN path).

Design (the written PS/embedding-async plan; reference:
ParameterServerTrainer.java:32-66 pushNDArray over Aeron,
SparkSequenceVectors.java:292-294 VoidParameterServer):

Why a PS at all, when gradient allreduce covers dense training? Embedding
workloads touch a SPARSE, tiny slice of an enormous table each step;
allreducing a dense table-sized gradient per step is absurd, and the
hot-word rows tolerate stale updates (async SGD is the reference's own
semantics — it documents the nondeterminism, DeepWalk.java:223). So:

  server:  row-sharded tables (syn0/syn1/syn1neg) in host memory, one
           process per DCN endpoint; applies row DELTAS in arrival order
           (Hogwild-style), serves row PULLS. HTTP here; the transport is
           the pluggable part (the reference swapped Aeron in the same
           slot) — gRPC/DCN drops into _Transport without touching
           trainer logic.
  client:  per-batch: PULL the rows the batch touches, run the jitted
           device skip-gram/CBOW step (nlp/learning.py — the
           AggregateSkipGram analog) on those rows only, PUSH back the
           row deltas fire-and-forget on a bounded queue.
  sharding: row id -> shard by modulo over server endpoints; each
           endpoint owns rows i with i % n_servers == k, so pushes from
           all workers for one row serialize at one owner (no
           cross-server coordination).

Staleness bound: one in-flight push window per worker (the queue), i.e.
a worker's pulls lag its own pushes by <= queue depth; convergence for
embedding objectives is unaffected in practice (the reference ships the
same tradeoff).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import random
import struct
import threading
import time
import urllib.request
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.utils import faultpoints as _faults
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import resourcemeter as _resourcemeter
from deeplearning4j_tpu.utils import tenancy as _tenancy
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.utils.concurrency import (
    QueueAborted,
    put_abortable,
)
from deeplearning4j_tpu.utils.jsonhttp import (
    JsonHttpServer,
    json_response,
    traced_headers,
)

logger = logging.getLogger("deeplearning4j_tpu")


# -- binary wire format -------------------------------------------------------
# A real [vocab, dim] f32 table pushed as JSON lists is ~10x the bytes and
# far more CPU than raw rows; the hot routes (/pull.bin, /push.bin) move
# raw little-endian buffers instead. JSON routes remain for debugging and
# as the "transport is the pluggable part" demonstration.
#
#   request  := u16 name_len | name utf8 | u32 n_rows | u32 dim
#               | i64 * n_rows row ids | f32 * n_rows * dim deltas
#               (dim == 0 for pulls: no payload follows the ids)
#   pull rsp := u32 n_rows | u32 dim | f32 * n_rows * dim raw rows
#
# bf16 wire payload (opt-in, EmbeddingPSClient(wire_dtype="bf16")): the
# top bit of the `dim` field tags the ROW PAYLOAD as bf16 (u16 per
# element, round-to-nearest-even truncation of the f32). A pull request
# (dim == 0, no payload) sets the tag to ask for a bf16 RESPONSE.
# Accumulation stays f32 on both ends — only the wire narrows, halving
# `paramserver_wire_bytes_total` for the row blocks. Row ids stay i64.
# An untagged request is f32, so old clients keep working unchanged.

_BF16_FLAG = 0x80000000


def _bf16_from_f32(a: np.ndarray) -> np.ndarray:
    """f32 -> bf16 (as u16), round-to-nearest-even. NaN payloads can
    carry into the exponent under RNE — pinned to the canonical quiet
    NaN instead (sign preserved)."""
    u = np.ascontiguousarray(a, "<f4").view("<u4")
    rne = ((u >> np.uint32(16)) & np.uint32(1)) + np.uint32(0x7FFF)
    out = ((u + rne) >> np.uint32(16)).astype("<u2")
    nan = ((u & np.uint32(0x7F800000)) == np.uint32(0x7F800000)) \
        & ((u & np.uint32(0x007FFFFF)) != 0)
    if nan.any():
        out = np.where(
            nan, ((u >> np.uint32(16)) & np.uint16(0x8000))
            .astype("<u2") | np.uint16(0x7FC0), out)
    return out


def _f32_from_bf16(u16: np.ndarray) -> np.ndarray:
    return (np.ascontiguousarray(u16, "<u2").astype("<u4")
            << np.uint32(16)).view("<f4")


def _pack_request(table: str, rows: np.ndarray,
                  deltas: Optional[np.ndarray] = None,
                  wire_dtype: str = "f32") -> bytes:
    name = table.encode()
    rows = np.ascontiguousarray(rows, dtype="<i8")
    flag = _BF16_FLAG if wire_dtype == "bf16" else 0
    if deltas is None:
        head = struct.pack("<H", len(name)) + name + struct.pack(
            "<II", rows.size, flag)
        return head + rows.tobytes()
    deltas = np.asarray(deltas, np.float32)
    if deltas.ndim != 2 or deltas.shape[0] != rows.size:
        raise ValueError(f"deltas must be [n_rows, dim], got {deltas.shape} "
                         f"for {rows.size} rows")
    head = struct.pack("<H", len(name)) + name + struct.pack(
        "<II", rows.size, deltas.shape[1] | flag)
    payload = (_bf16_from_f32(deltas) if flag
               else np.ascontiguousarray(deltas, "<f4"))
    return head + rows.tobytes() + payload.tobytes()


def _unpack_request(body: bytes):
    """Returns (table, rows, deltas_f32_or_None, wire_dtype) — the dtype
    tag tells a pull handler which payload encoding the CLIENT asked the
    response to use."""
    (name_len,) = struct.unpack_from("<H", body, 0)
    name = body[2:2 + name_len].decode()
    n, dim = struct.unpack_from("<II", body, 2 + name_len)
    wire_dtype = "bf16" if dim & _BF16_FLAG else "f32"
    dim &= ~_BF16_FLAG
    off = 2 + name_len + 8
    rows = np.frombuffer(body, "<i8", count=n, offset=off)
    off += 8 * n
    deltas = None
    if dim:
        if wire_dtype == "bf16":
            deltas = _f32_from_bf16(np.frombuffer(
                body, "<u2", count=n * dim, offset=off)).reshape(n, dim)
        else:
            deltas = np.frombuffer(body, "<f4", count=n * dim,
                                   offset=off).reshape(n, dim)
    return name, rows, deltas, wire_dtype


def _pack_rows(rows: np.ndarray, wire_dtype: str = "f32") -> bytes:
    rows = np.asarray(rows, np.float32)
    n, dim = rows.shape
    if wire_dtype == "bf16":
        return struct.pack("<II", n, dim | _BF16_FLAG) \
            + _bf16_from_f32(rows).tobytes()
    return struct.pack("<II", n, dim) \
        + np.ascontiguousarray(rows, "<f4").tobytes()


def _unpack_rows(body: bytes) -> np.ndarray:
    n, dim = struct.unpack_from("<II", body, 0)
    if dim & _BF16_FLAG:
        dim &= ~_BF16_FLAG
        return _f32_from_bf16(np.frombuffer(
            body, "<u2", count=n * dim, offset=8)).reshape(n, dim)
    return np.frombuffer(body, "<f4", count=n * dim, offset=8).reshape(n, dim)


class EmbeddingParameterServer:
    """One shard-owner process. Tables are {name: [rows, dim]} float32.

    `journal_dir` arms crash durability: every push is appended to a
    write-ahead journal (`journal.bin`, length-prefixed binary push
    records — the wire format, reused) BEFORE it is applied, and
    `snapshot()` persists the tables (`tables.npz`, atomic rename) and
    truncates the journal. A restarted server pointed at the same
    directory restores snapshot + replays the journal tail, so a shard
    owner dying mid-run costs nothing but the restart window — the
    client's replay buffer covers that (EmbeddingPSClient). A torn final
    journal record (killed mid-append) is detected by its length prefix
    and discarded; everything before it replays. `snapshot_every` > 0
    auto-snapshots after that many pushes, bounding replay time."""

    def __init__(self, tables: Dict[str, np.ndarray], port: int = 0,
                 journal_dir: Optional[str] = None,
                 snapshot_every: int = 0):
        self.tables = {k: np.asarray(v, np.float32) for k, v in tables.items()}
        self._locks = {k: threading.Lock() for k in self.tables}
        self._server = JsonHttpServer(post=self._post, port=port)
        self.pushes_applied = 0
        self.journal_dir = journal_dir
        self.snapshot_every = int(snapshot_every)
        self._journal = None
        self._jlock = threading.Lock()
        self._since_snapshot = 0
        # RPC counters + latency histograms in the shared registry, by
        # route — the PS hot path (pull.bin/push.bin) becomes a series an
        # operator can alert on instead of a private attribute
        reg = _metrics.get_registry()
        self._m_rpc = reg.counter(
            "paramserver_rpc_total", "parameter-server RPCs served",
            ("route",))
        self._m_rpc_sec = reg.histogram(
            "paramserver_rpc_seconds", "parameter-server RPC service time",
            ("route",))
        self._m_journal = reg.counter(
            "paramserver_journal_records_total",
            "pushes appended to the write-ahead journal").labels()
        self._m_replayed = reg.counter(
            "paramserver_journal_replayed_total",
            "journaled pushes re-applied on restart").labels()
        self._m_snapshots = reg.counter(
            "paramserver_snapshots_total",
            "table snapshots persisted (journal truncations)").labels()
        if journal_dir is not None:
            self._restore_from_dir()

    @property
    def port(self) -> int:
        return self._server.port

    # -- durability -----------------------------------------------------------

    def _snapshot_path(self) -> str:
        return os.path.join(self.journal_dir, "tables.npz")

    def _journal_path(self) -> str:
        return os.path.join(self.journal_dir, "journal.bin")

    def _restore_from_dir(self):
        os.makedirs(self.journal_dir, exist_ok=True)
        snap = self._snapshot_path()
        if os.path.exists(snap):
            with np.load(snap) as npz:
                for name in npz.files:
                    if name not in self.tables:
                        raise ValueError(
                            f"snapshot table {name!r} unknown to this "
                            f"server (have {sorted(self.tables)})")
                    if npz[name].shape != self.tables[name].shape:
                        raise ValueError(
                            f"snapshot table {name!r} shape "
                            f"{npz[name].shape} != configured "
                            f"{self.tables[name].shape}")
                    self.tables[name] = npz[name].astype(np.float32)
        replayed = 0
        jpath = self._journal_path()
        if os.path.exists(jpath):
            with open(jpath, "rb") as f:
                buf = f.read()
            off = 0
            while off + 4 <= len(buf):
                (rec_len,) = struct.unpack_from("<I", buf, off)
                if off + 4 + rec_len > len(buf):
                    logger.warning(
                        "journal ends in a torn record (%d of %d bytes) — "
                        "a writer died mid-append; discarding the tail",
                        len(buf) - off - 4, rec_len)
                    break
                name, rows, deltas, _ = _unpack_request(
                    buf[off + 4:off + 4 + rec_len])
                # same contract as the snapshot branch above: a journal
                # written by a differently-configured server fails with
                # a descriptive error, not a raw KeyError/IndexError
                if name not in self.tables:
                    raise ValueError(
                        f"journal record #{replayed} targets table "
                        f"{name!r} unknown to this server "
                        f"(have {sorted(self.tables)})")
                table = self.tables[name]
                if rows.size and (int(rows.max()) >= table.shape[0]
                                  or int(rows.min()) < 0):
                    raise ValueError(
                        f"journal record #{replayed} for table {name!r} "
                        f"addresses row {int(rows.max())} outside the "
                        f"configured shape {table.shape}")
                if deltas.shape[1:] != table.shape[1:]:
                    raise ValueError(
                        f"journal record #{replayed} for table {name!r} "
                        f"has row dim {deltas.shape[1:]} != configured "
                        f"{table.shape[1:]}")
                self._apply(name, rows.tolist(), deltas)
                replayed += 1
                off += 4 + rec_len
            if off != len(buf) and off + 4 > len(buf):
                logger.warning("journal ends mid-length-prefix; "
                               "discarding the tail")
        if replayed:
            self._m_replayed.inc(replayed)
            logger.info("paramserver restored: replayed %d journaled "
                        "push(es) from %s", replayed, jpath)
        self._journal = open(jpath, "ab")

    def snapshot(self) -> str:
        """Persist the tables and truncate the journal — the recovery
        point moves to NOW. Atomic: readers of the directory never see a
        half-written snapshot (tmp + rename), and the journal is only
        truncated after the snapshot is durable."""
        if self.journal_dir is None:
            raise ValueError("server was built without journal_dir")
        with self._jlock:
            copies = {}
            for name in sorted(self.tables):
                with self._locks[name]:
                    copies[name] = self.tables[name].copy()
            path = self._snapshot_path()
            tmp = f"{path}.{os.getpid()}.tmp"
            np.savez(tmp, **copies)
            # np.savez appends .npz when missing — normalize
            tmp_real = tmp if os.path.exists(tmp) else tmp + ".npz"
            os.replace(tmp_real, path)
            if self._journal is not None:
                self._journal.close()
            self._journal = open(self._journal_path(), "wb")
            self._since_snapshot = 0
        self._m_snapshots.inc()
        logger.info("paramserver snapshot: %s", path)
        return path

    def _journal_push(self, name: str, rows, deltas: np.ndarray) -> bool:
        """Journal the push and apply it under ONE _jlock hold, so a
        concurrent snapshot() (which also takes _jlock) can never copy
        tables missing a delta whose journal record it is about to
        truncate. Returns True when an auto-snapshot is due — taken by
        the caller AFTER the apply, so the triggering push is in the
        snapshot it causes."""
        payload = _pack_request(name, np.asarray(rows, np.int64),
                                np.asarray(deltas, np.float32))
        with self._jlock:
            if self._journal is None:  # closed (stop()): apply-only
                self._apply(name, rows, deltas)
                return False
            self._journal.write(struct.pack("<I", len(payload)) + payload)
            self._journal.flush()
            self._apply(name, rows, deltas)
            self._since_snapshot += 1
            due = (self.snapshot_every > 0
                   and self._since_snapshot >= self.snapshot_every)
        self._m_journal.inc()
        return due

    # -- core ops ------------------------------------------------------------

    def pull(self, name: str, rows: List[int]) -> np.ndarray:
        with self._locks[name]:
            return self.tables[name][rows].copy()

    def _apply(self, name: str, rows: List[int], deltas: np.ndarray) -> None:
        with self._locks[name]:
            np.add.at(self.tables[name], rows, deltas)
            self.pushes_applied += 1

    def push(self, name: str, rows: List[int], deltas: np.ndarray) -> None:
        """Apply row deltas in arrival order (async SGD). Journaled
        BEFORE application when durability is armed — a crash between
        the two re-applies the delta on restart, which async-SGD
        semantics tolerate (at-least-once beats silent loss)."""
        if self.journal_dir is not None:
            if self._journal_push(name, rows, deltas):
                self.snapshot()
            return
        self._apply(name, rows, deltas)

    # -- http transport ------------------------------------------------------

    def _post(self, path, body, headers):
        if path in ("/pull.bin", "/push.bin", "/pull", "/push"):
            route = path.lstrip("/")
            t0 = time.perf_counter()
            try:
                # nests under jsonhttp's http/server span, which already
                # joined the client's traceparent — a pull made mid-
                # request shows up inside the caller's trace with the
                # route named
                with _tracing.span("ps/server/" + route):
                    out = self._post_timed(path, body)
                # tenant wire accounting: request + response payload,
                # booked under the identity that arrived in X-Tenant
                # (jsonhttp attached it to this handler thread, next to
                # the traceparent). Charged server-side only, so an
                # in-process client+server pair never double-counts.
                resp = out[2] if len(out) > 2 else b""
                _resourcemeter.note_wire(
                    _tenancy.current_tenant(),
                    _resourcemeter.TIER_PARAMSERVER,
                    len(body) + (len(resp)
                                 if isinstance(resp, (bytes, bytearray))
                                 else 0))
                return out
            finally:
                self._m_rpc.labels(route).inc()
                self._m_rpc_sec.labels(route).observe(
                    time.perf_counter() - t0)
        if path == "/meta":
            return json_response({
                "tables": {k: list(v.shape) for k, v in self.tables.items()},
                "pushes_applied": self.pushes_applied,
            })
        return None

    def _post_timed(self, path, body):
        if path == "/pull.bin":
            # the request's dtype tag asks which encoding the response
            # payload should ride — bf16 halves the row-block bytes
            name, rows, _, wire_dtype = _unpack_request(body)
            return 200, "application/octet-stream", _pack_rows(
                self.pull(name, rows.tolist()), wire_dtype)
        if path == "/push.bin":
            # _unpack_request already widened a bf16 payload to f32 —
            # accumulation (np.add.at in _apply) is always f32
            name, rows, deltas, _ = _unpack_request(body)
            self.push(name, rows.tolist(), deltas)
            return 200, "application/octet-stream", b"ok"
        req = json.loads(body)
        name = req["table"]
        rows = req["rows"]
        if path == "/pull":
            return json_response({"data": self.pull(name, rows).tolist()})
        self.push(name, rows, np.asarray(req["deltas"], np.float32))
        return json_response({"status": "ok"})

    def start(self) -> int:
        return self._server.start()

    def stop(self):
        self._server.stop()
        with self._jlock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None


class EmbeddingPSClient:
    """Worker-side pull/push. Pushes ride a bounded background queue
    (fire-and-forget, the Aeron pushNDArray analog); pulls are
    synchronous (the step needs the rows). The wire format is raw
    little-endian rows (see _pack_request) — JSON would be ~10x the bytes
    for real [vocab, dim] tables.

    Failover: every RPC retries with bounded exponential backoff
    (`max_retries`/`retry_backoff`), and a push whose endpoint stays
    down after the retries is PARKED in a per-endpoint FIFO replay
    buffer (`replay_capacity` batches) instead of dropped — the drain
    thread re-attempts parked pushes before any newer work for that
    endpoint, so a restarted server (journal-backed, see
    EmbeddingParameterServer) receives every batch in order and the run
    converges. Only replay-buffer OVERFLOW drops, and `dropped_pushes` /
    `paramserver_client_push_dropped_total` still count every loss —
    degradation stays observable, never silent. `replay_capacity=0`
    restores the old drop-immediately behavior."""

    def __init__(self, urls: List[str], queue_size: int = 64,
                 timeout: float = 10.0, max_retries: int = 2,
                 retry_backoff: float = 0.05,
                 replay_capacity: int = 128,
                 tenant: Optional[str] = None,
                 wire_dtype: str = "f32"):
        self.urls = [u.rstrip("/") for u in urls]
        # opt-in narrow wire payload (mirrors the sharded trainer's
        # grad_dtype="bf16"): row blocks ride bf16, ids stay i64,
        # accumulation stays f32 server-side. NEVER default-on — the
        # caller opts into the precision trade explicitly.
        if wire_dtype not in ("f32", "bf16"):
            raise ValueError(f"wire_dtype must be 'f32' or 'bf16', "
                             f"got {wire_dtype!r}")
        self.wire_dtype = wire_dtype
        # the identity this client's RPCs book under on the server side
        # (X-Tenant next to the traceparent). Explicit beats ambient:
        # the push drain runs on its own thread, where the fit loop's
        # thread-local tenant would otherwise be invisible.
        self.tenant = None if tenant is None else _tenancy.intern(tenant)
        self.timeout = timeout
        self.max_retries = max(0, int(max_retries))
        self.retry_backoff = float(retry_backoff)
        self.replay_capacity = max(0, int(replay_capacity))
        # per-client backoff jitter stream (de-correlates clients; needs
        # no cross-run determinism — fault injection has its own RNGs)
        self._jitter = random.Random()
        self.dropped_pushes = 0
        self._dims: Dict[str, int] = {}
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        # per-endpoint parked pushes, FIFO; drain-thread-only once the
        # worker is running (close() touches it only after the join)
        self._pending: List[deque] = [deque() for _ in self.urls]
        reg = _metrics.get_registry()
        self._m_rpc = reg.counter(
            "paramserver_client_rpc_total",
            "parameter-server client RPCs issued", ("route",))
        self._m_rpc_sec = reg.histogram(
            "paramserver_client_rpc_seconds",
            "parameter-server client RPC round-trip time", ("route",))
        self._m_dropped = reg.counter(
            "paramserver_client_push_dropped_total",
            "push batches lost to dead/misbehaving endpoints").labels()
        self._m_retries = reg.counter(
            "paramserver_client_retry_total",
            "RPC attempts beyond the first (endpoint flaky/down)",
            ("route",))
        self._m_replayed = reg.counter(
            "paramserver_client_push_replayed_total",
            "parked pushes delivered after their endpoint came back"
        ).labels()
        self._m_wire = reg.counter(
            "paramserver_wire_bytes_total",
            "client-side request + response payload bytes by route — "
            "the number wire_dtype='bf16' halves for row blocks",
            ("route",))
        self._stop = threading.Event()
        # liveness: the drain holds a busy slot only while delivering a
        # push batch — a wedged endpoint (socket past its timeout, DNS
        # hang) flips `component_health{component=paramserver_push}`
        self._hb = _health.get_health().register(
            "paramserver_push", stall_after=max(60.0, 4.0 * timeout))
        self._worker = threading.Thread(target=self._drain, daemon=True,
                                        name="dl4j-paramserver-push")
        self._worker.start()

    def _owner(self, row: int) -> int:
        return row % len(self.urls)

    def _post_bin(self, url: str, route: str, payload: bytes) -> bytes:
        label = route.lstrip("/")
        t0 = time.perf_counter()
        # the client RPC span opens FIRST so the traceparent injected
        # below carries ITS context: the remote server's http/server span
        # parents to this span, and the cross-process tree reads
        # caller -> ps/client/<route> -> http/server -> ps/server/<route>
        with _tracing.span("ps/client/" + label):
            req = urllib.request.Request(
                f"{url}{route}", data=payload,
                headers=_tenancy.tenant_headers(
                    traced_headers(
                        {"Content-Type": "application/octet-stream"}),
                    tenant=self.tenant))
            try:  # count failures too (server side does the same): an
                # outage must show up in the RPC series, not just the
                # drop counter
                # chaos hook: an `error` fault is a dropped/refused RPC
                # (the retry/replay machinery absorbs it); `latency` is a
                # slow network; `hang` is the wedged-endpoint case the
                # push drain's heartbeat exists for
                _faults.fault_point("paramserver_rpc", route=label)
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    resp = r.read()
                self._m_wire.labels(label).inc(len(payload) + len(resp))
                return resp
            finally:
                self._m_rpc.labels(label).inc()
                self._m_rpc_sec.labels(label).observe(
                    time.perf_counter() - t0)

    def _post_with_retry(self, url: str, route: str, payload: bytes,
                         deadline: Optional[float] = None) -> bytes:
        """`_post_bin` with bounded, JITTERED exponential backoff — a
        blip (server restart, transient network fault) costs latency,
        not data. The final failure propagates; push callers park the
        payload for replay, pull callers surface it (the step needs the
        rows NOW).

        Jitter (±50% per sleep, from a per-client RNG): pure exponential
        backoff synchronizes — every client that failed in the same
        server outage retries at the same instants and thundering-herds
        the recovering endpoint; the spread de-correlates them. `deadline`
        (time.monotonic seconds) caps the TOTAL retry spend: a caller
        with a latency budget stops burning it on a dead endpoint — the
        failure surfaces while the budget can still pay for a fallback."""
        label = route.lstrip("/")
        attempt = 0
        while True:
            try:
                return self._post_bin(url, route, payload)
            except Exception:
                if attempt >= self.max_retries or self._stop.is_set():
                    raise
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    raise
                sleep = (self.retry_backoff * (2 ** attempt)
                         * self._jitter.uniform(0.5, 1.5))
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if sleep >= remaining:
                        raise  # the wait alone would blow the budget
                self._m_retries.labels(label).inc()
                # stop-aware sleep: a close() mid-backoff aborts the wait
                self._stop.wait(sleep)
                attempt += 1

    def _dim(self, table: str) -> int:
        """Table dim, cached from the first shard's /meta (needed to shape
        empty pulls)."""
        if table not in self._dims:
            req = urllib.request.Request(self.urls[0] + "/meta", data=b"{}")
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                meta = json.loads(r.read())
            for k, shape in meta["tables"].items():
                self._dims[k] = int(shape[1])
        return self._dims[table]

    def _pull_shard(self, s: int, table: str, rows_sel: np.ndarray,
                    deadline: Optional[float]) -> np.ndarray:
        return _unpack_rows(self._post_with_retry(
            self.urls[s], "/pull.bin",
            _pack_request(table, rows_sel, wire_dtype=self.wire_dtype),
            deadline=deadline))

    def pull(self, table: str, rows: np.ndarray,
             deadline_ms: Optional[float] = None) -> np.ndarray:
        """Fetch rows (grouped per owning shard, order restored). Empty
        row sets return a well-formed [0, dim] array. `deadline_ms`
        caps the retry spend across every shard RPC: past it, the
        failure propagates instead of backing off further.

        The per-shard sub-pulls run CONCURRENTLY (one short-lived
        `dl4j-ps-pull-*` thread per shard with rows): an S-shard table
        costs ~max of the shard round trips, not their sum. Each thread
        keeps the full per-endpoint retry/backoff/deadline semantics
        (`_post_with_retry`), and the caller's span context is attached
        so the per-shard `ps/client/pull.bin` spans stay inside the
        calling step's trace. The threads are joined before return —
        nothing outlives the call."""
        deadline = (None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1e3)
        rows = np.asarray(rows, np.int64)
        if rows.size == 0:
            return np.zeros((0, self._dim(table)), np.float32)
        sels = [(s, sel) for s, sel in
                ((s, np.nonzero(rows % len(self.urls) == s)[0])
                 for s in range(len(self.urls)))
                if sel.size]
        out: Optional[np.ndarray] = None
        if len(sels) == 1:  # one owner: no thread overhead
            s, sel = sels[0]
            got = self._pull_shard(s, table, rows[sel], deadline)
            out = np.zeros((rows.size, got.shape[1]), np.float32)
            out[sel] = got
        else:
            ctx = _tracing.current_context()
            results: List[Optional[np.ndarray]] = [None] * len(sels)
            errors: List[BaseException] = []

            def one(i: int, s: int, sel: np.ndarray) -> None:
                try:
                    with _tracing.attached_ctx(ctx):
                        results[i] = self._pull_shard(
                            s, table, rows[sel], deadline)
                except Exception as e:
                    errors.append(e)

            threads = [threading.Thread(
                target=one, args=(i, s, sel), daemon=True,
                name=f"dl4j-ps-pull-{s}")
                for i, (s, sel) in enumerate(sels)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:  # same contract as the old serial walk: the
                # first shard failure propagates to the caller
                raise errors[0]
            for (s, sel), got in zip(sels, results):
                if out is None:
                    out = np.zeros((rows.size, got.shape[1]), np.float32)
                out[sel] = got
        self._dims.setdefault(table, int(out.shape[1]))
        return out

    def push_async(self, table: str, rows: np.ndarray,
                   deltas: np.ndarray) -> None:
        deltas = np.asarray(deltas, np.float32)
        if deltas.ndim != 2 or deltas.shape[0] != np.asarray(rows).size:
            raise ValueError(  # fail at the call site, not in the drain
                f"deltas must be [n_rows, dim], got {deltas.shape}")
        # the enqueue-time span context rides the item: the drain thread
        # attaches it, so the push RPC's spans (and its traceparent to
        # the server) stay in the trace of the step that produced the
        # deltas instead of rooting fresh per-push traces
        item = (table, np.asarray(rows, np.int64),
                np.asarray(deltas, np.float32),
                _tracing.current_context())
        if self._stop.is_set() or not self._worker.is_alive():
            # the drain is gone: an enqueue would never be serviced —
            # count the drop instead of losing gradient mass silently
            self.dropped_pushes += 1
            self._m_dropped.inc()
            logger.warning("PS push dropped (%d total): drain thread gone",
                           self.dropped_pushes)
            return
        try:
            self._q.put_nowait(item)
        except queue.Full:
            # backpressure: block — dropping would lose gradient mass.
            # Abortable: if the drain thread died (or close() ran), a
            # blocked producer counts a drop instead of wedging forever
            try:
                put_abortable(self._q, item,
                              abort=lambda: (self._stop.is_set()
                                             or not self._worker.is_alive()))
            except QueueAborted:
                self.dropped_pushes += 1
                self._m_dropped.inc()
                logger.warning(
                    "PS push dropped (%d total): drain thread gone",
                    self.dropped_pushes)

    def close(self):
        """Stop accepting pushes and retire the drain thread. Pushes
        already queued are still delivered (queued items win over the
        stop flag), so close() waits up to ~10s; against a dead endpoint
        delivery can outlast the join timeout — the daemon thread then
        finishes (or dies) on its own. Parked pushes get one last
        single-shot delivery attempt; whatever still cannot land is
        accounted as dropped — a closing client must not pretend parked
        work will ever flush."""
        self._stop.set()
        self._worker.join(timeout=10)
        if not self._worker.is_alive():
            self._flush_pending()
            for s, pend in enumerate(self._pending):
                while pend:
                    pend.popleft()
                    self._count_drop(
                        f"client closed with endpoint {s} still down")
        _health.get_health().unregister(self._hb)

    def _count_drop(self, why):
        self.dropped_pushes += 1
        self._m_dropped.inc()
        logger.warning("PS push dropped (%d total): %s",
                       self.dropped_pushes, why)

    def _deliver(self, table: str, rows: np.ndarray, deltas: np.ndarray,
                 ctx=None):
        """Route one push batch: per owning shard, the payload joins that
        endpoint's FIFO (behind anything parked from an outage — arrival
        order per shard is preserved) and the FIFO is flushed head-first."""
        for s in range(len(self.urls)):
            sel = np.nonzero(rows % len(self.urls) == s)[0]
            if sel.size == 0:
                continue
            # [payload, failed_before, ctx]: the flag turns a later
            # delivery into a counted replay; the span context stays with
            # ITS payload, so a parked push replayed while a newer item
            # drains still reports under the trace that produced it
            self._pending[s].append(
                [_pack_request(table, rows[sel], deltas[sel],
                               wire_dtype=self.wire_dtype), False, ctx])
            self._flush_endpoint(s)

    def _flush_endpoint(self, s: int):
        pend = self._pending[s]
        while pend:
            rec = pend[0]
            try:
                with _tracing.attached_ctx(rec[2]):
                    self._post_with_retry(self.urls[s], "/push.bin", rec[0])
            except Exception as e:
                rec[1] = True
                if self.replay_capacity == 0:
                    # failover disabled: the old drop-and-move-on path
                    pend.popleft()
                    self._count_drop(e)
                elif len(pend) > self.replay_capacity:
                    # overflow evicts the OLDEST parked push (its loss is
                    # the least stale) — and is the ONLY way a push is
                    # lost while the client lives
                    pend.popleft()
                    self._count_drop(
                        f"replay buffer full ({self.replay_capacity}) "
                        f"while endpoint {s} is down: {e}")
                return
            pend.popleft()
            if rec[1]:
                self._m_replayed.inc()

    def _flush_pending(self):
        for s in range(len(self.urls)):
            if self._pending[s]:
                self._flush_endpoint(s)

    def _drain(self):
        while True:
            try:
                # timeout-ful get doubles as the retry tick: while the
                # producer is quiet, parked pushes get re-attempted, so
                # a recovered endpoint converges without new traffic
                item = self._q.get(timeout=0.25)
            except queue.Empty:
                if self._stop.is_set():
                    return
                if any(self._pending):
                    with self._hb.busy():
                        self._flush_pending()
                continue
            table, rows, deltas, ctx = item
            try:
                with self._hb.busy():
                    self._deliver(table, rows, deltas, ctx)
            finally:
                self._q.task_done()

    def flush(self, timeout: float = 30.0) -> bool:
        """Wait until every push queued BEFORE this call has been
        ATTEMPTED (delivered or parked), bounded by `timeout`. Returns
        True when the drain caught up, False on timeout.

        This waits on the queue's unfinished-task count, NOT emptiness:
        the last item leaves the queue before its POST lands, so an
        emptiness poll lets a caller read tables the final delta has not
        reached yet (the RemoteUIStatsStorageRouter bug class, PR 8).
        And unlike a bare `Queue.join()`, the wait is bounded — a drain
        thread that died with items still queued (task_done never runs)
        or an endpoint wedged past its socket timeout makes this return
        False at the deadline instead of hanging forever past the
        advertised timeout. Parked pushes (endpoint down) are excluded —
        they wait for the endpoint, not for this call; `pending_pushes()`
        exposes them."""
        deadline = time.monotonic() + timeout
        q = self._q
        with q.all_tasks_done:
            while q.unfinished_tasks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                q.all_tasks_done.wait(remaining)
        return True

    def queued_pushes(self) -> int:
        """Push batches enqueued but not yet fully attempted — includes
        the in-flight item the drain is currently delivering (0 means
        every accepted push has been delivered or parked)."""
        return int(self._q.unfinished_tasks)

    def pending_pushes(self) -> int:
        """Push payloads parked for replay across all endpoints."""
        return sum(len(p) for p in self._pending)
