"""ComputationGraph configuration: vertex configs + GraphBuilder.

Analog of the reference's ComputationGraphConfiguration (748 LoC,
nn/conf/ComputationGraphConfiguration.java) and the vertex config set in
nn/conf/graph/ (MergeVertex, ElementWiseVertex, SubsetVertex, ...) plus the
RNN vertices in nn/conf/graph/rnn/.

A graph is: named inputs, a dict of named vertices (each with its list of
input names), and named outputs. Vertices are pure-data dataclasses; each
carries both its shape-inference rule (`output_type`) and its functional
forward (`forward(xs, env)`) — the runtime walk is a fold over the cached
topological order (reference: ComputationGraph.java:340,1055 topo cache;
:1291-1292 forward walk). Backward is autodiff; fan-out epsilon
accumulation (reference :1480-1502) falls out of jax.grad for free.

`env` carries per-minibatch context a vertex may need beyond its direct
inputs: the LayerContext, per-input-name masks (LastTimeStepVertex), and
the activation dict built so far (DuplicateToTimeSeriesVertex reads the
time length of another vertex's activation).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalInput,
    FeedForwardInput,
    RecurrentInput,
)
from deeplearning4j_tpu.nn.conf.serde import (
    config_from_dict,
    config_to_dict,
    register_config,
)


@dataclasses.dataclass(kw_only=True)
class GraphVertexConf:
    """Base for non-layer vertices (parameterless transforms)."""

    def output_type(self, its: List):
        raise NotImplementedError

    def forward(self, xs: List, env: dict):
        raise NotImplementedError


@register_config("vertex.layer")
@dataclasses.dataclass(kw_only=True)
class LayerVertex(GraphVertexConf):
    """A layer as a DAG node, with an optional input preprocessor
    (reference: nn/graph/vertex/impl/LayerVertex.java)."""

    layer: Optional[L.LayerConf] = None
    preprocessor: Optional[object] = None

    def output_type(self, its: List):
        it = its[0]
        if self.preprocessor is not None and it is not None:
            it = self.preprocessor.output_type(it)
        return self.layer.output_type(it) if it is not None else None

    # forward is special-cased by the runtime (params + state threading)


@register_config("vertex.merge")
@dataclasses.dataclass(kw_only=True)
class MergeVertex(GraphVertexConf):
    """Concatenate along the feature/channel axis (reference:
    MergeVertex.java concatenates along dim 1 of NCHW — here NHWC, so the
    last axis for ff/cnn/rnn alike)."""

    def output_type(self, its: List):
        first = its[0]
        if isinstance(first, ConvolutionalInput):
            return ConvolutionalInput(first.height, first.width,
                                      sum(i.channels for i in its))
        if isinstance(first, RecurrentInput):
            return RecurrentInput(sum(i.size for i in its), first.timesteps)
        return FeedForwardInput(sum(i.arity() for i in its))

    def forward(self, xs, env):
        return jnp.concatenate(xs, axis=-1)


@register_config("vertex.elementwise")
@dataclasses.dataclass(kw_only=True)
class ElementWiseVertex(GraphVertexConf):
    """Pointwise combine: add/subtract/product/average/max (reference:
    ElementWiseVertex.java — subtract requires exactly 2 inputs)."""

    op: str = "add"

    def output_type(self, its: List):
        return its[0]

    def forward(self, xs, env):
        op = self.op
        if op == "subtract":
            if len(xs) != 2:
                raise ValueError("ElementWiseVertex(subtract) needs 2 inputs")
            return xs[0] - xs[1]
        acc = xs[0]
        for x in xs[1:]:
            if op == "add" or op == "average":
                acc = acc + x
            elif op == "product":
                acc = acc * x
            elif op == "max":
                acc = jnp.maximum(acc, x)
            else:
                raise ValueError(f"unknown elementwise op {op!r}")
        if op == "average":
            acc = acc / len(xs)
        return acc


@register_config("vertex.subset")
@dataclasses.dataclass(kw_only=True)
class SubsetVertex(GraphVertexConf):
    """Feature-range slice, inclusive bounds (reference: SubsetVertex.java
    [from, to] on the feature axis)."""

    from_: int = 0
    to: int = 0

    def output_type(self, its: List):
        n = self.to - self.from_ + 1
        it = its[0]
        if isinstance(it, ConvolutionalInput):
            return ConvolutionalInput(it.height, it.width, n)
        if isinstance(it, RecurrentInput):
            return RecurrentInput(n, it.timesteps)
        return FeedForwardInput(n)

    def forward(self, xs, env):
        return xs[0][..., self.from_ : self.to + 1]


@register_config("vertex.stack")
@dataclasses.dataclass(kw_only=True)
class StackVertex(GraphVertexConf):
    """Concatenate along the batch axis (reference: StackVertex.java —
    used to push several inputs through shared layers)."""

    def output_type(self, its: List):
        return its[0]

    def forward(self, xs, env):
        return jnp.concatenate(xs, axis=0)


@register_config("vertex.unstack")
@dataclasses.dataclass(kw_only=True)
class UnstackVertex(GraphVertexConf):
    """Take slice `from_` of `stack_size` equal batch-axis parts
    (reference: UnstackVertex.java)."""

    from_: int = 0
    stack_size: int = 1

    def output_type(self, its: List):
        return its[0]

    def forward(self, xs, env):
        x = xs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_ * step : (self.from_ + 1) * step]


@register_config("vertex.scale")
@dataclasses.dataclass(kw_only=True)
class ScaleVertex(GraphVertexConf):
    """x * scale (reference: ScaleVertex.java)."""

    scale: float = 1.0

    def output_type(self, its: List):
        return its[0]

    def forward(self, xs, env):
        return xs[0] * self.scale


@register_config("vertex.shift")
@dataclasses.dataclass(kw_only=True)
class ShiftVertex(GraphVertexConf):
    """x + shift (reference: ShiftVertex.java)."""

    shift: float = 0.0

    def output_type(self, its: List):
        return its[0]

    def forward(self, xs, env):
        return xs[0] + self.shift


@register_config("vertex.reshape")
@dataclasses.dataclass(kw_only=True)
class ReshapeVertex(GraphVertexConf):
    """Reshape the per-example trailing dims; batch dim is preserved
    (reference: ReshapeVertex.java)."""

    new_shape: Sequence[int] = ()

    def output_type(self, its: List):
        s = tuple(self.new_shape)
        if len(s) == 1:
            return FeedForwardInput(s[0])
        if len(s) == 2:
            return RecurrentInput(s[1], s[0])
        if len(s) == 3:
            return ConvolutionalInput(s[0], s[1], s[2])
        return None

    def forward(self, xs, env):
        return xs[0].reshape((xs[0].shape[0],) + tuple(self.new_shape))


@register_config("vertex.preprocessor")
@dataclasses.dataclass(kw_only=True)
class PreprocessorVertex(GraphVertexConf):
    """Standalone InputPreProcessor as a vertex (reference:
    PreprocessorVertex.java)."""

    preprocessor: Optional[object] = None

    def output_type(self, its: List):
        return self.preprocessor.output_type(its[0])

    def forward(self, xs, env):
        return self.preprocessor(xs[0], {"timesteps": env.get("timesteps")})


@register_config("vertex.l2")
@dataclasses.dataclass(kw_only=True)
class L2Vertex(GraphVertexConf):
    """Pairwise L2 distance between two inputs -> [batch, 1] (reference:
    L2Vertex.java — siamese distance)."""

    eps: float = 1e-8

    def output_type(self, its: List):
        return FeedForwardInput(1)

    def forward(self, xs, env):
        a = xs[0].reshape(xs[0].shape[0], -1)
        b = xs[1].reshape(xs[1].shape[0], -1)
        d = a - b
        return jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) + self.eps)


@register_config("vertex.l2_normalize")
@dataclasses.dataclass(kw_only=True)
class L2NormalizeVertex(GraphVertexConf):
    """x / max(||x||2, eps) per example (reference: L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def output_type(self, its: List):
        return its[0]

    def forward(self, xs, env):
        x = xs[0]
        flat = x.reshape(x.shape[0], -1)
        n = jnp.sqrt(jnp.sum(flat * flat, axis=-1) + self.eps)
        return x / n.reshape((-1,) + (1,) * (x.ndim - 1))


@register_config("vertex.last_time_step")
@dataclasses.dataclass(kw_only=True)
class LastTimeStepVertex(GraphVertexConf):
    """[b,t,f] -> [b,f]: the last time step, or — when the named network
    input has a mask — the last *unmasked* step per example (reference:
    nn/conf/graph/rnn/LastTimeStepVertex.java)."""

    mask_input: Optional[str] = None

    def output_type(self, its: List):
        return FeedForwardInput(its[0].size)

    def forward(self, xs, env):
        x = xs[0]
        mask = None
        if self.mask_input is not None:
            mask = env.get("input_masks", {}).get(self.mask_input)
        if mask is None:
            return x[:, -1]
        idx = jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1
        idx = jnp.clip(idx, 0, x.shape[1] - 1)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]


@register_config("vertex.duplicate_to_time_series")
@dataclasses.dataclass(kw_only=True)
class DuplicateToTimeSeriesVertex(GraphVertexConf):
    """[b,f] -> [b,t,f], t taken from the named input's time axis
    (reference: nn/conf/graph/rnn/DuplicateToTimeSeriesVertex.java)."""

    ref_input: Optional[str] = None

    def output_type(self, its: List):
        return RecurrentInput(its[0].arity())

    def forward(self, xs, env):
        ref = env["activations"][self.ref_input]
        t = ref.shape[1]
        return jnp.broadcast_to(
            xs[0][:, None, :], (xs[0].shape[0], t, xs[0].shape[-1])
        )


# -- configuration -----------------------------------------------------------


@register_config("compgraph_conf")
@dataclasses.dataclass(kw_only=True)
class ComputationGraphConfiguration:
    """DAG network configuration (reference:
    nn/conf/ComputationGraphConfiguration.java)."""

    net_conf: object = None
    inputs: List[str] = dataclasses.field(default_factory=list)
    outputs: List[str] = dataclasses.field(default_factory=list)
    vertices: Dict[str, object] = dataclasses.field(default_factory=dict)
    vertex_inputs: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    input_types: Optional[List[object]] = None

    def to_json(self) -> str:
        return json.dumps(config_to_dict(self), indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        obj = config_from_dict(json.loads(s))
        if not isinstance(obj, ComputationGraphConfiguration):
            raise ValueError("JSON does not describe a ComputationGraphConfiguration")
        return obj

    # -- topology ------------------------------------------------------------

    def topological_order(self) -> List[str]:
        """Deterministic Kahn topo sort over input + vertex names
        (reference: ComputationGraph.java:340 cached topologicalOrder)."""
        indeg = {name: len(ins) for name, ins in self.vertex_inputs.items()}
        consumers: Dict[str, List[str]] = {}
        for name, ins in self.vertex_inputs.items():
            for src in ins:
                consumers.setdefault(src, []).append(name)
        order: List[str] = []
        ready = list(self.inputs)
        seen = set(self.inputs)
        while ready:
            v = ready.pop(0)
            order.append(v)
            for c in consumers.get(v, []):
                indeg[c] -= 1
                if indeg[c] == 0 and c not in seen:
                    seen.add(c)
                    ready.append(c)
        unreached = set(self.vertices) - set(order)
        if unreached:
            raise ValueError(
                f"graph has unreachable or cyclic vertices: {sorted(unreached)}"
            )
        return order


class GraphBuilder:
    """Fluent DAG builder (reference:
    ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self, net_conf):
        self._net_conf = net_conf
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, object] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._input_types: Optional[List[object]] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_bwd = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def add_layer(self, name: str, layer: L.LayerConf, *inputs: str,
                  preprocessor=None) -> "GraphBuilder":
        if not inputs:
            raise ValueError(f"layer {name!r} needs at least one input")
        self._check_new(name, inputs)
        if len(inputs) > 1:
            # a layer consumes exactly one activation: auto-insert a
            # MergeVertex over multiple inputs, as the reference does
            # (ComputationGraphConfiguration.java:580-584)
            merge_name = f"{name}-merge"
            if merge_name in self._vertices or merge_name in self._inputs:
                raise ValueError(
                    f"cannot auto-insert merge vertex {merge_name!r}: name "
                    "already taken"
                )
            self._vertices[merge_name] = MergeVertex()
            self._vertex_inputs[merge_name] = list(inputs)
            inputs = (merge_name,)
        self._vertices[name] = LayerVertex(layer=layer, preprocessor=preprocessor)
        self._vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(self, name: str, vertex: GraphVertexConf, *inputs: str) -> "GraphBuilder":
        if not inputs:
            raise ValueError(f"vertex {name!r} needs at least one input")
        self._check_new(name, inputs)
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def backprop_type(self, t: str) -> "GraphBuilder":
        self._backprop_type = t
        return self

    def t_bptt_lengths(self, fwd: int, bwd: Optional[int] = None) -> "GraphBuilder":
        self._tbptt_fwd = fwd
        self._tbptt_bwd = bwd if bwd is not None else fwd
        return self

    def _check_new(self, name, inputs):
        if name in self._vertices or name in self._inputs:
            raise ValueError(f"duplicate vertex name {name!r}")
        known = set(self._inputs) | set(self._vertices)
        for i in inputs:
            if i not in known:
                raise ValueError(
                    f"vertex {name!r} references unknown input {i!r} "
                    "(vertices must be added after their inputs)"
                )

    def build(self) -> ComputationGraphConfiguration:
        from deeplearning4j_tpu.nn.conf.network import (
            _apply_defaults,
            auto_preprocessor,
        )

        if not self._outputs:
            raise ValueError("set_outputs(...) is required")
        for name in self._outputs:
            if name not in self._vertices:
                raise ValueError(f"output {name!r} is not a vertex")
        conf = ComputationGraphConfiguration(
            net_conf=self._net_conf,
            inputs=self._inputs,
            outputs=self._outputs,
            vertices=self._vertices,
            vertex_inputs=self._vertex_inputs,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_bwd_length=self._tbptt_bwd,
            input_types=self._input_types,
        )
        # hyperparameter inheritance into every layer conf
        for v in self._vertices.values():
            if isinstance(v, LayerVertex):
                _apply_defaults(v.layer, self._net_conf)
        # shape inference + auto preprocessor insertion along topo order
        if self._input_types is not None:
            if len(self._input_types) != len(self._inputs):
                raise ValueError("set_input_types arity != add_inputs arity")
            types: Dict[str, object] = dict(zip(self._inputs, self._input_types))
            for name in conf.topological_order():
                if name in types:
                    continue
                v = self._vertices[name]
                its = [types.get(i) for i in self._vertex_inputs[name]]
                if any(i is None for i in its):
                    types[name] = None
                    continue
                if isinstance(v, LayerVertex):
                    it = its[0]
                    if v.preprocessor is None:
                        v.preprocessor = auto_preprocessor(it, v.layer)
                    if v.preprocessor is not None:
                        it = v.preprocessor.output_type(it)
                    v.layer.infer_n_in(it)
                    types[name] = v.layer.output_type(it)
                else:
                    types[name] = v.output_type(its)
        return conf
