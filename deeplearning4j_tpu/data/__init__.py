"""Subpackage."""
