"""REST k-NN server over a VPTree (reference:
nearestneighbor/server/NearestNeighborsServer.java:29,70 — loads a stored
points NDArray, builds a VPTree with --similarityFunction/--invert, and
serves POST /knn with {"k": int, "inputIndex": int} ->
{"results": [{"index": i}, ...]}; DTOs in nearestneighbor/model/).

Extensions beyond the reference API (same shape, additive):
- POST /knnvector {"k": int, "vector": [floats]} — query by raw vector
  instead of stored-point index.
- GET /health — liveness.
Distances are included in each result row (the reference computes them
but only returns indices).
"""

from __future__ import annotations

import argparse
import json
import logging

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.utils.jsonhttp import JsonHttpServer, json_response

logger = logging.getLogger("deeplearning4j_tpu")


class NearestNeighborsServer:
    def __init__(self, points: np.ndarray,
                 similarity_function: str = "euclidean",
                 invert: bool = False, port: int = 9000):
        self.points = np.asarray(points, np.float32)
        self.tree = VPTree(self.points, similarity_function, invert)
        self._server = JsonHttpServer(get=self._get, post=self._post,
                                      port=port)

    @property
    def port(self) -> int:
        return self._server.port

    # -- request handling ---------------------------------------------------

    def _get(self, path, body, headers):
        if path == "/health":
            return json_response({"status": "ok",
                                  "points": self.points.shape[0]})
        return None

    def _post(self, path, body, headers):
        req = json.loads(body or b"{}")
        if path == "/knn":
            k = int(req["k"])
            idx = int(req["inputIndex"])
            if not (0 <= idx < self.points.shape[0]):
                return json_response(
                    {"error": f"inputIndex {idx} out of range"}, 400)
            target = self.points[idx]
        elif path == "/knnvector":
            k = int(req["k"])
            target = np.asarray(req["vector"], np.float32)
            if target.shape != (self.points.shape[1],):
                return json_response(
                    {"error":
                     f"vector must have dim {self.points.shape[1]}"}, 400)
        else:
            return None
        indices, distances = self.tree.search(target, k)
        return json_response({
            "results": [
                {"index": int(i), "distance": float(d)}
                for i, d in zip(indices, distances)
            ]
        })

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        return self._server.start()

    def stop(self):
        self._server.stop()

    def join(self):
        self._server.join()


def main(argv=None):
    """CLI matching the reference's flags (NearestNeighborsServer.java):
    --ndarrayPath (a .npy file), --nearestNeighborsPort,
    --similarityFunction, --invert."""
    ap = argparse.ArgumentParser(description="k-NN REST server")
    ap.add_argument("--ndarrayPath", required=True)
    ap.add_argument("--nearestNeighborsPort", type=int, default=9000)
    ap.add_argument("--similarityFunction", default="euclidean")
    ap.add_argument("--invert", action="store_true")
    args = ap.parse_args(argv)
    points = np.load(args.ndarrayPath)
    server = NearestNeighborsServer(points, args.similarityFunction,
                                    args.invert, args.nearestNeighborsPort)
    # operator surface: announce through the package logger (library
    # code never prints — lint CC006); opt in to real output first
    from deeplearning4j_tpu import configure_logging

    if all(isinstance(h, logging.NullHandler) for h in logger.handlers):
        configure_logging()
    port = server.start()
    logger.info("nearest-neighbors server listening on :%d", port)
    try:
        server.join()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
