"""Sequence parallelism: ring attention over the 8-device CPU mesh ==
single-device full attention; the SelfAttentionLayer in the DSL trains.

This is NEW capability beyond the reference (SURVEY §5: DL4J has no
long-context machinery beyond TBPTT) — the equivalence test is the
contract that the sharded path computes the same math.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.sequence import (
    SEQ_AXIS,
    full_attention,
    ring_attention_sharded,
    ring_self_attention,
)


def _seq_mesh():
    return Mesh(np.array(jax.devices()), (SEQ_AXIS,))


def _qkv(B=2, T=32, H=4, D=8, seed=0):
    # T=32 (was 64): same 8-hop ring coverage at a quarter of the
    # compile/grad cost — these tests went from import-broken (the
    # jax.shard_map shim un-broke them) to ~130s of the 870s tier-1
    # budget, and the math they pin is shape-independent
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.standard_normal((B, T, H, D)) * 0.5, jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_equals_full_attention(causal):
    from deeplearning4j_tpu.parallel.mesh import shard_map_fn
    shard_map = shard_map_fn()
    from jax.sharding import PartitionSpec as P

    q, k, v = _qkv()
    mesh = _seq_mesh()
    spec = P(None, SEQ_AXIS, None, None)
    ring = shard_map(
        lambda q, k, v: ring_attention_sharded(q, k, v, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    out_ring = np.asarray(ring(q, k, v))
    out_full = np.asarray(full_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(out_ring, out_full, rtol=2e-5, atol=2e-6)


@pytest.mark.slow  # ~30-80s of 8-way SPMD compile on the 1.5-core gate box;
# tier-1 keeps the ring==full equivalence pair + the DSL layer test (at seed this
# whole file was import-broken, so gate coverage still strictly improves)
def test_ring_self_attention_projections():
    rng = np.random.default_rng(1)
    B, T, E, H = 2, 32, 16, 4
    x = jnp.asarray(rng.standard_normal((B, T, E)), jnp.float32)
    ws = [jnp.asarray(rng.standard_normal((E, E)) * 0.2, jnp.float32)
          for _ in range(4)]
    mesh = _seq_mesh()
    out = np.asarray(ring_self_attention(
        x, *ws, mesh=mesh, n_heads=H, causal=True))
    q = (x @ ws[0]).reshape(B, T, H, E // H)
    k = (x @ ws[1]).reshape(B, T, H, E // H)
    v = (x @ ws[2]).reshape(B, T, H, E // H)
    ref = np.asarray(
        full_attention(q, k, v, causal=True).reshape(B, T, E) @ ws[3])
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)


@pytest.mark.slow  # ~30-80s of 8-way SPMD compile on the 1.5-core gate box;
# tier-1 keeps the ring==full equivalence pair + the DSL layer test (at seed this
# whole file was import-broken, so gate coverage still strictly improves)
def test_ring_attention_bf16_accumulates_f32():
    """bf16 long-context inputs: softmax statistics accumulate in f32
    inside the ring, so the sharded bf16 result stays close to the f32
    full-attention truth (within one bf16 rounding of inputs/outputs) —
    and exactly matches single-device attention run with the same f32
    accumulation policy."""
    from deeplearning4j_tpu.parallel.mesh import shard_map_fn
    shard_map = shard_map_fn()
    from jax.sharding import PartitionSpec as P

    q, k, v = _qkv(T=16)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    mesh = _seq_mesh()
    spec = P(None, SEQ_AXIS, None, None)
    ring = shard_map(
        lambda q, k, v: ring_attention_sharded(q, k, v, causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    out_ring = np.asarray(ring(qb, kb, vb)).astype(np.float32)
    assert out_ring.dtype == np.float32  # cast back from bf16 for compare
    out_full_f32 = np.asarray(full_attention(q, k, v, causal=True))
    # error budget: bf16 inputs (~8-bit mantissa) dominate; f32 stats mean
    # no error growth with ring hops
    np.testing.assert_allclose(out_ring, out_full_f32, rtol=0.05, atol=0.02)
    # and bf16 single-device (same accumulation policy) agrees bitwise-ish
    out_full_bf16 = np.asarray(
        full_attention(qb, kb, vb, causal=True)).astype(np.float32)
    np.testing.assert_allclose(out_ring, out_full_bf16, rtol=0.02, atol=0.01)


@pytest.mark.slow  # ~30-80s of 8-way SPMD compile on the 1.5-core gate box;
# tier-1 keeps the ring==full equivalence pair + the DSL layer test (at seed this
# whole file was import-broken, so gate coverage still strictly improves)
def test_ring_attention_differentiable():
    """Gradients flow through the ring (training viability, not just
    inference)."""
    from deeplearning4j_tpu.parallel.mesh import shard_map_fn
    shard_map = shard_map_fn()
    from jax.sharding import PartitionSpec as P

    q, k, v = _qkv(T=16)
    # 4-device ring (the other tests cover the full 8): the backward of
    # the statically-unrolled ring is the suite's single most expensive
    # compile on the 2-core box — a 4-hop ring proves the same property
    # (multi-hop grad == full attention grad) at half the program size
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), (SEQ_AXIS,))
    spec = P(None, SEQ_AXIS, None, None)

    def loss_ring(q, k, v):
        f = shard_map(
            lambda q, k, v: ring_attention_sharded(q, k, v, causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return jnp.sum(jnp.square(f(q, k, v)))

    def loss_full(q, k, v):
        return jnp.sum(jnp.square(full_attention(q, k, v, causal=True)))

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-6,
                                   err_msg=f"d{name}")


def test_self_attention_layer_in_dsl():
    """SelfAttentionLayer trains end-to-end inside MultiLayerNetwork and
    honors time masks + causality."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        RnnOutputLayer,
        SelfAttentionLayer,
    )
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(5).updater("adam")
            .learning_rate(1e-2).weight_init("xavier").list()
            .layer(SelfAttentionLayer(n_out=16, n_heads=4, causal=True))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(8)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    # task: label at t = sign of x[:, 0, 0] (requires attending position 0)
    x = rng.standard_normal((32, 10, 8)).astype(np.float32)
    cls = (x[:, 0, 0] > 0).astype(int)
    y = np.zeros((32, 10, 2), np.float32)
    y[np.arange(32), :, :] = np.eye(2, dtype=np.float32)[cls][:, None, :]
    for _ in range(150):
        net.fit(x, y, batch_size=32, epochs=1, async_prefetch=False)
    out = np.asarray(net.output(x))
    acc = float(np.mean(np.argmax(out[:, -1], -1) == cls))
    assert acc > 0.9, acc

    # gradient check through the layer at f64 (the framework's own harness)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork as MLN
    from deeplearning4j_tpu.train.gradientcheck import check_gradients

    conf2 = (NeuralNetConfiguration.builder().seed(6)
             .weight_init("xavier").list()
             .layer(SelfAttentionLayer(n_out=8, n_heads=2))
             .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
             .set_input_type(InputType.recurrent(4)).build())
    xs = np.random.default_rng(2).standard_normal((3, 5, 4))
    ys = np.zeros((3, 5, 2))
    ys[..., 0] = 1.0
    assert check_gradients(MLN(conf2).init(), xs, ys)
