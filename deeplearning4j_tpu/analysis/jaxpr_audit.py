"""Jaxpr program auditor — TPU hazards caught on an abstract trace.

One `jax.make_jaxpr` of a network's train-step loss (abstract inputs, no
compile, no device) and a walk over the program catches the hazard
classes that otherwise only show up as slow steps or OOMs on real
silicon:

  JX001  float64/complex128 values — TPUs emulate f64 at 10-100x cost
  JX002  widening float casts (bf16/f16 -> f32, f32 -> f64) — each one
         is a promotion point paying bandwidth for precision
  JX003  large constants folded into the program — baked into every
         executable and re-shipped per trace (pass them as arguments)
  JX004  host callbacks inside jit — a device->host round trip per step
  JX005  params with no cotangent path to the loss — dead weights that
         still cost memory, init time and optimizer state
  JX006  non-donated step buffers on a device backend — params + updater
         state held twice across the update (peak memory doubles)

Two entry points: `audit_fn` for any jittable callable (used by tests
and ad-hoc investigation), `audit_network` for a MultiLayerNetwork /
ComputationGraph (used by `net.doctor()` and `cli doctor`). The walk
recurses into sub-jaxprs (scan/while/cond bodies), so an LSTM's scanned
cell is audited too.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax import core as jax_core

from deeplearning4j_tpu.analysis.findings import (
    ERROR,
    INFO,
    WARNING,
    Finding,
)

LARGE_CONST_BYTES = 1 << 20  # 1 MiB: bigger than any literal that belongs

_WIDE_FLOATS = ("float64", "complex128")
_FLOAT_WIDTH = {"bfloat16": 2, "float16": 2, "float32": 4, "float64": 8}
_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                   "callback", "debug_print", "host_callback")


def _iter_jaxprs(jaxpr):
    """Yield a jaxpr and every sub-jaxpr reachable through eqn params
    (scan/while/cond/pjit bodies)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            for sub in _extract_jaxprs(v):
                yield from _iter_jaxprs(sub)


def _extract_jaxprs(v):
    if isinstance(v, jax_core.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jax_core.Jaxpr):
        yield v
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _extract_jaxprs(item)


def _aval_dtype(var) -> Optional[str]:
    aval = getattr(var, "aval", None)
    dt = getattr(aval, "dtype", None)
    return None if dt is None else str(dt)


def _walk_eqns(closed: jax_core.ClosedJaxpr):
    for jaxpr in _iter_jaxprs(closed.jaxpr):
        yield from jaxpr.eqns


def audit_closed_jaxpr(
    closed: jax_core.ClosedJaxpr,
    *,
    large_const_bytes: int = LARGE_CONST_BYTES,
    what: str = "program",
) -> List[Finding]:
    """JX001-JX004 over an already-traced program."""
    findings: List[Finding] = []

    # JX001: any f64/c128 aval anywhere. Top-level invars/constvars are
    # counted once; inside sub-jaxprs only eqn OUTPUTS count (a
    # sub-jaxpr's invars alias values the enclosing level already
    # counted — tallying them again would inflate the diagnosis)
    f64_prims = {}
    for var in list(closed.jaxpr.invars) + list(closed.jaxpr.constvars):
        if _aval_dtype(var) in _WIDE_FLOATS:
            f64_prims["input/const"] = f64_prims.get("input/const", 0) + 1
    for eqn in _walk_eqns(closed):
        for var in eqn.outvars:
            if _aval_dtype(var) in _WIDE_FLOATS:
                key = eqn.primitive.name
                f64_prims[key] = f64_prims.get(key, 0) + 1
    if f64_prims:
        total = sum(f64_prims.values())
        findings.append(Finding(
            "JX001", ERROR, f"jaxpr:{what}",
            f"{total} float64/complex128 value(s) in the program "
            f"(by source: {dict(sorted(f64_prims.items()))}) — TPUs have "
            "no f64 units; this runs emulated",
            "keep x64 disabled, or cast the offending inputs/constants "
            "to f32 before the jit boundary"))

    # JX002: widening float casts (dedup by src->dst pair)
    widenings = {}
    for eqn in _walk_eqns(closed):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = _aval_dtype(eqn.invars[0]) if eqn.invars else None
        dst = _aval_dtype(eqn.outvars[0]) if eqn.outvars else None
        if (src in _FLOAT_WIDTH and dst in _FLOAT_WIDTH
                and _FLOAT_WIDTH[dst] > _FLOAT_WIDTH[src]):
            key = (src, dst)
            widenings[key] = widenings.get(key, 0) + 1
    for (src, dst), n in sorted(widenings.items()):
        sev = WARNING if dst == "float64" else INFO
        findings.append(Finding(
            "JX002", sev, f"jaxpr:{what}",
            f"{n} widening cast(s) {src} -> {dst} in the program",
            "intentional at loss/accumulation boundaries; anywhere else "
            "it silently pays f32 bandwidth for bf16 math",
            name=f"JX002:jaxpr:{what}:{src}->{dst}"))

    # JX003: big constants folded into the graph
    for i, const in enumerate(closed.consts):
        nbytes = getattr(const, "nbytes", None)
        if nbytes is None:
            try:
                nbytes = np.asarray(const).nbytes
            except Exception:
                continue
        if nbytes >= large_const_bytes:
            shape = getattr(const, "shape", ())
            findings.append(Finding(
                "JX003", WARNING, f"jaxpr:{what}",
                f"constant #{i} ({nbytes / 2**20:.1f} MiB, shape {shape}) "
                "is folded into the program — it is re-traced into every "
                "shape variant and resident in every executable",
                "pass it as a function argument (or device_put it once) "
                "instead of closing over it",
                name=f"JX003:jaxpr:{what}:const{i}"))

    # JX004: host callbacks under jit
    callbacks = {}
    for eqn in _walk_eqns(closed):
        pname = eqn.primitive.name
        if pname in _CALLBACK_PRIMS or "callback" in pname:
            callbacks[pname] = callbacks.get(pname, 0) + 1
    for pname, n in sorted(callbacks.items()):
        findings.append(Finding(
            "JX004", WARNING, f"jaxpr:{what}",
            f"{n} host callback eqn(s) [{pname}] inside the program — "
            "each forces a device->host sync per step",
            "move host work outside jit, or gate debug callbacks off the "
            "hot path",
            name=f"JX004:jaxpr:{what}:{pname}"))

    return findings


def _live_invars(jaxpr, out_slice: Optional[int] = None):
    """Conservative liveness: which invars can reach the (first
    `out_slice`) outputs. One reverse pass suffices — eqns are in
    topological order. Sub-jaxpr-calling eqns are treated atomically
    (an invar consumed by a live scan counts as live), which can only
    under-report dead params, never false-positive them."""
    outs = jaxpr.outvars if out_slice is None else jaxpr.outvars[:out_slice]
    live = {v for v in outs if isinstance(v, jax_core.Var)}
    for eqn in reversed(jaxpr.eqns):
        if any(v in live for v in eqn.outvars):
            live.update(v for v in eqn.invars
                        if isinstance(v, jax_core.Var))
    return live


def _dead_arg_findings(closed, arg_leaf_labels: Sequence[str],
                       n_score_outputs: Optional[int],
                       what: str, code_target: str) -> List[Finding]:
    live = _live_invars(closed.jaxpr, n_score_outputs)
    findings = []
    for var, label in zip(closed.jaxpr.invars, arg_leaf_labels):
        if label is None:
            continue  # not a leaf we audit (states, data, rng)
        if var not in live:
            findings.append(Finding(
                "JX005", WARNING, label,
                f"{code_target} has no path to the loss — it is "
                "initialized, stored, and optimizer-tracked but can never "
                "receive a gradient",
                "remove the dead layer/vertex, or wire it into an output",
                name=f"JX005:{label}"))
    return findings


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "idx"):
            parts.append(f"[{p.idx}]")
        elif hasattr(p, "key"):
            parts.append(f".{p.key}")
        else:
            parts.append(str(p))
    return "".join(parts)


def audit_fn(fn, *example_args,
             large_const_bytes: int = LARGE_CONST_BYTES,
             what: str = "fn") -> List[Finding]:
    """Audit any jittable callable on abstract inputs (arrays or
    jax.ShapeDtypeStruct). Dead-input analysis runs against ALL outputs."""
    closed = jax.make_jaxpr(fn)(*example_args)
    findings = audit_closed_jaxpr(
        closed, large_const_bytes=large_const_bytes, what=what)
    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(example_args)
    labels = [f"{what}:arg{_path_str(path)}"
              for path, _ in leaves_with_path]
    findings.extend(_dead_arg_findings(
        closed, labels, None, what, "input"))
    return findings


def check_donation(donate_argnums: Tuple[int, ...],
                   backend: Optional[str] = None) -> List[Finding]:
    """JX006: on device backends the train step must donate its params
    and updater-state buffers (netbase._make_step donates argnums 0 and
    2) or peak memory holds both the old and new copies."""
    backend = backend or jax.default_backend()
    if backend == "cpu":
        return []  # donation is a no-op on cpu; nothing to enforce
    missing = [i for i in (0, 2) if i not in tuple(donate_argnums)]
    if not missing:
        return []
    return [Finding(
        "JX006", WARNING, f"train_step:{backend}",
        f"train-step argnums {missing} (params/updater state) are not "
        f"donated on the {backend} backend — both old and new buffers "
        "are live across the update, doubling peak parameter memory",
        "jit the step with donate_argnums=(0, 2) as "
        "nn/netbase._make_step does")]


# -- network-level audit ------------------------------------------------------


def _sds(shape, dtype=np.float32):
    return jax.ShapeDtypeStruct(tuple(int(s) for s in shape), dtype)


def _features_sds(it, batch: int, timesteps: int):
    from deeplearning4j_tpu.nn.conf.inputs import (
        ConvolutionalFlatInput,
        ConvolutionalInput,
        FeedForwardInput,
        RecurrentInput,
    )

    if isinstance(it, ConvolutionalInput):
        return _sds((batch, it.height, it.width, it.channels))
    if isinstance(it, ConvolutionalFlatInput):
        return _sds((batch, it.arity()))
    if isinstance(it, RecurrentInput):
        return _sds((batch, it.timesteps or timesteps, it.size))
    if isinstance(it, FeedForwardInput):
        return _sds((batch, it.size))
    return None


def _labels_sds(out_type, batch: int, timesteps: int):
    from deeplearning4j_tpu.nn.conf.inputs import RecurrentInput

    if isinstance(out_type, RecurrentInput):
        return _sds((batch, out_type.timesteps or timesteps, out_type.size))
    if out_type is not None:
        return _sds((batch, out_type.arity()))
    return None


def _param_leaf_labels(params_list, layer_names,
                       skip_idx=()) -> List[Optional[str]]:
    """One label per flattened param leaf: '<layer>/<param name>'.
    Leaves of layers in `skip_idx` get label None — the dead-arg check
    skips unlabeled invars, which is how host-resident embedding tables
    (trained through the paramserver, not the device cotangent path)
    are exempted from JX005."""
    labels = []
    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(params_list)
    for path, _ in leaves_with_path:
        idx = next((p.idx for p in path if hasattr(p, "idx")), None)
        if idx is not None and idx in skip_idx:
            labels.append(None)
            continue
        key = next((p.key for p in path if hasattr(p, "key")), "?")
        layer = layer_names[idx] if idx is not None and \
            idx < len(layer_names) else f"layer[{idx}]"
        labels.append(f"param:{layer}/{key}")
    return labels


def audit_network(net, *, batch_size: int = 2, timesteps: int = 8,
                  large_const_bytes: int = LARGE_CONST_BYTES) -> List[Finding]:
    """Abstract-trace `net`'s training loss once and audit the program.

    Works for MultiLayerNetwork and ComputationGraph. Needs the conf's
    InputType(s) to shape an abstract batch; without them the audit is
    skipped with an INFO finding (shapeflow reports the same gap)."""
    from deeplearning4j_tpu.analysis import shapeflow
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration

    net._require_init()
    conf = net.conf
    rng = jax.random.PRNGKey(0)
    skip = [Finding(
        "JX000", INFO, "network",
        "no InputType on the configuration — cannot shape an abstract "
        "batch, jaxpr audit skipped",
        "set an InputType (builder .set_input_type / .set_input_types)")]

    if isinstance(conf, MultiLayerConfiguration):
        x = _features_sds(conf.input_type, batch_size, timesteps)
        out_types = shapeflow.propagate_types(conf)
        y = _labels_sds(out_types[-1] if out_types else None,
                        batch_size, timesteps)
        if x is None or y is None:
            return skip
        layer_names = [
            getattr(lc, "name", None) or f"layer[{i}]"
            for i, lc in enumerate(net._ordered_layer_confs())]

        def loss(params, states, x, y):
            return net._loss(params, states, x, y, None, None, rng,
                             training=True)[0]

        args = (net.params_list, net.state_list, x, y)
    else:
        if conf.input_types is None:
            return skip
        xs = tuple(_features_sds(t, batch_size, timesteps)
                   for t in conf.input_types)
        types = shapeflow.propagate_types(conf)
        ys = tuple(_labels_sds(types.get(name), batch_size, timesteps)
                   for name in conf.outputs)
        if any(v is None for v in xs) or any(v is None for v in ys):
            return skip
        layer_names = list(net.layer_vertex_names)

        def loss(params, states, xs, ys):
            return net._loss(params, states, xs, ys, None, None, rng,
                             training=True)[0]

        args = (net.params_list, net.state_list, xs, ys)

    closed = jax.make_jaxpr(loss)(*args)
    findings = audit_closed_jaxpr(
        closed, large_const_bytes=large_const_bytes, what="train_loss")

    # dead-weight analysis: which param leaves reach the score output
    # (`loss` returns ONLY the scalar score, so every program output is
    # score — liveness against all outputs IS the cotangent-path check)
    try:
        host_idx = frozenset(
            i for i, lc in enumerate(net._ordered_layer_confs())
            if getattr(lc, "host_resident", False))
    except Exception:
        host_idx = frozenset()
    param_labels = _param_leaf_labels(net.params_list, layer_names,
                                      skip_idx=host_idx)
    all_labels = param_labels + [None] * (
        len(closed.jaxpr.invars) - len(param_labels))
    findings.extend(_dead_arg_findings(
        closed, all_labels, None, "train_loss", "parameter"))

    # donation policy of the step this loss will be jitted into: audit
    # the value the net's step builders RECORDED (every jit site calls
    # netbase._step_donate_argnums) — if no step was built yet, calling
    # the same helper records and returns what the first build will use
    donate = getattr(net, "_donate_argnums", None)
    if donate is None:
        donate = net._step_donate_argnums()
    findings.extend(check_donation(donate))
    return findings
