"""Numeric gradient checking.

Analog of the reference's GradientCheckUtil
(gradientcheck/GradientCheckUtil.java, 515 LoC): central-difference
numerical gradients vs the analytic ones, per parameter, in f64. The
reference enforces global double precision and a whitelist of smooth
activations (:48-91); here f64 runs on the CPU backend via the enable_x64
context (TPUs don't do f64 — the check is a host-side correctness tool,
exactly like the reference runs it on the CPU backend).

Where the reference compares hand-written backprop against finite
differences, here the analytic side is jax.grad — so this harness validates
layer forward implementations + loss wiring (a wrong forward still yields a
consistent-but-wrong gradient pair only if the forward itself is what we
meant; any non-differentiable kink or masking bug shows up as a mismatch).
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")


def enable_x64():
    """f64 context manager across jax versions: `jax.enable_x64` was
    removed in favor of `jax.experimental.enable_x64` in the jax this
    image ships — the check is useless without it (f64 is the whole
    point, see module docstring)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64()
    from jax.experimental import enable_x64 as _e64

    return _e64()


def numeric_gradient(f: Callable, flat: np.ndarray, epsilon: float = 1e-6,
                     indices=None, chunk: int = 128) -> np.ndarray:
    """Central differences: (f(x+eps e_i) - f(x-eps e_i)) / (2 eps).

    Vectorized: perturbation rows are evaluated through jit(vmap(f)) in
    chunks — the whole sweep is a handful of compiled batched evaluations
    instead of 2N eager forward passes."""
    flat = np.asarray(flat, dtype=np.float64)
    idx = np.fromiter(
        (range(flat.size) if indices is None else indices), dtype=np.int64
    )
    fv = jax.jit(jax.vmap(f))
    out = np.zeros(flat.size, dtype=np.float64)
    for start in range(0, idx.size, chunk):
        sel = idx[start : start + chunk]
        base = np.broadcast_to(flat, (sel.size, flat.size)).copy()
        plus = base.copy()
        plus[np.arange(sel.size), sel] += epsilon
        minus = base
        minus[np.arange(sel.size), sel] -= epsilon
        fp = np.asarray(fv(jnp.asarray(plus)))
        fm = np.asarray(fv(jnp.asarray(minus)))
        out[sel] = (fp - fm) / (2.0 * epsilon)
    return out


def check_gradients_fn(
    loss_of_flat: Callable,
    flat_params: np.ndarray,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-5,
    min_abs_error: float = 1e-8,
    max_checks: Optional[int] = None,
    seed: int = 0,
    verbose: bool = False,
) -> bool:
    """Check d(loss)/d(flat) analytic vs numeric. `loss_of_flat` must be a
    pure function of a flat f64 vector. Mirrors the reference's pass
    criterion: relative error (|a-n| / (|a|+|n|)) <= max_rel_error, with an
    absolute-error floor for near-zero gradients
    (GradientCheckUtil.java:161-180)."""
    with enable_x64():
        flat64 = jnp.asarray(np.asarray(flat_params, dtype=np.float64))
        analytic = np.asarray(jax.grad(lambda p: loss_of_flat(p))(flat64))

        n = flat64.size
        if max_checks is not None and max_checks < n:
            rng = np.random.default_rng(seed)
            indices = rng.choice(n, size=max_checks, replace=False)
        else:
            indices = range(n)

        numeric = numeric_gradient(loss_of_flat, np.asarray(flat64), epsilon, indices)

        fails = 0
        for i in indices:
            a, m = analytic[i], numeric[i]
            denom = abs(a) + abs(m)
            rel = abs(a - m) / denom if denom > 0 else 0.0
            if rel > max_rel_error and abs(a - m) > min_abs_error:
                fails += 1
                if verbose:
                    logger.info("param %d: analytic=%.8g numeric=%.8g "
                                "rel=%.3g", i, a, m, rel)
        if verbose:
            logger.info("gradient check: %d/%d ok",
                        len(list(indices)) - fails, len(list(indices)))
        return fails == 0


def check_gradients(net, x, y, features_mask=None, labels_mask=None,
                    epsilon: float = 1e-6, max_rel_error: float = 1e-5,
                    min_abs_error: float = 1e-8, max_checks: Optional[int] = None,
                    verbose: bool = False) -> bool:
    """Gradient-check a MultiLayerNetwork's full loss (data term + l1/l2)
    against its flattened parameter vector (reference:
    GradientCheckUtil.checkGradients(MultiLayerNetwork, ...))."""
    from deeplearning4j_tpu.common.dtypes import PrecisionPolicy
    from deeplearning4j_tpu.nn.params import flat_to_params

    net._require_init()
    # the network's normal policy would downcast to its compute dtype; the
    # check must run end-to-end f64 (reference: GradientCheckUtil enforces
    # global double precision, :77-91)
    saved_policy = net.policy
    net.policy = PrecisionPolicy(
        param_dtype=jnp.float64, compute_dtype=jnp.float64, output_dtype=jnp.float64
    )
    try:
        return _check_gradients_x64(net, x, y, features_mask, labels_mask,
                                    epsilon, max_rel_error, min_abs_error,
                                    max_checks, verbose)
    finally:
        net.policy = saved_policy


def check_gradients_graph(net, xs, ys, features_masks=None, labels_masks=None,
                          epsilon: float = 1e-6, max_rel_error: float = 1e-5,
                          min_abs_error: float = 1e-8,
                          max_checks: Optional[int] = None,
                          verbose: bool = False) -> bool:
    """Gradient-check a ComputationGraph (reference:
    GradientCheckUtil.checkGradients(ComputationGraph, ...) and the
    GradientCheckTestsComputationGraph suite). xs/ys are lists aligned with
    the graph's inputs/outputs."""
    from deeplearning4j_tpu.common.dtypes import PrecisionPolicy
    from deeplearning4j_tpu.nn.params import flat_to_params, params_to_flat

    net._require_init()
    saved_policy = net.policy
    net.policy = PrecisionPolicy(
        param_dtype=jnp.float64, compute_dtype=jnp.float64, output_dtype=jnp.float64
    )
    try:
        with enable_x64():
            confs = net._ordered_layer_confs()
            params64 = jax.tree_util.tree_map(
                lambda a: jnp.asarray(np.asarray(a, dtype=np.float64)),
                net.params_list,
            )
            states64 = [
                None if s is None else {k: jnp.asarray(np.asarray(v, np.float64))
                                        for k, v in s.items()}
                for s in net.state_list
            ]
            xs64 = [jnp.asarray(np.asarray(x, np.float64)) for x in xs]
            ys64 = [jnp.asarray(np.asarray(y, np.float64)) for y in ys]
            as64 = lambda ms: None if ms is None else [
                None if m is None else jnp.asarray(np.asarray(m, np.float64))
                for m in ms
            ]
            fms, lms = as64(features_masks), as64(labels_masks)

            def loss_of_flat(flat):
                plist = flat_to_params(confs, params64, flat)
                s, _ = net._loss(plist, states64, xs64, ys64, fms, lms,
                                 rng=None, training=True)
                return s

            flat0 = params_to_flat(confs, params64)
            return check_gradients_fn(
                loss_of_flat, np.asarray(flat0), epsilon=epsilon,
                max_rel_error=max_rel_error, min_abs_error=min_abs_error,
                max_checks=max_checks, verbose=verbose,
            )
    finally:
        net.policy = saved_policy


def _check_gradients_x64(net, x, y, features_mask, labels_mask, epsilon,
                         max_rel_error, min_abs_error, max_checks, verbose):
    from deeplearning4j_tpu.nn.params import flat_to_params

    with enable_x64():
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a, dtype=np.float64)), net.params_list
        )
        states64 = [
            None if s is None else {k: jnp.asarray(np.asarray(v, np.float64))
                                    for k, v in s.items()}
            for s in net.state_list
        ]
        x64 = jnp.asarray(np.asarray(x, np.float64))
        y64 = jnp.asarray(np.asarray(y, np.float64))
        fm = None if features_mask is None else jnp.asarray(np.asarray(features_mask, np.float64))
        lm = None if labels_mask is None else jnp.asarray(np.asarray(labels_mask, np.float64))

        def loss_of_flat(flat):
            plist = flat_to_params(net.layer_confs, params64, flat)
            # training=True exercises the train-path math but with no rng =>
            # deterministic (dropout inactive), matching the reference's
            # gradient-check preconditions (no dropout, smooth activations)
            s, _ = net._loss(plist, states64, x64, y64, fm, lm, rng=None,
                             training=True)
            return s

        from deeplearning4j_tpu.nn.params import params_to_flat

        flat0 = params_to_flat(net.layer_confs, params64)
        return check_gradients_fn(
            loss_of_flat, np.asarray(flat0), epsilon=epsilon,
            max_rel_error=max_rel_error, min_abs_error=min_abs_error,
            max_checks=max_checks, verbose=verbose,
        )
