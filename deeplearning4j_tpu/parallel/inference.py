"""ParallelInference — multi-request serving over the device mesh.

Reference: deeplearning4j-scaleout/.../parallelism/ParallelInference.java
(:33-126) — a pool of model replicas fed from a queue, with
InferenceMode.SEQUENTIAL (one request per replica call) vs BATCHED (dynamic
batching via BatchedInferenceObservable, inference/observers/).

TPU-native design: one set of replicated parameters on the mesh; the
"replica pool" is replaced by batch sharding — a dynamically-batched
request group is sharded across the data axis and executed once. Dynamic
batching (the BATCHED mode) is the part that carries over unchanged: a
collector thread drains the request queue, concatenates up to
`max_batch_size` examples, runs the jitted forward, and scatters results
back to the waiting callers.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Optional

import jax
import numpy as np

from deeplearning4j_tpu.parallel.mesh import (
    batch_sharded,
    data_parallel_mesh,
    data_shards,
    pad_wrap,
    replicated,
)


class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class ParallelInference:
    def __init__(
        self,
        model,
        mesh=None,
        inference_mode: str = InferenceMode.BATCHED,
        max_batch_size: int = 64,
        batch_timeout_ms: float = 2.0,
    ):
        self.model = model
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.mode = inference_mode
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout = batch_timeout_ms / 1e3
        self.n_shards = data_shards(self.mesh)
        model._require_init()
        rep = replicated(self.mesh)
        model.params_list = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, rep), model.params_list
        )
        self._q: "queue.Queue" = queue.Queue()
        self._expected_shape = None  # set by the first request
        self._shutdown = False
        self._worker: Optional[threading.Thread] = None
        if self.mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(target=self._collector, daemon=True)
            self._worker.start()

    # -- public --------------------------------------------------------------

    def output(self, x):
        """Thread-safe inference. In BATCHED mode the call may be fused
        with concurrent callers' batches (reference:
        BatchedInferenceObservable)."""
        if self._shutdown:
            raise RuntimeError("ParallelInference has been shut down")
        xx = np.asarray(x)
        if self._expected_shape is None:
            self._expected_shape = xx.shape[1:]
        elif xx.shape[1:] != self._expected_shape:
            # validate HERE, not deep inside the collector where a bad
            # request would fail the whole fused group
            raise ValueError(
                f"request feature shape {xx.shape[1:]} does not match this "
                f"ParallelInference's {self._expected_shape}"
            )
        if self.mode == InferenceMode.SEQUENTIAL:
            return self._run(xx)
        if xx.shape[0] > self.max_batch_size:
            # oversized request: run it alone instead of overshooting a
            # fused group arbitrarily
            return self._run(xx)
        fut: Future = Future()
        self._q.put((xx, fut))
        return fut.result()

    def shutdown(self):
        self._shutdown = True
        if self._worker is not None:
            self._q.put(None)
            self._worker.join(timeout=5)
            # requests that raced the sentinel would otherwise hang their
            # callers forever — fail them explicitly
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                if item is not None and not item[1].done():
                    item[1].set_exception(
                        RuntimeError("ParallelInference shut down")
                    )

    # -- internals -----------------------------------------------------------

    def _run(self, xx: np.ndarray):
        """Sharded forward; non-divisible batches are padded by wrapping
        and sliced — sharded execution with a stable trace shape instead
        of a replicated fallback."""
        n = xx.shape[0]
        pad = (-n) % self.n_shards
        if pad:
            xx = pad_wrap(xx, self.n_shards)
        out = self.model.output(jax.device_put(xx, batch_sharded(self.mesh)))
        return out[:n] if pad else out

    def _collector(self):
        while not self._shutdown:
            item = self._q.get()
            if item is None:
                return
            group = [item]
            count = item[0].shape[0]
            # drain more requests until the batch limit or a short timeout
            while count < self.max_batch_size:
                try:
                    nxt = self._q.get(timeout=self.batch_timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    self._shutdown = True
                    break
                group.append(nxt)
                count += nxt[0].shape[0]
            try:
                batch = np.concatenate([g[0] for g in group], axis=0)
                out = np.asarray(self._run(batch))
                off = 0
                for xx, fut in group:
                    n = xx.shape[0]
                    fut.set_result(out[off : off + n])
                    off += n
            except BaseException as e:  # propagate to all waiting callers
                for _, fut in group:
                    if not fut.done():
                        fut.set_exception(e)
