"""Weight initialization tests (reference: WeightInitUtil semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.weights import WeightInit, init_weights

SCHEMES = [
    WeightInit.ZERO, WeightInit.ONES, WeightInit.UNIFORM, WeightInit.XAVIER,
    WeightInit.XAVIER_UNIFORM, WeightInit.XAVIER_FAN_IN,
    WeightInit.XAVIER_LEGACY, WeightInit.RELU, WeightInit.RELU_UNIFORM,
    WeightInit.SIGMOID_UNIFORM, WeightInit.LECUN_NORMAL,
    WeightInit.LECUN_UNIFORM, WeightInit.NORMAL,
]


@pytest.mark.parametrize("scheme", SCHEMES)
def test_shape_and_determinism(scheme, rng_key):
    w1 = init_weights(rng_key, (64, 32), 64, 32, scheme)
    w2 = init_weights(rng_key, (64, 32), 64, 32, scheme)
    assert w1.shape == (64, 32)
    np.testing.assert_array_equal(w1, w2)  # same key -> same draw


def test_xavier_statistics():
    key = jax.random.PRNGKey(7)
    fan_in, fan_out = 400, 300
    w = init_weights(key, (fan_in, fan_out), fan_in, fan_out, WeightInit.XAVIER)
    expected_std = (2.0 / (fan_in + fan_out)) ** 0.5
    assert abs(float(jnp.std(w)) - expected_std) / expected_std < 0.05
    assert abs(float(jnp.mean(w))) < 0.001


def test_relu_statistics():
    key = jax.random.PRNGKey(8)
    w = init_weights(key, (500, 500), 500, 500, WeightInit.RELU)
    expected_std = (2.0 / 500) ** 0.5
    assert abs(float(jnp.std(w)) - expected_std) / expected_std < 0.05


def test_uniform_bounds():
    key = jax.random.PRNGKey(9)
    w = init_weights(key, (100, 100), 100, 100, WeightInit.UNIFORM)
    bound = 1.0 / 10.0
    assert float(jnp.max(jnp.abs(w))) <= bound + 1e-7


def test_distribution_init():
    key = jax.random.PRNGKey(10)
    w = init_weights(
        key, (200, 200), 200, 200, WeightInit.DISTRIBUTION,
        distribution={"type": "normal", "mean": 3.0, "std": 0.5},
    )
    assert abs(float(jnp.mean(w)) - 3.0) < 0.05
    u = init_weights(
        key, (50, 50), 50, 50, WeightInit.DISTRIBUTION,
        distribution={"type": "uniform", "lower": 0.0, "upper": 2.0},
    )
    assert float(jnp.min(u)) >= 0.0 and float(jnp.max(u)) <= 2.0


def test_identity_init():
    w = init_weights(jax.random.PRNGKey(0), (4, 4), 4, 4, WeightInit.IDENTITY)
    np.testing.assert_array_equal(w, jnp.eye(4))


def test_different_keys_differ():
    w1 = init_weights(jax.random.PRNGKey(1), (10, 10), 10, 10, WeightInit.XAVIER)
    w2 = init_weights(jax.random.PRNGKey(2), (10, 10), 10, 10, WeightInit.XAVIER)
    assert not np.allclose(np.asarray(w1), np.asarray(w2))
