"""Loss function tests (reference: LossFunctions / ILossFunction impls,
exercised by LossFunctionGradientCheck.java)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.losses import LossFunction, loss_value

ALL = [
    "mse", "l1", "l2", "xent", "mcxent", "squared_loss",
    "negativeloglikelihood", "kl_divergence", "cosine_proximity", "hinge",
    "squared_hinge", "poisson", "mean_absolute_error",
    "mean_absolute_percentage_error", "mean_squared_logarithmic_error",
    "reconstruction_crossentropy", "rmse_xent",
]


def _probs(key, shape):
    x = jax.random.uniform(key, shape, minval=0.05, maxval=1.0)
    return x / jnp.sum(x, axis=-1, keepdims=True)


@pytest.mark.parametrize("name", ALL)
def test_shape_and_finite(name):
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    preout = jax.random.normal(k1, (4, 3))
    if name in ("hinge", "squared_hinge"):
        labels = jnp.sign(jax.random.normal(k2, (4, 3)))
        act = "identity"
    elif name in ("xent", "kl_divergence", "reconstruction_crossentropy"):
        labels = _probs(k2, (4, 3))
        act = "sigmoid"
    elif name in ("mcxent", "negativeloglikelihood"):
        labels = jax.nn.one_hot(jnp.array([0, 1, 2, 1]), 3)
        act = "softmax"
    elif name == "poisson":
        labels = jnp.abs(jax.random.normal(k2, (4, 3)))
        act = "softplus"
    elif name == "mean_absolute_percentage_error":
        labels = 1.0 + jnp.abs(jax.random.normal(k2, (4, 3)))
        act = "identity"
    elif name == "mean_squared_logarithmic_error":
        labels = jnp.abs(jax.random.normal(k2, (4, 3)))
        act = "softplus"
    else:
        labels = jax.random.normal(k2, (4, 3))
        act = "identity"
    v = loss_value(name, labels, preout, act)
    assert v.shape == (4,)
    assert bool(jnp.all(jnp.isfinite(v)))
    # loss must be differentiable end-to-end
    g = jax.grad(lambda p: jnp.mean(loss_value(name, labels, p, act)))(preout)
    assert g.shape == preout.shape
    assert bool(jnp.all(jnp.isfinite(g)))


def test_mse_known_value():
    labels = jnp.array([[1.0, 2.0]])
    preout = jnp.array([[0.0, 0.0]])
    v = loss_value("mse", labels, preout, "identity")
    np.testing.assert_allclose(v, [(1.0 + 4.0) / 2.0])
    # l2 = SSE without the 1/n
    v2 = loss_value("l2", labels, preout, "identity")
    np.testing.assert_allclose(v2, [5.0])


def test_mcxent_matches_manual_softmax_ce():
    key = jax.random.PRNGKey(3)
    logits = jax.random.normal(key, (6, 5))
    labels = jax.nn.one_hot(jnp.arange(6) % 5, 5)
    v = loss_value("mcxent", labels, logits, "softmax")
    manual = -jnp.sum(labels * jnp.log(jax.nn.softmax(logits, -1)), axis=-1)
    np.testing.assert_allclose(v, manual, rtol=1e-4, atol=1e-5)


def test_mcxent_stable_at_extreme_logits():
    logits = jnp.array([[1000.0, -1000.0, 0.0]])
    labels = jnp.array([[0.0, 1.0, 0.0]])
    v = loss_value("mcxent", labels, logits, "softmax")
    assert bool(jnp.isfinite(v[0]))
    assert float(v[0]) > 100  # huge but finite loss


def test_xent_stable_from_logits():
    logits = jnp.array([[800.0, -800.0]])
    labels = jnp.array([[0.0, 1.0]])
    v = loss_value("xent", labels, logits, "sigmoid")
    assert bool(jnp.isfinite(v[0]))


def test_masking_zeroes_out_elements():
    labels = jnp.ones((2, 4))
    preout = jnp.zeros((2, 4))
    mask = jnp.array([[1.0, 1.0, 0.0, 0.0], [1.0, 1.0, 1.0, 1.0]])
    v = loss_value("l2", labels, preout, "identity", mask)
    np.testing.assert_allclose(v, [2.0, 4.0])


def test_cosine_proximity():
    a = jnp.array([[1.0, 0.0]])
    v = loss_value("cosine_proximity", a, a, "identity")
    np.testing.assert_allclose(v, [-1.0], atol=1e-6)


def test_time_series_loss_reduces_over_time():
    # [batch, time, features] per-example score sums over time+features
    labels = jnp.ones((2, 3, 4))
    preout = jnp.zeros((2, 3, 4))
    v = loss_value("l2", labels, preout, "identity")
    np.testing.assert_allclose(v, [12.0, 12.0])


def test_enum_names_resolve():
    for name in vars(LossFunction):
        if not name.startswith("_"):
            loss_value(
                getattr(LossFunction, name),
                jnp.ones((2, 2)) * 0.5,
                jnp.zeros((2, 2)),
                "sigmoid",
            )
