"""Preemption-aware checkpointing.

Reference baseline: ModelSerializer zips + early-stopping savers, all
manual — SURVEY §5 calls elastic/preemption handling "absent...
greenfield for the TPU build". TPU-idiomatic answer: periodic
checkpointing as a LISTENER on the existing SPI plus a preemption signal
hook, because TPU pools reclaim VMs with a SIGTERM grace window; a run
that saves on SIGTERM and resumes from the newest checkpoint loses at
most one save interval.

    listener = CheckpointListener("ckpts/", every_n_iterations=500,
                                  keep_last=3, save_on_preemption=True,
                                  async_save=True)
    net.set_listeners(listener)
    ...
    net2, meta = CheckpointListener.restore_latest("ckpts/")
    # or, continuing an existing object with mid-epoch replay:
    net.fit(iterator, epochs=E, resume_from="ckpts/")

`async_save=True` splits a save into the two costs
utils.model_serializer.ModelSnapshot separates: the fit thread only
CAPTURES (reference grabs — the blocking `snapshot` phase of the
`checkpoint_save_seconds{phase=...}` histogram) and a `dl4j-ckpt-writer`
daemon does the serialize/compress/rename (`write` phase), so a save no
longer stalls the step loop. Every checkpoint also carries the net's
TrainState (iteration/epoch + iterator position) for byte-identical
mid-epoch resume; see nn/netbase.py.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from typing import List, Optional, Tuple

from deeplearning4j_tpu.train.listeners import IterationListener
from deeplearning4j_tpu.utils import blackbox as _blackbox
from deeplearning4j_tpu.utils import faultpoints as _faults
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import sigchain as _sigchain
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.utils.concurrency import QueueAborted, get_abortable

logger = logging.getLogger("deeplearning4j_tpu")

_LATEST = "latest.json"


def scan_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """(iteration, filename) for every complete checkpoint zip in
    `directory`, ascending by iteration — the metadata-independent view
    (in-flight `*.tmp` writes never appear: the atomic rename publishes
    a zip only once it is whole)."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for f in names:
        if f.startswith("checkpoint_iter") and f.endswith(".zip"):
            try:
                out.append((int(f[len("checkpoint_iter"):-len(".zip")]), f))
            except ValueError:
                continue
    return sorted(out)


def checkpoint_candidates(directory: str):
    """Yield (path, meta) newest-first: the `latest.json` target leads
    when it exists, then the scanned zips (deduped, each zip's own meta)
    — the metadata file is an accelerator, never a single point of
    failure. Zips whose embedded meta cannot be read are still yielded,
    flagged `"unreadable": True`, so the VERIFIED consumers can reject
    them loudly instead of silently stepping past corruption."""
    seen = set()
    meta_path = os.path.join(directory, _LATEST)
    try:
        with open(meta_path) as f:
            meta = json.load(f)
        path = os.path.join(directory, meta["file"])
        if os.path.exists(path):
            seen.add(meta["file"])
            yield path, dict(meta)
        else:
            logger.warning("checkpoint metadata points at missing %r; "
                           "falling back to a directory scan",
                           meta["file"])
    except FileNotFoundError:
        pass
    except (OSError, ValueError, KeyError, json.JSONDecodeError):
        logger.warning("torn/unreadable %s in %r; falling back to a "
                       "directory scan", _LATEST, directory)
    import zipfile

    for it, name in reversed(scan_checkpoints(directory)):
        if name in seen:
            continue
        path = os.path.join(directory, name)
        meta = {
            "iteration": it,
            "epoch": 0,
            "reason": "scan",  # recovered without metadata
            "file": name,
        }
        try:
            meta["ts"] = os.path.getmtime(path)
            with zipfile.ZipFile(path) as zf:
                zmeta = json.loads(zf.read("meta.json").decode("utf-8"))
            meta["iteration"] = int(zmeta.get("iteration", it))
            meta["epoch"] = int(zmeta.get("epoch", 0))
        except Exception:
            meta["unreadable"] = True
        yield path, meta


def latest_checkpoint(directory: str) -> Optional[Tuple[str, dict]]:
    """(path, meta) of the newest READABLE checkpoint, or None when the
    directory holds none. Metadata-level only (the original PR 7
    contract — unreadable zips are skipped with a warning); the restore
    paths use `verified_checkpoints` instead, which additionally checks
    each candidate's SHA-256 digest manifest."""
    for path, meta in checkpoint_candidates(directory):
        if meta.get("unreadable"):
            logger.warning("skipping unreadable checkpoint %r",
                           meta.get("file"))
            continue
        return path, meta
    return None


class NoUsableCheckpointError(RuntimeError):
    """Checkpoints EXISTED in the directory but every one was rejected
    (digest mismatch, failed load, tainted/non-finite). Deliberately
    distinct from the empty-directory fresh start: a preemptible pod
    restarting over a rotted sole checkpoint must stop for an operator,
    not silently restart from iteration 0 and then GC the evidence."""


def note_bad_checkpoint(path: str, why: str) -> None:
    """Account one rejected checkpoint — loudly: a counter (the
    fallback is observable, not silent), a flight-recorder event
    (`cli blackbox` shows the corruption in the incident timeline), and
    an ERROR log naming the file and the reason."""
    _metrics.get_registry().counter(
        "checkpoint_integrity_failures_total",
        "checkpoints rejected at restore time (digest mismatch, "
        "unreadable entries, failed load, or non-finite params) — each "
        "rejection fell back to the previous good checkpoint").labels() \
        .inc()
    _blackbox.get_recorder().record_event(
        "checkpoint_corrupt", checkpoint=str(path), why=str(why)[:300])
    logger.error("checkpoint %s rejected: %s — falling back to the "
                 "previous good checkpoint", path, why)


def verified_checkpoints(directory: str):
    """Yield (path, meta) newest-first, SKIPPING — loudly, counted via
    `note_bad_checkpoint` — every candidate whose per-entry SHA-256
    manifest fails verification (bit flip, torn entry, missing entry).
    Pre-digest legacy checkpoints carry no manifest and pass through
    unverified (`verify_checkpoint` reports them `legacy`), so old
    checkpoint directories keep restoring."""
    from deeplearning4j_tpu.utils.model_serializer import verify_checkpoint

    for path, meta in checkpoint_candidates(directory):
        v = verify_checkpoint(path)
        if not v["ok"]:
            bad = [f"{name}:{entry['status']}"
                   for name, entry in v["entries"].items()
                   if entry["status"] != "ok"]
            note_bad_checkpoint(
                path, "integrity verification failed ("
                      + (", ".join(bad) or v.get("error", "unknown"))
                      + ")")
            continue
        yield path, meta


def describe_latest(directory: str) -> Optional[dict]:
    """Operator view of the newest checkpoint (cli resume): meta plus
    age and absolute path. None when the directory holds none."""
    found = latest_checkpoint(directory)
    if found is None:
        return None
    path, meta = found
    out = dict(meta)
    out["path"] = path
    ts = meta.get("ts")
    out["age_seconds"] = None if ts is None else max(0.0, time.time() - ts)
    from deeplearning4j_tpu.utils.model_serializer import read_train_state

    try:
        out["train_state"] = read_train_state(path)
    except Exception:
        out["train_state"] = None
    return out


def corrupt_zip_entry(path: str, entry: Optional[str] = None) -> str:
    """Flip one byte inside a zip entry's stored data — the `corrupt`
    fault kind's damage, also used directly by the corruption-fallback
    tests. Targets the largest entry by default (the parameter payload:
    the flip that would silently train a wrong model if restored
    unverified). Returns the damaged entry's name."""
    import zipfile

    with zipfile.ZipFile(path, "r") as zf:
        infos = [i for i in zf.infolist()
                 if entry is None or i.filename == entry]
        if not infos:
            raise ValueError(f"no such entry {entry!r} in {path}")
        info = max(infos, key=lambda i: i.compress_size)
    with open(path, "r+b") as f:
        # the local file header's name/extra lengths may differ from the
        # central directory's — read them from the header itself
        f.seek(info.header_offset + 26)
        nlen = int.from_bytes(f.read(2), "little")
        elen = int.from_bytes(f.read(2), "little")
        data_off = info.header_offset + 30 + nlen + elen
        pos = data_off + min(8, max(0, info.compress_size - 1))
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0x55]))
    logger.warning("corrupted zip entry %r in %s (injected byte flip)",
                   info.filename, path)
    return info.filename


class CheckpointListener(IterationListener):
    """Periodic + preemption-triggered model saves with retention.

    every_n_iterations / every_n_epochs / every_n_seconds: any
    combination; a save fires when any schedule is due.
    keep_last: retain the newest N checkpoints (0 = keep all).
    save_on_preemption: register a SIGTERM action (utils/sigchain, at
    PRIORITY_SAVE — always before the flight recorder's crash dump) that
    saves synchronously before the process dies (the TPU/GCE preemption
    contract).
    async_save: the fit thread only snapshots (device references); a
    `dl4j-ckpt-writer` daemon serializes and renames in the background.
    At most `queue_depth` snapshots wait; when the writer falls behind,
    the OLDEST queued snapshot is coalesced away (counted) — the newest
    state always wins."""

    def __init__(self, directory: str, *,
                 every_n_iterations: Optional[int] = None,
                 every_n_epochs: Optional[int] = 1,
                 every_n_seconds: Optional[float] = None,
                 keep_last: int = 3,
                 save_updater: bool = True,
                 save_on_preemption: bool = False,
                 async_save: bool = False,
                 queue_depth: int = 2):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.every_iter = every_n_iterations
        self.every_epoch = every_n_epochs
        self.every_seconds = every_n_seconds
        self.keep_last = int(keep_last)
        self.save_updater = save_updater
        self.async_save = bool(async_save)
        self.queue_depth = max(1, int(queue_depth))
        self._last_time = time.monotonic()
        self._model = None
        self._lock = threading.Lock()    # one save() call at a time
        self._io_lock = threading.Lock()  # serializes zip/meta file IO
        self._writer_q: Optional["queue.Queue"] = None
        self._writer_t: Optional[threading.Thread] = None
        self._writer_stop = threading.Event()
        self._writer_hb: Optional[_health.Heartbeat] = None
        self._closed = False
        reg = _metrics.get_registry()
        self._m_saves = reg.counter(
            "checkpoint_saves_total", "checkpoints written", ("reason",))
        self._m_phase = reg.histogram(
            "checkpoint_save_seconds",
            "checkpoint save duration by phase: `snapshot` is the "
            "fit-thread blocking part (capture + enqueue under "
            "async_save), `write` the serialize + atomic rename",
            ("phase",))
        self._m_coalesced = reg.counter(
            "checkpoint_coalesced_total",
            "queued snapshots displaced by a newer one before the "
            "async writer got to them").labels()
        self._m_failures = reg.counter(
            "checkpoint_save_failures_total",
            "checkpoint writes that raised (save skipped, training "
            "unaffected)").labels()
        if save_on_preemption:
            self._install_preemption_hook()

    # -- listener hooks -------------------------------------------------------

    def iteration_done(self, model, iteration, info):
        self._model = model
        due = (self.every_iter is not None and iteration > 0
               and iteration % self.every_iter == 0)
        if (not due and self.every_seconds is not None
                and time.monotonic() - self._last_time >= self.every_seconds):
            due = True
        if due:
            self.save(model, reason="schedule")

    def on_epoch_end(self, model, epoch):
        self._model = model
        if self.every_epoch is not None and (epoch + 1) % self.every_epoch == 0:
            self.save(model, reason="epoch")

    def on_fit_end(self, model):
        # a fit that returns (or raises) leaves no checkpoint still in
        # flight: the resume contract starts where the fit ended
        self.flush()

    # -- saving ---------------------------------------------------------------

    def save(self, model, reason: str = "manual",
             blocking: bool = True) -> Optional[str]:
        """blocking=False (the SIGTERM handler) skips instead of waiting:
        if a save is already mid-capture on this thread, re-entering
        would corrupt it — and its result is at most one interval stale.
        Returns the checkpoint path (under async_save: the path the
        background writer will publish)."""
        from deeplearning4j_tpu.utils.model_serializer import ModelSnapshot

        if not self._lock.acquire(blocking=blocking):
            logger.warning("checkpoint save already in flight; skipping "
                           "(%s)", reason)
            return None
        try:
            t0 = time.perf_counter()
            ts_fn = getattr(model, "train_state", None)
            train_state = ts_fn() if callable(ts_fn) else None
            with _tracing.span("checkpoint/snapshot", reason=reason):
                snap = ModelSnapshot.capture(model, self.save_updater,
                                             train_state=train_state)
            name = f"checkpoint_iter{snap.iteration:09d}.zip"
            path = os.path.join(self.dir, name)
            # preemption writes synchronously even under async_save: the
            # process is dying, there is no background left to defer to.
            # Same after close(): its contract is "saves synchronously
            # afterwards" — re-entering the async path would respawn a
            # writer thread + heartbeat nothing will ever retire
            if self.async_save and not self._closed and reason != "preemption":
                self._ensure_writer()
                self._enqueue(snap, reason)
                self._m_phase.labels("snapshot").observe(
                    time.perf_counter() - t0)
                self._last_time = time.monotonic()
                return path
            self._m_phase.labels("snapshot").observe(
                time.perf_counter() - t0)
            out = self._write_snapshot(snap, reason)
            self._last_time = time.monotonic()
            return out
        finally:
            self._lock.release()

    def _enqueue(self, snap, reason: str):
        q = self._writer_q
        while True:
            try:
                q.put_nowait((snap, reason))
                return
            except queue.Full:
                # the writer fell behind: displace the OLDEST pending
                # snapshot (the newest state always wins) and say so
                try:
                    q.get_nowait()
                    q.task_done()
                    self._m_coalesced.inc()
                    logger.warning(
                        "checkpoint writer behind; coalesced an older "
                        "queued snapshot (%s)", reason)
                except queue.Empty:
                    continue

    def _write_snapshot(self, snap, reason: str) -> Optional[str]:
        """Serialize one captured snapshot to its zip + metadata —
        shared by the synchronous path and the background writer (which
        is why the file IO has its own lock: a preemption save must be
        able to run while the writer owns an older snapshot)."""
        name = f"checkpoint_iter{snap.iteration:09d}.zip"
        path = os.path.join(self.dir, name)
        tmp = f"{path}.{os.getpid()}.{reason}.tmp"  # unique per writer
        t0 = time.perf_counter()
        with self._io_lock:
            with _tracing.span("checkpoint/write", reason=reason):
                # chaos hook: an `error` fault before the write is a
                # full-disk / dead-volume save failure; landing before
                # snap.write means no tmp file is ever created, and one
                # BETWEEN write and replace would be the torn-file case
                # the atomic rename makes survivable (the .tmp is
                # swept by _gc, latest.json still names the previous
                # good checkpoint). A `corrupt` fault byte-flips an
                # entry of the zip that WAS written — the silent
                # bit-rot case the digest manifest + restore fallback
                # exist for, made deterministically replayable.
                injected = _faults.fault_point("ckpt_write", reason=reason)
                snap.write(tmp)
                if injected == "corrupt":
                    corrupt_zip_entry(tmp)
                os.replace(tmp, path)  # atomic: never a torn checkpoint
            meta = {
                "iteration": snap.iteration,
                "epoch": snap.epoch,
                "ts": time.time(),
                "reason": reason,
                "file": name,
            }
            self._write_latest(meta)
            self._gc()
        self._m_saves.labels(reason).inc()
        self._m_phase.labels("write").observe(time.perf_counter() - t0)
        _blackbox.get_recorder().record_event(
            "checkpoint_saved", iteration=snap.iteration, reason=reason,
            file=name)
        logger.info("checkpoint saved: %s (%s)", path, reason)
        return path

    def _write_latest(self, meta: dict):
        """Publish `latest.json` the same way the zip is published: tmp +
        `os.replace`, so a crash mid-write can never leave torn JSON
        behind (and restore_latest scans the zips if it somehow does).
        Monotonic: an async writer finishing an OLDER snapshot after a
        preemption save must not roll the pointer back."""
        path = os.path.join(self.dir, _LATEST)
        try:
            with open(path) as f:
                cur = json.load(f)
            if int(cur.get("iteration", -1)) > int(meta["iteration"]):
                return
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            pass
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    # -- the background writer ------------------------------------------------

    def _ensure_writer(self):
        if self._writer_t is not None and self._writer_t.is_alive():
            return
        self._writer_stop = threading.Event()
        self._writer_q = queue.Queue(maxsize=self.queue_depth)
        # heartbeat-registered with the watchdog: a writer wedged inside
        # a device pull or filesystem stall flips
        # component_health{component=ckpt_writer} instead of silently
        # letting checkpoints go stale
        self._writer_hb = _health.get_health().register(
            "ckpt_writer", stall_after=300.0)
        self._writer_t = threading.Thread(
            target=self._writer_loop,
            args=(self._writer_q, self._writer_stop, self._writer_hb),
            daemon=True, name="dl4j-ckpt-writer")
        self._writer_t.start()

    def _writer_loop(self, q, stop, hb):
        while True:
            try:
                snap, reason = get_abortable(q, stop)
            except QueueAborted:
                return
            try:
                with hb.busy():
                    self._write_snapshot(snap, reason)
            except Exception:
                # a failed write loses ONE interval, not the run — and
                # never the writer thread (a dead writer would wedge
                # every later save)
                self._m_failures.inc()
                logger.exception("async checkpoint write failed")
            finally:
                q.task_done()

    def flush(self, timeout: float = 120.0):
        """Wait until every queued snapshot is on disk (no-op for the
        synchronous mode)."""
        q = self._writer_q
        if q is None:
            return
        deadline = time.monotonic() + timeout
        while q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.02)
        if q.unfinished_tasks:
            logger.warning("checkpoint flush timed out with %d pending "
                           "write(s)", q.unfinished_tasks)

    def close(self):
        """Flush pending writes and retire the writer thread + signal
        hook. Idempotent; the listener saves synchronously afterwards."""
        self._closed = True
        _sigchain.unregister(self._sig_name())
        self.flush()
        self._writer_stop.set()
        if self._writer_t is not None:
            self._writer_t.join(timeout=10)
            self._writer_t = None
        if self._writer_hb is not None:
            _health.get_health().unregister(self._writer_hb)
            self._writer_hb = None
        self._writer_q = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _gc(self):
        # orphaned temp files from writers killed mid-save. A tmp file is
        # only an orphan if its embedded pid is not a live process (several
        # hosts may share the dir) AND it hasn't been touched recently —
        # deleting a peer's in-flight write would corrupt its save.
        now = time.time()
        for f in os.listdir(self.dir):
            if ".tmp" in f and f.startswith("checkpoint_iter"):
                path = os.path.join(self.dir, f)
                try:
                    pid = int(f.split(".")[-3])
                except (ValueError, IndexError):
                    pid = None
                if pid is not None and pid != os.getpid():
                    try:
                        os.kill(pid, 0)  # 0 = existence probe, no signal
                        continue  # writer is alive: leave its tmp alone
                    except ProcessLookupError:
                        pass  # dead pid: orphan
                    except OSError:
                        continue  # EPERM etc: play safe, keep the file
                try:
                    if now - os.path.getmtime(path) < 300:
                        continue  # written moments ago: grace window
                    os.remove(path)
                except OSError:
                    pass
        if self.keep_last <= 0:
            return
        ckpts = [name for _, name in scan_checkpoints(self.dir)]
        for stale in ckpts[:-self.keep_last]:
            try:
                os.remove(os.path.join(self.dir, stale))
            except OSError:
                pass

    # -- preemption -----------------------------------------------------------

    def _sig_name(self) -> str:
        return f"checkpoint-save-{id(self):x}"

    def _install_preemption_hook(self):
        def action(signum, frame):
            model = self._model
            if model is not None:
                try:
                    self.save(model, reason="preemption", blocking=False)
                except Exception:
                    logger.exception("preemption save failed")

        # PRIORITY_SAVE < PRIORITY_DUMP: the model hits disk before the
        # flight recorder dumps (the dump then even records the
        # checkpoint_saved event), regardless of which subsystem armed
        # its hook first — see utils/sigchain
        _sigchain.register(self._sig_name(), action,
                           priority=_sigchain.PRIORITY_SAVE)

    # -- resume ---------------------------------------------------------------

    @staticmethod
    def restore_latest(directory: str,
                       load_updater: bool = True) -> Tuple[object, dict]:
        """(model, meta) from the newest GOOD checkpoint in `directory`.
        Raises FileNotFoundError when none exists (fresh start). Survives
        torn/missing `latest.json` by scanning the checkpoint zips, and
        survives a corrupted newest checkpoint: every candidate's
        per-entry SHA-256 manifest is verified (and the load itself is
        allowed to fail) before trusting it — a bit-flipped zip is
        skipped loudly (`checkpoint_integrity_failures_total`, a
        `checkpoint_corrupt` flight-recorder event) and the previous
        good checkpoint is restored instead. When checkpoints EXIST but
        every one is rejected, the error is NoUsableCheckpointError, not
        FileNotFoundError — an `except FileNotFoundError: fresh_start()`
        caller must not silently rebuild over a corrupted history."""
        from deeplearning4j_tpu.utils.model_serializer import load_model

        for path, meta in verified_checkpoints(directory):
            t0 = time.perf_counter()
            try:
                with _tracing.span("checkpoint/load", file=meta.get("file")):
                    model = load_model(path, load_updater=load_updater)
            except Exception as e:
                note_bad_checkpoint(
                    path, f"load failed: {type(e).__name__}: {e}")
                continue
            _metrics.get_registry().histogram(
                "checkpoint_load_seconds",
                "checkpoint restore duration").observe(
                    time.perf_counter() - t0)
            return model, meta
        if any(True for _ in checkpoint_candidates(directory)):
            raise NoUsableCheckpointError(
                f"checkpoints exist in {directory!r} but every candidate "
                f"was rejected (see checkpoint_integrity_failures_total "
                f"and the checkpoint_corrupt events)")
        raise FileNotFoundError(f"no checkpoint in {directory!r}")
