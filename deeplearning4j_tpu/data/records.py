"""Record-reader ETL bridge (the DataVec analog).

Reference: RecordReader SPI + CSVRecordReader (datavec-api, consumed via
deeplearning4j-core's RecordReaderDataSetIterator, datasets/datavec/) —
rows of typed fields streamed from storage, converted to DataSets with a
label column and one-hot encoding.
"""

from __future__ import annotations

import csv
import io
from typing import Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator


class RecordReader:
    """SPI: iterable of records (lists of field values)."""

    def __iter__(self) -> Iterator[List[str]]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CollectionRecordReader(RecordReader):
    """In-memory records (reference: CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = [list(r) for r in records]

    def __iter__(self):
        return iter(self.records)


class CSVRecordReader(RecordReader):
    """CSV rows from a path or file-like (reference: CSVRecordReader with
    skipNumLines + delimiter)."""

    def __init__(self, source: Union[str, io.IOBase], skip_lines: int = 0,
                 delimiter: str = ","):
        self.source = source
        self.skip_lines = int(skip_lines)
        self.delimiter = delimiter

    def __iter__(self):
        if isinstance(self.source, str):
            fh = open(self.source, newline="")
            close = True
        else:
            self.source.seek(0)
            fh = self.source
            close = False
        try:
            reader = csv.reader(fh, delimiter=self.delimiter)
            for i, row in enumerate(reader):
                if i < self.skip_lines or not row:
                    continue
                yield row
        finally:
            if close:
                fh.close()


class RecordReaderDataSetIterator(DataSetIterator):
    """records -> DataSet batches (reference:
    RecordReaderDataSetIterator(reader, batchSize, labelIndex, numClasses)
    for classification; labelIndexFrom/To for regression)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 label_index_from: Optional[int] = None,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self.batch_size = int(batch_size)
        self.label_index = label_index
        self.num_classes = num_classes
        self.label_from = label_index_from
        self.label_to = label_index_to
        if (label_index is None) == (label_index_from is None):
            raise ValueError(
                "exactly one of label_index (classification) or "
                "label_index_from/to (regression) is required")
        self._it: Optional[Iterator] = None

    def reset(self):
        self.reader.reset()
        self._it = None

    def __iter__(self):
        self._it = iter(self.reader)
        while True:
            rows = []
            for rec in self._it:
                rows.append(rec)
                if len(rows) == self.batch_size:
                    break
            if not rows:
                return
            yield self._to_dataset(rows)

    def _to_dataset(self, rows: List[List[str]]) -> DataSet:
        a = np.asarray(rows, dtype=object)
        if self.label_index is not None:
            li = self.label_index
            feat_cols = [c for c in range(a.shape[1]) if c != li]
            x = a[:, feat_cols].astype(np.float32)
            labels = a[:, li].astype(np.int64)
            y = np.zeros((len(rows), self.num_classes), np.float32)
            y[np.arange(len(rows)), labels] = 1.0
        else:
            lo, hi = self.label_from, self.label_to
            feat_cols = [c for c in range(a.shape[1])
                         if not (lo <= c <= hi)]
            x = a[:, feat_cols].astype(np.float32)
            y = a[:, lo:hi + 1].astype(np.float32)
        return DataSet(x, y)
