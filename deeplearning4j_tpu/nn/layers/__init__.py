"""Functional layer implementations.

Runtime mirror of the config catalog (reference: nn/layers/, 35 files).
Where the reference pairs every layer with a hand-written backpropGradient,
here each layer is a pure forward function and JAX autodiff supplies the
backward pass — the whole network step compiles to one XLA program.

Dispatch: conf dataclass type -> (init_params, forward) via the registry in
registry.py. Param dicts use stable, ordered names so the flattened
parameter view (reference: MultiLayerNetwork flattenedParams,
nn/params/*ParamInitializer layouts) is deterministic.
"""

from deeplearning4j_tpu.nn.layers.registry import (
    forward_layer,
    init_layer_params,
    init_layer_state,
    param_order,
)

# Import impl modules for their registration side effects.
from deeplearning4j_tpu.nn.layers import attention, core, conv, norm, rbm, recurrent, special  # noqa: E402,F401
