"""Fused Pallas LSTM sequence kernel — the CudnnHelper-equivalent.

Why: the scan-based LSTM (nn/layers/recurrent.py) dispatches one tiny
recurrent matmul per timestep; h/c round-trip HBM every step and nothing
overlaps. Measured 0.7% MFU on the char-rnn bench (VERDICT weak #3) —
exactly the case the reference hands to cuDNN's fused LSTM
(deeplearning4j-cuda; SURVEY §7 stage 8). This kernel runs the WHOLE
sequence in one pallas_call: grid over time, h/c/RW resident in VMEM
across grid steps (TPU grids execute sequentially, scratch persists), so
HBM traffic is just xg in / y out.

Peepholes (GravesLSTM — the char-rnn baseline model) are first-class:
pI/pF feed the input/forget gates from c_{t-1}, pO feeds the output gate
from c_t, matching nn/layers/recurrent.py's Graves formulation. Plain
LSTM passes zero vectors (the [H] vector work is negligible and keeps
one kernel).

Scope (checked by the helper probe, scan fallback otherwise): sigmoid
gates + tanh cell, no time mask, forward direction. Gate blocks
[i,f,g,o] as in recurrent.py.

Backward is a second reverse-time kernel (custom_vjp): recomputes c_t
from saved post-activation gates, accumulates dRW/dpI/dpF/dpO in VMEM,
emits per-step dgate-preactivations (dxg) from which autodiff outside
the kernel derives dW/db/dx through the big batched input projection.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INTERPRET = False  # flipped by tests on CPU


def _fwd_kernel(xg_ref, rw_ref, pi_ref, pf_ref, po_ref, h0_ref, c0_ref,
                y_ref, acts_ref, hprev_ref, cprev_ref,
                h_scr, c_scr):
    t = pl.program_id(0)
    H = h0_ref.shape[-1]

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    c = c_scr[:]
    hprev_ref[0] = h.astype(hprev_ref.dtype)
    cprev_ref[0] = c.astype(cprev_ref.dtype)

    pre = xg_ref[0].astype(jnp.float32) + jnp.dot(
        h, rw_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32)
    pi = pi_ref[0].astype(jnp.float32)
    pf = pf_ref[0].astype(jnp.float32)
    po = po_ref[0].astype(jnp.float32)
    i = jax.nn.sigmoid(pre[:, :H] + c * pi)
    f = jax.nn.sigmoid(pre[:, H:2 * H] + c * pf)
    g = jnp.tanh(pre[:, 2 * H:3 * H])
    c_new = f * c + i * g
    o = jax.nn.sigmoid(pre[:, 3 * H:] + c_new * po)
    h_new = o * jnp.tanh(c_new)

    acts_ref[0] = jnp.concatenate([i, f, g, o], axis=-1).astype(acts_ref.dtype)
    y_ref[0] = h_new.astype(y_ref.dtype)
    h_scr[:] = h_new
    c_scr[:] = c_new


def _bwd_kernel(acts_ref, hprev_ref, cprev_ref, rw_ref,
                pi_ref, pf_ref, po_ref, dy_ref, dcF_ref,
                dxg_ref, drw_ref, dpi_ref, dpf_ref, dpo_ref,
                dh0_ref, dc0_ref,
                dh_scr, dc_scr, drw_scr, dp_scr):
    k = pl.program_id(0)           # 0 .. T-1, walking time BACKWARD
    T = pl.num_programs(0)
    H = dh0_ref.shape[-1]

    @pl.when(k == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = dcF_ref[:].astype(jnp.float32)
        drw_scr[:] = jnp.zeros_like(drw_scr)
        dp_scr[:] = jnp.zeros_like(dp_scr)

    acts = acts_ref[0].astype(jnp.float32)
    i, f = acts[:, :H], acts[:, H:2 * H]
    g, o = acts[:, 2 * H:3 * H], acts[:, 3 * H:]
    hprev = hprev_ref[0].astype(jnp.float32)
    cprev = cprev_ref[0].astype(jnp.float32)
    pi = pi_ref[0].astype(jnp.float32)
    pf = pf_ref[0].astype(jnp.float32)
    po = po_ref[0].astype(jnp.float32)

    dh = dh_scr[:] + dy_ref[0].astype(jnp.float32)
    c_t = f * cprev + i * g        # recomputed, not stored
    tc = jnp.tanh(c_t)
    do = dh * tc
    dpre_o = do * o * (1.0 - o)
    # dc collects: tanh path, next-step carry, and the output peephole
    dc = dh * o * (1.0 - tc * tc) + dc_scr[:] + dpre_o * po
    di = dc * g
    dg = dc * i
    df = dc * cprev
    dpre_i = di * i * (1.0 - i)
    dpre_f = df * f * (1.0 - f)
    dpre_g = dg * (1.0 - g * g)
    dpre = jnp.concatenate([dpre_i, dpre_f, dpre_g, dpre_o], axis=-1)

    dxg_ref[0] = dpre.astype(dxg_ref.dtype)
    drw_scr[:] += jnp.dot(hprev.T, dpre, preferred_element_type=jnp.float32)
    # peephole grads: rows 0/1/2 of dp_scr = dpI/dpF/dpO ([1, H] sums)
    dp_scr[0, :] += jnp.sum(dpre_i * cprev, axis=0)
    dp_scr[1, :] += jnp.sum(dpre_f * cprev, axis=0)
    dp_scr[2, :] += jnp.sum(dpre_o * c_t, axis=0)
    dh_scr[:] = jnp.dot(dpre, rw_ref[:].astype(jnp.float32).T,
                        preferred_element_type=jnp.float32)
    dc_scr[:] = dc * f + dpre_i * pi + dpre_f * pf

    @pl.when(k == T - 1)
    def _():
        drw_ref[:] = drw_scr[:].astype(drw_ref.dtype)
        dpi_ref[0] = dp_scr[0, :].astype(dpi_ref.dtype)
        dpf_ref[0] = dp_scr[1, :].astype(dpf_ref.dtype)
        dpo_ref[0] = dp_scr[2, :].astype(dpo_ref.dtype)
        dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)
        dc0_ref[:] = dc_scr[:].astype(dc0_ref.dtype)


def _fwd_call(xg, rw, pI, pF, pO, h0, c0):
    T, B, H4 = xg.shape
    H = H4 // 4
    dt = xg.dtype
    vec = lambda: pl.BlockSpec((1, H), lambda t: (0, 0),
                               memory_space=pltpu.VMEM)
    y, acts, hprev, cprev = pl.pallas_call(
        _fwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H, H4), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            vec(), vec(), vec(),
            pl.BlockSpec((B, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((T, B, H4), dt),
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((T, B, H), dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(xg, rw, pI[None, :], pF[None, :], pO[None, :], h0, c0)
    return y, acts, hprev, cprev


def _bwd_call(acts, hprev, cprev, rw, pI, pF, pO, dy, dcF):
    T, B, H4 = acts.shape
    H = H4 // 4
    dt = acts.dtype
    rev = lambda t: (T - 1 - t, 0, 0)
    fixed = lambda shape: pl.BlockSpec(shape, lambda t: (0,) * len(shape),
                                       memory_space=pltpu.VMEM)
    dxg, drw, dpi, dpf, dpo, dh0, dc0 = pl.pallas_call(
        _bwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B, H), rev, memory_space=pltpu.VMEM),
            fixed((H, H4)),
            fixed((1, H)), fixed((1, H)), fixed((1, H)),
            pl.BlockSpec((1, B, H), rev, memory_space=pltpu.VMEM),
            fixed((B, H)),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H4), rev, memory_space=pltpu.VMEM),
            fixed((H, H4)),
            fixed((1, H)), fixed((1, H)), fixed((1, H)),
            fixed((B, H)), fixed((B, H)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H4), dt),
            jax.ShapeDtypeStruct((H, H4), jnp.float32),
            jax.ShapeDtypeStruct((1, H), jnp.float32),
            jax.ShapeDtypeStruct((1, H), jnp.float32),
            jax.ShapeDtypeStruct((1, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((H, H4), jnp.float32),
            pltpu.VMEM((3, H), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(acts, hprev, cprev, rw, pI[None, :], pF[None, :], pO[None, :],
      dy, dcF)
    return dxg, drw, dpi[0], dpf[0], dpo[0], dh0, dc0


@jax.custom_vjp
def lstm_sequence(xg, rw, pI, pF, pO, h0, c0):
    """Fused (peephole-capable) LSTM over a whole sequence.

    xg: [T, B, 4H] precomputed input projections + bias (time-major).
    rw: [H, 4H] recurrent weights. pI/pF/pO: [H] peephole vectors (zeros
    for plain LSTM). h0/c0: [B, H].
    Returns (y [T, B, H], hF, cF)."""
    out, _ = _lstm_fwd(xg, rw, pI, pF, pO, h0, c0)
    return out


def _lstm_fwd(xg, rw, pI, pF, pO, h0, c0):
    y, acts, hprev, cprev = _fwd_call(xg, rw, pI, pF, pO, h0, c0)
    H = rw.shape[0]
    a_last = acts[-1].astype(jnp.float32)
    cF = (a_last[:, H:2 * H] * cprev[-1].astype(jnp.float32)
          + a_last[:, :H] * a_last[:, 2 * H:3 * H]).astype(y.dtype)
    return (y, y[-1], cF), (acts, hprev, cprev, rw, pI, pF, pO)


def _lstm_bwd(res, cts):
    acts, hprev, cprev, rw, pI, pF, pO = res
    dy, dhF, dcF = cts
    # the hF cotangent folds into the last dy row; dcF enters the kernel
    dy = dy.at[-1].add(dhF.astype(dy.dtype))
    dxg, drw, dpi, dpf, dpo, dh0, dc0 = _bwd_call(
        acts, hprev, cprev, rw, pI, pF, pO, dy, dcF.astype(dy.dtype))
    return (dxg, drw.astype(rw.dtype), dpi.astype(pI.dtype),
            dpf.astype(pF.dtype), dpo.astype(pO.dtype), dh0, dc0)


lstm_sequence.defvjp(_lstm_fwd, _lstm_bwd)


# -- single-step decode kernel ------------------------------------------------
# The serving decode engine (serving/decode.py) advances every slot by ONE
# timestep per dispatch. Routing that through the sequence kernel would
# emit the VJP stashes (acts/hprev/cprev — 6x the useful output) for a
# path that never differentiates; this kernel is the inference-only step:
# one [B,H]x[H,4H] MXU matmul + gate math, h/c in, h/c out.


def _step_kernel(xg_ref, rw_ref, pi_ref, pf_ref, po_ref, h0_ref, c0_ref,
                 h_ref, c_ref):
    H = h0_ref.shape[-1]
    h = h0_ref[:].astype(jnp.float32)
    c = c0_ref[:].astype(jnp.float32)
    pre = xg_ref[:].astype(jnp.float32) + jnp.dot(
        h, rw_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32)
    pi = pi_ref[0].astype(jnp.float32)
    pf = pf_ref[0].astype(jnp.float32)
    po = po_ref[0].astype(jnp.float32)
    i = jax.nn.sigmoid(pre[:, :H] + c * pi)
    f = jax.nn.sigmoid(pre[:, H:2 * H] + c * pf)
    g = jnp.tanh(pre[:, 2 * H:3 * H])
    c_new = f * c + i * g
    o = jax.nn.sigmoid(pre[:, 3 * H:] + c_new * po)
    h_ref[:] = (o * jnp.tanh(c_new)).astype(h_ref.dtype)
    c_ref[:] = c_new.astype(c_ref.dtype)


def lstm_step(xg, rw, pI, pF, pO, h0, c0):
    """One decode timestep, fused. xg: [B, 4H] precomputed input
    projection + bias; rw: [H, 4H]; pI/pF/pO: [H] peephole vectors
    (zeros for plain LSTM); h0/c0: [B, H]. Returns (h1, c1).
    Inference-only: no VJP is defined — the decode path never
    differentiates."""
    B, H4 = xg.shape
    H = H4 // 4
    dt = xg.dtype
    whole = lambda shape: pl.BlockSpec(shape, lambda: (0,) * len(shape),
                                       memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _step_kernel,
        in_specs=[whole((B, H4)), whole((H, H4)),
                  whole((1, H)), whole((1, H)), whole((1, H)),
                  whole((B, H)), whole((B, H))],
        out_specs=[whole((B, H)), whole((B, H))],
        out_shape=[jax.ShapeDtypeStruct((B, H), dt),
                   jax.ShapeDtypeStruct((B, H), dt)],
        interpret=_INTERPRET,
    )(xg, rw, pI[None, :], pF[None, :], pO[None, :], h0, c0)


def step_supported(*, peephole, gate_act, cell_act, **_):
    """Probe for the single-step decode kernel: same numeric scope as the
    sequence kernel (sigmoid gates + tanh cell, peepholes optional); the
    decode call site only consults it for unmasked forward steps."""
    del peephole
    if gate_act not in ("sigmoid",) or cell_act not in ("tanh",):
        return False
    backend = jax.default_backend()
    return backend == "tpu" or _INTERPRET


def supported(*, peephole, mask, gate_act, cell_act, reverse, **_):
    """Helper probe: the fused kernel covers sigmoid gates + tanh cell,
    forward direction, no time mask (with or without peepholes); anything
    else falls back to the scan path (reference: cuDNN helper
    checkSupported fallback)."""
    del peephole  # both variants supported
    if reverse or mask is not None:
        return False
    if gate_act not in ("sigmoid",) or cell_act not in ("tanh",):
        return False
    backend = jax.default_backend()
    return backend == "tpu" or _INTERPRET


def register():
    from deeplearning4j_tpu.ops.helpers import register_helper

    register_helper("lstm_sequence", lstm_sequence, supported,
                    name="pallas_fused_lstm", family=lambda **_: "lstm_seq")
    register_helper("lstm_decode_step", lstm_step, step_supported,
                    name="pallas_lstm_step", family=lambda **_: "lstm_step")


register()
