"""Line-search (full-gradient) optimizers: line gradient descent,
conjugate gradient, L-BFGS.

Reference: optimize/solvers/ — LineGradientDescent, ConjugateGradient,
LBFGS over BaseOptimizer (line-search optimize() :182-230) with
BackTrackLineSearch (Armijo backtracking). These run the model's compiled
value+gradient function inside a host-side search loop: the per-evaluation
math is one jitted XLA call on the flat parameter vector, the search logic
(direction update, step halving) is Python — the same split as the
reference's Java-loop-around-native-ops, with XLA in place of libnd4j.

SGD remains the fast path (one fused jitted step, train/updaters.py);
these optimizers trade steps/sec for better per-batch convergence, exactly
as in the reference.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


class BackTrackLineSearch:
    """Armijo backtracking (reference: optimize/solvers/BackTrackLineSearch
    .java): try the full step, halve until sufficient decrease or maxIter."""

    def __init__(self, c1: float = 1e-4, rho: float = 0.5, max_iterations: int = 5):
        self.c1 = c1
        self.rho = rho
        self.max_iterations = max_iterations

    def search(self, value_fn: Callable, x0, f0, g0, direction, step0: float):
        """Returns (x_new, f_new, step_taken)."""
        slope = float(jnp.vdot(g0, direction))
        if slope >= 0:
            # not a descent direction — caller should reset (CG/LBFGS do)
            return x0, f0, 0.0
        step = step0
        for i in range(self.max_iterations):
            x_new = x0 + step * direction
            f_new = float(value_fn(x_new))
            if np.isfinite(f_new) and f_new <= f0 + self.c1 * step * slope:
                if i == 0:
                    # full step accepted — expand while it keeps helping
                    # (reference: BackTrackLineSearch stpmax probing)
                    for _ in range(self.max_iterations):
                        x_try = x0 + 2.0 * step * direction
                        f_try = float(value_fn(x_try))
                        ok = (
                            np.isfinite(f_try)
                            and f_try <= f0 + self.c1 * 2.0 * step * slope
                            and f_try < f_new
                        )
                        if not ok:
                            break
                        step *= 2.0
                        x_new, f_new = x_try, f_try
                return x_new, f_new, step
            step *= self.rho  # backtrack
        return x0, f0, 0.0


class _FlatProblem:
    """value_and_grad of the network loss as a function of the flat param
    vector. Jitted ONCE on the network (batch data are traced arguments, so
    successive batches reuse the compiled program)."""

    def __init__(self, net):
        from deeplearning4j_tpu.nn.params import flat_to_params

        confs = net._ordered_layer_confs()
        params0 = net.params_list

        def loss_of_flat(flat, states, x, y, f_mask, l_mask, rng):
            plist = flat_to_params(confs, params0, flat)
            s, _ = net._loss(plist, states, x, y, f_mask, l_mask,
                             rng=rng, training=True)
            return s

        self._vg = jax.jit(jax.value_and_grad(loss_of_flat))
        self._v = jax.jit(loss_of_flat)
        self._bound = None

    def bind(self, states, x, y, f_mask, l_mask, rng) -> "_FlatProblem":
        self._bound = (states, x, y, f_mask, l_mask, rng)
        return self

    def value_and_grad(self, flat):
        return self._vg(flat, *self._bound)

    def value(self, flat):
        return self._v(flat, *self._bound)


class BaseLineSearchOptimizer:
    """One `optimize(...)` call = direction + line search on one batch
    (reference: BaseOptimizer.optimize :182-230)."""

    name = "base"

    def __init__(self, max_line_search_iterations: int = 5):
        self.line_search = BackTrackLineSearch(
            max_iterations=max_line_search_iterations
        )
        self.reset()

    def reset(self):
        pass

    def direction(self, g, flat):
        raise NotImplementedError

    def optimize(self, problem: _FlatProblem, flat, step0: float):
        f0, g = problem.value_and_grad(flat)
        f0 = float(f0)
        d = self.direction(g, flat)
        new_flat, f_new, step = self.line_search.search(
            problem.value, flat, f0, g, d, step0
        )
        if step == 0.0:
            # no progress along d (or non-descent) — reset memory and take a
            # plain small gradient step (reference: step fallback)
            self.reset()
            new_flat = flat - step0 * g
            f_new = float(problem.value(new_flat))
        self._post_step(g, new_flat - flat)
        return new_flat, f_new

    def _post_step(self, g, s):
        pass


class LineGradientDescent(BaseLineSearchOptimizer):
    """Steepest descent + line search (reference: LineGradientDescent.java)."""

    name = "line_gradient_descent"

    def direction(self, g, flat):
        return -g


class ConjugateGradient(BaseLineSearchOptimizer):
    """Nonlinear CG, Polak-Ribière with automatic restart (reference:
    ConjugateGradient.java)."""

    name = "conjugate_gradient"

    def reset(self):
        self._g_prev = None
        self._d_prev = None

    def direction(self, g, flat):
        if self._g_prev is None:
            d = -g
        else:
            gg = float(jnp.vdot(self._g_prev, self._g_prev))
            beta = max(0.0, float(jnp.vdot(g, g - self._g_prev)) / max(gg, 1e-20))
            d = -g + beta * self._d_prev
            if float(jnp.vdot(d, g)) >= 0:  # not a descent direction: restart
                d = -g
        self._g_prev = g
        self._d_prev = d
        return d


class LBFGS(BaseLineSearchOptimizer):
    """Limited-memory BFGS, two-loop recursion (reference: LBFGS.java,
    default history m=10)."""

    name = "lbfgs"

    def __init__(self, m: int = 10, max_line_search_iterations: int = 5):
        self.m = m
        super().__init__(max_line_search_iterations)

    def reset(self):
        self._s = []  # param deltas
        self._y = []  # gradient deltas
        self._g_prev = None

    def direction(self, g, flat):
        if self._g_prev is not None:
            y = g - self._g_prev
            s = self._last_step
            ys = float(jnp.vdot(y, s))
            if ys > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.m:
                    self._s.pop(0)
                    self._y.pop(0)
        q = g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / float(jnp.vdot(y, s))
            a = rho * float(jnp.vdot(s, q))
            alphas.append((a, rho, s, y))
            q = q - a * y
        if self._y:
            y_last, s_last = self._y[-1], self._s[-1]
            gamma = float(jnp.vdot(s_last, y_last)) / float(jnp.vdot(y_last, y_last))
            q = gamma * q
        for a, rho, s, y in reversed(alphas):
            b = rho * float(jnp.vdot(y, q))
            q = q + (a - b) * s
        self._g_prev = g
        return -q

    def _post_step(self, g, s):
        self._last_step = s


_OPTIMIZERS = {
    "line_gradient_descent": LineGradientDescent,
    "conjugate_gradient": ConjugateGradient,
    "lbfgs": LBFGS,
}


def make_line_search_optimizer(algo: str) -> BaseLineSearchOptimizer:
    cls = _OPTIMIZERS.get(algo)
    if cls is None:
        raise ValueError(
            f"unknown optimization algorithm {algo!r}; known: sgd, "
            + ", ".join(sorted(_OPTIMIZERS))
        )
    return cls()
