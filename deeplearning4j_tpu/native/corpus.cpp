// Native corpus pipeline — tokenization + vocab construction + indexing.
//
// The runtime-side analog of the reference's text pipeline
// (text/tokenization/ + VocabConstructor.java, 612 LoC, which fans out
// Java worker threads because per-token JVM work was the bottleneck).
// Here the whole pass — read, tokenize, hash-count, frequency-sort,
// re-index — runs in C++ behind a ctypes boundary; Python sees only
// numpy arrays. A pure-Python dict/Counter pass over a multi-GB corpus
// is 10-30x slower and holds the GIL the whole time.
//
// Contract (must match nlp/vocab.VocabConstructor): vocabulary sorted by
// (count desc, word asc); tokens split on ASCII whitespace; optional
// lowercasing.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 corpus.cpp -o libdl4jcorpus.so
// (native/__init__.py does this on first use and caches the .so).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Corpus {
    // token stream as indices into `words` (pre-filter ids)
    std::vector<int64_t> stream;
    std::vector<int64_t> sentence_offsets;  // start of each sentence
    std::vector<std::string> words;         // first-seen order
    std::vector<int64_t> counts;            // aligned with words

    // filtered+sorted view (built per min_count)
    int64_t cached_min_count = -1;
    std::vector<int64_t> rank;      // pre-filter id -> vocab index or -1
    std::vector<int64_t> vocab_ids; // vocab index -> pre-filter id
};

inline bool is_space(char c) {
    return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v'
        || c == '\f';
}

void build_ranks(Corpus* c, int64_t min_count) {
    if (c->cached_min_count == min_count) return;
    std::vector<int64_t> keep;
    keep.reserve(c->words.size());
    for (int64_t i = 0; i < (int64_t)c->words.size(); ++i)
        if (c->counts[i] >= min_count) keep.push_back(i);
    // (count desc, word asc) — the VocabConstructor ordering
    std::sort(keep.begin(), keep.end(), [&](int64_t a, int64_t b) {
        if (c->counts[a] != c->counts[b]) return c->counts[a] > c->counts[b];
        return c->words[a] < c->words[b];
    });
    c->rank.assign(c->words.size(), -1);
    for (int64_t r = 0; r < (int64_t)keep.size(); ++r)
        c->rank[keep[r]] = r;
    c->vocab_ids = std::move(keep);
    c->cached_min_count = min_count;
}

}  // namespace

extern "C" {

// Tokenize + count a whole file. Returns an opaque handle (nullptr on
// I/O failure). newline = sentence boundary.
void* corpus_open(const char* path, int lowercase) {
    std::ifstream f(path, std::ios::binary);
    if (!f) return nullptr;
    auto* c = new Corpus();
    std::unordered_map<std::string, int64_t> ids;
    std::string line, tok;
    while (std::getline(f, line)) {
        c->sentence_offsets.push_back((int64_t)c->stream.size());
        size_t i = 0, n = line.size();
        while (i < n) {
            while (i < n && is_space(line[i])) ++i;
            size_t j = i;
            while (j < n && !is_space(line[j])) ++j;
            if (j > i) {
                tok.assign(line, i, j - i);
                if (lowercase)
                    for (auto& ch : tok)
                        if (ch >= 'A' && ch <= 'Z') ch += 32;
                auto it = ids.find(tok);
                int64_t id;
                if (it == ids.end()) {
                    id = (int64_t)c->words.size();
                    ids.emplace(tok, id);
                    c->words.push_back(tok);
                    c->counts.push_back(0);
                } else {
                    id = it->second;
                }
                ++c->counts[id];
                c->stream.push_back(id);
            }
            i = j;
        }
    }
    c->sentence_offsets.push_back((int64_t)c->stream.size());
    return c;
}

void corpus_close(void* h) { delete static_cast<Corpus*>(h); }

int64_t corpus_total_tokens(void* h) {
    return (int64_t)static_cast<Corpus*>(h)->stream.size();
}

int64_t corpus_num_sentences(void* h) {
    return (int64_t)static_cast<Corpus*>(h)->sentence_offsets.size() - 1;
}

int64_t corpus_vocab_size(void* h, int64_t min_count) {
    auto* c = static_cast<Corpus*>(h);
    build_ranks(c, min_count);
    return (int64_t)c->vocab_ids.size();
}

// Byte length of the '\n'-joined vocab dump (for buffer sizing).
int64_t corpus_vocab_bytes(void* h, int64_t min_count) {
    auto* c = static_cast<Corpus*>(h);
    build_ranks(c, min_count);
    int64_t total = 0;
    for (int64_t id : c->vocab_ids) total += (int64_t)c->words[id].size() + 1;
    return total;
}

// Write words ('\n'-joined, vocab order) into buf and counts into
// counts_out [vocab_size]. Returns bytes written, or -1 if buf too small.
int64_t corpus_vocab_dump(void* h, int64_t min_count, char* buf,
                          int64_t buf_len, int64_t* counts_out) {
    auto* c = static_cast<Corpus*>(h);
    build_ranks(c, min_count);
    int64_t off = 0;
    for (int64_t r = 0; r < (int64_t)c->vocab_ids.size(); ++r) {
        const std::string& w = c->words[c->vocab_ids[r]];
        if (off + (int64_t)w.size() + 1 > buf_len) return -1;
        std::memcpy(buf + off, w.data(), w.size());
        off += (int64_t)w.size();
        buf[off++] = '\n';
        counts_out[r] = c->counts[c->vocab_ids[r]];
    }
    return off;
}

// Re-index the token stream against the (min_count-filtered) vocab:
// tokens_out [total_tokens] gets the vocab index or -1 (filtered word);
// offsets_out [num_sentences + 1] gets sentence start offsets.
void corpus_index(void* h, int64_t min_count, int32_t* tokens_out,
                  int64_t* offsets_out) {
    auto* c = static_cast<Corpus*>(h);
    build_ranks(c, min_count);
    for (size_t i = 0; i < c->stream.size(); ++i)
        tokens_out[i] = (int32_t)c->rank[c->stream[i]];
    for (size_t i = 0; i < c->sentence_offsets.size(); ++i)
        offsets_out[i] = c->sentence_offsets[i];
}

}  // extern "C"
