"""Serving entry points: k-NN REST server (reference:
deeplearning4j-nearestneighbor-server), model-inference REST server
(bucketed+pipelined ParallelInference behind POST /predict), and
ParallelInference itself (parallel/)."""

from deeplearning4j_tpu.serving.inference_server import InferenceServer
from deeplearning4j_tpu.serving.knnserver import NearestNeighborsServer

__all__ = ["InferenceServer", "NearestNeighborsServer"]
