"""Layer forward-pass tests (reference: nn/layers/* behavior)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers import forward_layer, init_layer_params, init_layer_state
from deeplearning4j_tpu.nn.layers.registry import LayerContext

F32 = jnp.float32


def _mk(conf, **defaults):
    # fill network-default fields a builder would normally set
    for k, v in dict(activation="tanh", weight_init="xavier", bias_init=0.0,
                     l1=0.0, l2=0.0, dropout=0.0, **defaults).items():
        if hasattr(conf, k) and getattr(conf, k) is None:
            setattr(conf, k, v)
    return conf


def test_dense_forward_shape_and_math():
    conf = _mk(L.DenseLayer(n_in=4, n_out=3, activation="identity"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jnp.ones((2, 4))
    y, _ = forward_layer(conf, p, x, LayerContext())
    assert y.shape == (2, 3)
    np.testing.assert_allclose(y, x @ p["W"] + p["b"], rtol=1e-6)


def test_dropout_train_vs_test():
    conf = _mk(L.DenseLayer(n_in=10, n_out=10, activation="identity", dropout=0.5))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jnp.ones((4, 10))
    y_test, _ = forward_layer(conf, p, x, LayerContext(training=False))
    np.testing.assert_allclose(y_test, x @ p["W"] + p["b"], rtol=1e-6)
    y_tr, _ = forward_layer(conf, p, x, LayerContext(training=True, rng=jax.random.PRNGKey(1)))
    assert not np.allclose(np.asarray(y_tr), np.asarray(y_test))


def test_conv_shapes_truncate_and_same():
    conf = _mk(L.ConvolutionLayer(n_in=3, n_out=8, kernel_size=(3, 3),
                                  stride=(1, 1), activation="relu"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jnp.ones((2, 10, 10, 3))
    y, _ = forward_layer(conf, p, x, LayerContext())
    assert y.shape == (2, 8, 8, 8)

    conf2 = _mk(L.ConvolutionLayer(n_in=3, n_out=8, kernel_size=(3, 3),
                                   stride=(2, 2), convolution_mode="same",
                                   activation="relu"))
    p2 = init_layer_params(jax.random.PRNGKey(0), conf2, F32)
    y2, _ = forward_layer(conf2, p2, x, LayerContext())
    assert y2.shape == (2, 5, 5, 8)


def test_conv_identity_kernel():
    # 1x1 conv with identity weights reproduces input channels
    conf = _mk(L.ConvolutionLayer(n_in=2, n_out=2, kernel_size=(1, 1),
                                  activation="identity"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    p["W"] = jnp.eye(2).reshape(1, 1, 2, 2)
    p["b"] = jnp.zeros(2)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 4, 4, 2))
    y, _ = forward_layer(conf, p, x, LayerContext())
    np.testing.assert_allclose(y, x, rtol=1e-5)


def test_max_and_avg_pooling_values():
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    mx = _mk(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
    y, _ = forward_layer(mx, {}, x, LayerContext())
    np.testing.assert_allclose(y[0, :, :, 0], [[5, 7], [13, 15]])
    av = _mk(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2), pooling_type="avg"))
    y2, _ = forward_layer(av, {}, x, LayerContext())
    np.testing.assert_allclose(y2[0, :, :, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_pnorm_pooling():
    x = jnp.array([[3.0, 4.0], [0.0, 0.0]]).reshape(1, 2, 2, 1)
    pn = _mk(L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                pooling_type="pnorm", pnorm=2))
    y, _ = forward_layer(pn, {}, x, LayerContext())
    np.testing.assert_allclose(y[0, 0, 0, 0], 5.0, rtol=1e-6)


def test_batchnorm_normalizes_and_tracks_stats():
    conf = _mk(L.BatchNormalization(n_in=3))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    st = init_layer_state(conf, F32)
    x = 5.0 + 2.0 * jax.random.normal(jax.random.PRNGKey(1), (256, 3))
    y, new_st = forward_layer(conf, p, x, LayerContext(training=True, state=st))
    assert abs(float(jnp.mean(y))) < 0.1
    assert abs(float(jnp.std(y)) - 1.0) < 0.1
    # running stats moved toward batch stats: 0.9*0 + 0.1*mean(x)
    np.testing.assert_allclose(new_st["mean"], 0.1 * jnp.mean(x, 0), rtol=1e-3)
    # inference path uses provided stats
    y_inf, none_st = forward_layer(conf, p, x, LayerContext(training=False, state=new_st))
    assert none_st is None
    assert y_inf.shape == x.shape


def test_batchnorm_4d():
    conf = _mk(L.BatchNormalization(n_in=4))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    st = init_layer_state(conf, F32)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 5, 5, 4)) * 3 + 1
    y, _ = forward_layer(conf, p, x, LayerContext(training=True, state=st))
    assert y.shape == x.shape
    m = jnp.mean(y, axis=(0, 1, 2))
    np.testing.assert_allclose(m, jnp.zeros(4), atol=0.05)


def test_lrn_shape_and_scale_down():
    conf = L.LocalResponseNormalization()
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
    y, _ = forward_layer(conf, {}, x, LayerContext())
    assert y.shape == x.shape
    # denominator >= k^beta > 1 for k=2 => |y| < |x|
    assert float(jnp.max(jnp.abs(y))) < float(jnp.max(jnp.abs(x)))


def test_embedding_lookup():
    conf = _mk(L.EmbeddingLayer(n_in=10, n_out=4, activation="identity"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    idx = jnp.array([1, 3, 1])
    y, _ = forward_layer(conf, p, idx, LayerContext())
    assert y.shape == (3, 4)
    np.testing.assert_allclose(y[0], y[2], rtol=1e-6)
    np.testing.assert_allclose(y[0], p["W"][1] + p["b"], rtol=1e-6)


def test_lstm_shapes_and_determinism():
    conf = _mk(L.LSTM(n_in=6, n_out=5, activation="tanh"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 7, 6))
    y, _ = forward_layer(conf, p, x, LayerContext())
    assert y.shape == (3, 7, 5)
    y2, _ = forward_layer(conf, p, x, LayerContext())
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y2))


def test_graves_lstm_forward():
    conf = _mk(L.GravesLSTM(n_in=4, n_out=3, activation="tanh"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    assert "pI" in p and "pF" in p and "pO" in p
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 5, 4))
    y, _ = forward_layer(conf, p, x, LayerContext())
    assert y.shape == (2, 5, 3)


def test_lstm_masking_keeps_state_and_zeroes_output():
    conf = _mk(L.LSTM(n_in=3, n_out=4, activation="tanh"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 3))
    mask = jnp.array([[1.0, 1.0, 1.0, 0.0, 0.0, 0.0]])
    y, _ = forward_layer(conf, p, x, LayerContext(mask=mask))
    # outputs at masked steps are exactly zero
    np.testing.assert_array_equal(np.asarray(y[0, 3:]), np.zeros((3, 4)))
    # truncating the sequence gives identical prefix outputs
    y_short, _ = forward_layer(conf, p, x[:, :3], LayerContext())
    np.testing.assert_allclose(np.asarray(y[0, :3]), np.asarray(y_short[0]), rtol=1e-5)


def test_lstm_stateful_carry():
    conf = _mk(L.LSTM(n_in=3, n_out=4, activation="tanh"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 3))
    # full pass
    y_full, _ = forward_layer(conf, p, x, LayerContext())
    # two halves with carried state == full pass
    zeros = {"h": jnp.zeros((2, 4)), "c": jnp.zeros((2, 4))}
    y1, st1 = forward_layer(conf, p, x[:, :4], LayerContext(state=zeros))
    y2, _ = forward_layer(conf, p, x[:, 4:], LayerContext(state=st1))
    np.testing.assert_allclose(np.asarray(y_full), np.asarray(jnp.concatenate([y1, y2], axis=1)), rtol=1e-5)


def test_bidirectional_lstm_add_semantics():
    conf = _mk(L.GravesBidirectionalLSTM(n_in=3, n_out=4, activation="tanh"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 5, 3))
    y, _ = forward_layer(conf, p, x, LayerContext())
    assert y.shape == (2, 5, 4)
    # zeroing the backward params leaves the forward-only result
    p0 = dict(p)
    for k in list(p0):
        if k.startswith("b_"):
            p0[k] = jnp.zeros_like(p0[k])
    y_fwd_only, _ = forward_layer(conf, p0, x, LayerContext())
    # compare against a unidirectional GravesLSTM with the f_ params
    uni = _mk(L.GravesLSTM(n_in=3, n_out=4, activation="tanh"))
    pu = {k[2:]: v for k, v in p.items() if k.startswith("f_")}
    yu, _ = forward_layer(uni, pu, x, LayerContext())
    # backward pass with zero weights still contributes sigmoid(0)*tanh-ish
    # outputs of zero (tanh(0)=0) so add leaves the forward result
    np.testing.assert_allclose(np.asarray(y_fwd_only), np.asarray(yu), atol=1e-6)


def test_global_pooling_cnn_and_rnn_masked():
    gp = L.GlobalPoolingLayer(pooling_type="avg")
    x4 = jnp.arange(8.0).reshape(1, 2, 2, 2)
    y, _ = forward_layer(gp, {}, x4, LayerContext())
    np.testing.assert_allclose(y, [[(0 + 2 + 4 + 6) / 4, (1 + 3 + 5 + 7) / 4]])
    x3 = jnp.stack([jnp.ones((4, 3)), 2 * jnp.ones((4, 3))])  # [2,4,3]
    mask = jnp.array([[1.0, 1.0, 0.0, 0.0], [1.0, 1.0, 1.0, 1.0]])
    y2, _ = forward_layer(gp, {}, x3, LayerContext(mask=mask))
    np.testing.assert_allclose(y2, [[1.0] * 3, [2.0] * 3])


def test_zero_padding():
    conf = L.ZeroPaddingLayer(padding=(1, 2, 3, 4))
    x = jnp.ones((1, 5, 5, 2))
    y, _ = forward_layer(conf, {}, x, LayerContext())
    assert y.shape == (1, 8, 12, 2)
    assert float(y[0, 0, 0, 0]) == 0.0


def test_vae_forward_and_elbo():
    conf = _mk(L.VariationalAutoencoder(
        n_in=12, n_out=4, encoder_layer_sizes=[16], decoder_layer_sizes=[16],
        pzx_activation="identity", activation="tanh"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jax.random.uniform(jax.random.PRNGKey(1), (5, 12))
    y, _ = forward_layer(conf, p, x, LayerContext())
    assert y.shape == (5, 4)
    from deeplearning4j_tpu.nn.layers.special import vae_elbo

    elbo = vae_elbo(conf, p, x, jax.random.PRNGKey(2))
    assert elbo.shape == (5,)
    assert bool(jnp.all(jnp.isfinite(elbo)))


def test_frozen_layer_delegates():
    inner = _mk(L.DenseLayer(n_in=4, n_out=3, activation="identity", dropout=0.5))
    conf = L.FrozenLayer(inner=inner)
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jnp.ones((2, 4))
    # frozen: no dropout even in training mode
    y, _ = forward_layer(conf, p, x, LayerContext(training=True, rng=jax.random.PRNGKey(1)))
    np.testing.assert_allclose(y, x @ p["W"] + p["b"], rtol=1e-6)


def test_conv1d_and_subsampling1d():
    conf = _mk(L.Convolution1DLayer(n_in=4, n_out=6, kernel_size=3, activation="relu"))
    p = init_layer_params(jax.random.PRNGKey(0), conf, F32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 10, 4))
    y, _ = forward_layer(conf, p, x, LayerContext())
    assert y.shape == (2, 8, 6)
    sub = L.Subsampling1DLayer(kernel_size=2, stride=2)
    y2, _ = forward_layer(sub, {}, y, LayerContext())
    assert y2.shape == (2, 4, 6)


def test_vae_reconstruction_distribution_set():
    """Reference parity: the ReconstructionDistribution family
    (Gaussian/Bernoulli/Exponential/LossFunctionWrapper —
    nn/conf/layers/variational/)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.nn.layers.special import vae_elbo, vae_init

    rng = np.random.default_rng(0)
    x01 = jnp.asarray(rng.random((6, 8)), jnp.float32)  # in [0,1]
    for dist in ({"type": "bernoulli"},
                 {"type": "gaussian", "activation": "identity"},
                 {"type": "exponential"},
                 {"type": "loss_wrapper", "loss": "mse",
                  "activation": "sigmoid"}):
        conf = L.VariationalAutoencoder(
            n_in=8, n_out=3, encoder_layer_sizes=[10],
            decoder_layer_sizes=[10], activation="tanh",
            weight_init="xavier", pzx_activation="identity",
            reconstruction_distribution=dist)
        params = vae_init(jax.random.PRNGKey(0), conf, jnp.float32)
        elbo = vae_elbo(conf, params, x01, jax.random.PRNGKey(1))
        assert elbo.shape == (6,)
        assert bool(jnp.isfinite(elbo).all()), dist
    import pytest as _pytest

    conf = L.VariationalAutoencoder(
        n_in=8, n_out=3, encoder_layer_sizes=[10],
        decoder_layer_sizes=[10], weight_init="xavier",
        activation="tanh", pzx_activation="identity",
        reconstruction_distribution={"type": "nope"})
    params = vae_init(jax.random.PRNGKey(0), conf, jnp.float32)
    with _pytest.raises(ValueError, match="unknown reconstruction"):
        vae_elbo(conf, params, x01, jax.random.PRNGKey(1))
