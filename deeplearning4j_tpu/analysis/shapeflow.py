"""Shape/dtype flow checker over nn/conf configurations.

Symbolic propagation of InputTypes through a MultiLayerConfiguration or
ComputationGraphConfiguration — no params built, no tracing — the analog
of the reference's config-time validation (InputTypeUtil +
MultiLayerConfiguration.Builder.setInputType nIn inference), turned into
a reporting pass instead of scattered exceptions: every defect becomes a
Finding mapped to the layer/vertex NAME that caused it, so a
misconfigured graph is diagnosed before trace time instead of surfacing
as a cryptic XLA shape error five layers downstream.

The walk deliberately mirrors what the runtime will do
(MultiLayerConfiguration.input_types_per_layer / GraphBuilder.build's
topo propagation) but never mutates the conf and never raises: a layer
whose output_type throws produces an SF002 finding and propagation
continues with an unknown type.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.analysis.findings import (
    ERROR,
    INFO,
    WARNING,
    Finding,
)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration,
    ElementWiseVertex,
    GraphVertexConf,
    LayerVertex,
    MergeVertex,
    SubsetVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalInput,
    RecurrentInput,
)
from deeplearning4j_tpu.nn.conf.network import (
    MultiLayerConfiguration,
    _needs,
)

_OUTPUT_LAYER_TYPES = (L.OutputLayer, L.RnnOutputLayer, L.LossLayer,
                       L.CenterLossOutputLayer)

# which InputType kinds each layer family consumes directly (the "ff"
# family eats flattened image rows without a preprocessor — see
# nn/conf/network.auto_preprocessor)
_ACCEPTS = {"cnn": ("cnn",), "rnn": ("rnn",), "ff": ("ff", "cnn_flat")}

_BF16_NAMES = ("bf16", "bfloat16", "mixed")


def _inner(layer: L.LayerConf) -> L.LayerConf:
    return layer.inner if isinstance(layer, L.FrozenLayer) and layer.inner \
        else layer


def _layer_label(layer: L.LayerConf, fallback: str) -> str:
    name = getattr(_inner(layer), "name", None)
    return name or fallback


def _dense_chain_member(inner: L.LayerConf) -> bool:
    """Layers whose n_out IS the flat feature count the next dense layer
    consumes — the only producers/consumers the no-InputType fallback
    n_in check may compare against (conv n_out is channels, recurrent
    n_out is hidden size; comparing those is a false positive)."""
    if not isinstance(inner, L.FeedForwardLayerConf):
        return False
    return not isinstance(inner, (L.EmbeddingLayer, L.ConvolutionLayer,
                                  L.Convolution1DLayer,
                                  L.BaseRecurrentLayerConf,
                                  L.RnnOutputLayer))


def _expected_n_in(layer: L.LayerConf, it) -> Optional[int]:
    """What infer_n_in would wire for this input — computed on a throwaway
    copy so the check never mutates the configuration."""
    probe = copy.deepcopy(_inner(layer))
    try:
        probe.n_in = None
        probe.infer_n_in(it)
        return probe.n_in
    except Exception:
        return None


def _check_layer(layer: L.LayerConf, it, loc: str,
                 has_preprocessor: bool) -> Tuple[Optional[object], List[Finding]]:
    """Validate one layer against its (post-preprocessor) input type and
    return (output type or None, findings)."""
    out: List[Finding] = []
    inner = _inner(layer)

    if isinstance(inner, L.FeedForwardLayerConf) and inner.has_params() \
            and inner.n_out <= 0:
        out.append(Finding(
            "SF001", ERROR, loc,
            f"{type(inner).__name__} has n_out={inner.n_out} (unset)",
            "set n_out on the layer config"))

    if it is None:
        return None, out

    # input-family compatibility (would the runtime forward even make
    # sense?) — the builder auto-inserts preprocessors, but confs built
    # by hand / deserialized / imported may lack them
    need = _needs(layer)
    accepts = _ACCEPTS.get(need)
    if accepts is not None and it.kind not in accepts:
        out.append(Finding(
            "SF002", ERROR, loc,
            f"{type(inner).__name__} consumes {need!r} input but receives "
            f"{it.kind!r} ({type(it).__name__})"
            + ("" if has_preprocessor else " and no preprocessor is set"),
            f"insert the {it.kind}->{need} preprocessor "
            "(nn/conf/preprocessors) or rebuild via the builder with an "
            "InputType set"))
        return None, out

    # nIn wiring: what the layer declares vs what actually flows in
    # (EmbeddingLayer excluded: its nIn is the vocabulary size, while its
    # input is index columns — arity says nothing about it)
    if (isinstance(inner, (L.FeedForwardLayerConf, L.BatchNormalization))
            and not isinstance(inner, L.EmbeddingLayer)
            and getattr(inner, "n_in", None) is not None):
        expected = _expected_n_in(layer, it)
        if expected is not None and inner.n_in != expected:
            out.append(Finding(
                "SF001", ERROR, loc,
                f"{type(inner).__name__} declares n_in={inner.n_in} but the "
                f"incoming {type(it).__name__} supplies {expected}",
                f"set n_in={expected}, or let the builder infer it from "
                "the InputType"))

    try:
        return layer.output_type(it), out
    except Exception as e:
        out.append(Finding(
            "SF002", ERROR, loc,
            f"output_type failed for {type(inner).__name__}: {e}",
            "fix the layer's input wiring"))
        return None, out


def _promotion_findings(net_conf, head_locs: List[str]) -> List[Finding]:
    """bf16 compute policy promotes loss-head outputs to f32
    (PrecisionPolicy.cast_output) — flag each promotion point so the
    boundary is explicit, not silent."""
    precision = str(getattr(net_conf, "precision", "f32") or "f32").lower()
    if precision not in _BF16_NAMES:
        return []
    return [Finding(
        "SF006", INFO, loc,
        "bf16 compute promotes to f32 at this loss head "
        "(PrecisionPolicy.cast_output) — intentional for loss numerics",
        "no action needed unless the promotion shows up hot in a profile")
        for loc in head_locs]


# -- MultiLayerConfiguration --------------------------------------------------


def check_multilayer(conf: MultiLayerConfiguration) -> List[Finding]:
    findings: List[Finding] = []
    it = conf.input_type
    if it is None:
        findings.append(Finding(
            "SF002", INFO, "network",
            "no InputType set — shape flow starts unknown; only declared "
            "nIn/nOut can be checked",
            "build with .set_input_type(InputType...) for full checking"))
    prev_n_out = None
    for i, layer in enumerate(conf.layers):
        loc = f"layer[{i}]:{_layer_label(layer, type(_inner(layer)).__name__)}"
        pp = conf.preprocessors.get(str(i))
        if pp is not None and it is not None:
            try:
                it = pp.output_type(it)
            except Exception as e:
                findings.append(Finding(
                    "SF002", ERROR, loc,
                    f"preprocessor {type(pp).__name__} rejected the "
                    f"incoming {type(it).__name__}: {e}",
                    "fix or remove the preprocessor for this layer"))
                it = None
        # no InputType: the builder wires n_in from the previous n_out —
        # check declared wiring the same way. Only valid along a pure
        # dense chain: a conv/recurrent producer's n_out is channels/
        # hidden size, not the flattened arity a dense consumer sees, and
        # a preprocessor legitimately reshapes in between
        if it is None and prev_n_out is not None and pp is None:
            inner = _inner(layer)
            if (_dense_chain_member(inner)
                    and inner.n_in is not None
                    and inner.n_in != prev_n_out):
                findings.append(Finding(
                    "SF001", ERROR, loc,
                    f"{type(inner).__name__} declares n_in={inner.n_in} but "
                    f"the previous layer outputs n_out={prev_n_out}",
                    f"set n_in={prev_n_out}"))
        it, fs = _check_layer(layer, it, loc, pp is not None)
        findings.extend(fs)
        inner = _inner(layer)
        if _dense_chain_member(inner):
            prev_n_out = inner.n_out
        elif not isinstance(inner, (L.ActivationLayer, L.DropoutLayer,
                                    L.BatchNormalization, L.LossLayer)):
            # anything shape-transforming (conv/pool/rnn/...) breaks the
            # dense chain — stop comparing rather than compare wrongly
            prev_n_out = None

    last = conf.layers[-1] if conf.layers else None
    if last is None or not isinstance(_inner(last), _OUTPUT_LAYER_TYPES):
        findings.append(Finding(
            "SF007", WARNING, "network",
            "final layer is not an OutputLayer/RnnOutputLayer/LossLayer — "
            "fit() has no loss to train against",
            "end the network with a loss head (inference-only nets can "
            "ignore this)"))
    else:
        n = len(conf.layers) - 1
        findings.extend(_promotion_findings(
            conf.net_conf,
            [f"layer[{n}]:{_layer_label(last, type(_inner(last)).__name__)}"]))
    return findings


# -- ComputationGraphConfiguration -------------------------------------------


def _check_merge(v: MergeVertex, its: List, loc: str) -> List[Finding]:
    kinds = {i.kind for i in its}
    if len(kinds) > 1:
        return [Finding(
            "SF003", ERROR, loc,
            f"merge inputs mix kinds {sorted(kinds)} — concatenation along "
            "the feature axis is undefined across families",
            "insert preprocessors so all merge inputs share a family")]
    first = its[0]
    if isinstance(first, ConvolutionalInput):
        hw = {(i.height, i.width) for i in its}
        if len(hw) > 1:
            return [Finding(
                "SF003", ERROR, loc,
                f"merge inputs disagree on spatial size: {sorted(hw)} — "
                "channel-axis concat needs equal height/width",
                "align strides/padding of the merged branches")]
    if isinstance(first, RecurrentInput):
        ts = {i.timesteps for i in its if i.timesteps is not None}
        if len(ts) > 1:
            return [Finding(
                "SF003", ERROR, loc,
                f"merge inputs disagree on timesteps: {sorted(ts)}",
                "align the merged branches' time axes")]
    return []


def _type_sig(it):
    if isinstance(it, ConvolutionalInput):
        return ("cnn", it.height, it.width, it.channels)
    if isinstance(it, RecurrentInput):
        return ("rnn", it.size, it.timesteps)
    return (it.kind, it.arity())


def _check_vertex(v: GraphVertexConf, its: List, loc: str) -> List[Finding]:
    if isinstance(v, MergeVertex):
        return _check_merge(v, its, loc)
    if isinstance(v, ElementWiseVertex):
        out: List[Finding] = []
        if v.op == "subtract" and len(its) != 2:
            out.append(Finding(
                "SF005", ERROR, loc,
                f"ElementWiseVertex(subtract) needs exactly 2 inputs, "
                f"has {len(its)}", "wire exactly two inputs"))
        sigs = {_type_sig(i) for i in its}
        if len(sigs) > 1:
            out.append(Finding(
                "SF005", ERROR, loc,
                f"elementwise {v.op!r} over mismatched input shapes: "
                f"{sorted(sigs)}",
                "make all branches produce the same shape (projection "
                "shortcut, preprocessor, ...)"))
        return out
    if isinstance(v, SubsetVertex):
        # the runtime slices the LAST axis: channels for cnn, size for
        # rnn/ff — arity() (h*w*c) would let out-of-range subsets pass
        it0 = its[0]
        if isinstance(it0, ConvolutionalInput):
            n = it0.channels
        elif isinstance(it0, RecurrentInput):
            n = it0.size
        else:
            n = it0.arity()
        if v.from_ > v.to or v.to >= n or v.from_ < 0:
            return [Finding(
                "SF005", ERROR, loc,
                f"subset [{v.from_}, {v.to}] out of range for feature "
                f"size {n} (inclusive bounds)",
                "fix the subset bounds")]
    return []


def check_compgraph(conf: ComputationGraphConfiguration) -> List[Finding]:
    findings: List[Finding] = []

    for name in conf.outputs:
        if name not in conf.vertices:
            findings.append(Finding(
                "SF004", ERROR, f"vertex:{name}",
                f"declared output {name!r} is not a vertex",
                "set_outputs must name existing vertices"))

    try:
        order = conf.topological_order()
    except ValueError as e:
        findings.append(Finding(
            "SF004", ERROR, "graph",
            f"graph is not a DAG over its inputs: {e}",
            "every vertex must be reachable from add_inputs() and the "
            "edges must be acyclic"))
        return findings

    # dead vertices: computed every forward pass, feeding no output
    live = set(n for n in conf.outputs if n in conf.vertices)
    stack = list(live)
    while stack:
        n = stack.pop()
        for src in conf.vertex_inputs.get(n, []):
            if src not in live:
                live.add(src)
                stack.append(src)
    for name in sorted(set(conf.vertices) - live):
        findings.append(Finding(
            "SF004", WARNING, f"vertex:{name}",
            f"dead vertex {name!r}: computed but feeds no output "
            "(its work and its params are wasted every step)",
            "remove it, or add it to set_outputs"))
    for name in sorted(set(conf.inputs) - live):
        findings.append(Finding(
            "SF004", WARNING, f"input:{name}",
            f"graph input {name!r} feeds no output",
            "drop the input or wire it in"))

    # type propagation along topo order (non-mutating mirror of
    # GraphBuilder.build)
    types: Dict[str, Optional[object]] = {}
    if conf.input_types is not None:
        if len(conf.input_types) != len(conf.inputs):
            findings.append(Finding(
                "SF002", ERROR, "graph",
                f"{len(conf.input_types)} input_types for "
                f"{len(conf.inputs)} inputs", "match arities"))
        types.update(zip(conf.inputs, conf.input_types))
    head_locs: List[str] = []
    n_heads = 0
    for name in order:
        if name in types or name in conf.inputs:
            continue
        v = conf.vertices[name]
        loc = f"vertex:{name}"
        its = [types.get(i) for i in conf.vertex_inputs.get(name, [])]
        if isinstance(v, LayerVertex):
            if len(its) > 1:
                findings.append(Finding(
                    "SF002", ERROR, loc,
                    "a LayerVertex consumes exactly one activation but has "
                    f"{len(its)} inputs",
                    "merge the inputs explicitly (MergeVertex) — the "
                    "builder does this automatically"))
                types[name] = None
                continue
            it = its[0] if its else None
            if it is not None and v.preprocessor is not None:
                try:
                    it = v.preprocessor.output_type(it)
                except Exception as e:
                    findings.append(Finding(
                        "SF002", ERROR, loc,
                        f"preprocessor {type(v.preprocessor).__name__} "
                        f"rejected the incoming type: {e}",
                        "fix or remove the vertex preprocessor"))
                    it = None
            t, fs = _check_layer(v.layer, it, loc,
                                 v.preprocessor is not None)
            findings.extend(fs)
            types[name] = t
            if (name in conf.outputs
                    and isinstance(_inner(v.layer), _OUTPUT_LAYER_TYPES)):
                n_heads += 1
                head_locs.append(loc)
        else:
            if any(i is None for i in its):
                types[name] = None
                continue
            findings.extend(_check_vertex(v, its, loc))
            try:
                types[name] = v.output_type(its)
            except Exception as e:
                findings.append(Finding(
                    "SF005", ERROR, loc,
                    f"output_type failed for {type(v).__name__}: {e}",
                    "fix the vertex wiring"))
                types[name] = None

    if n_heads == 0:
        findings.append(Finding(
            "SF007", WARNING, "graph",
            "no output vertex is a loss head (OutputLayer/RnnOutputLayer/"
            "LossLayer) — fit() has no loss to train against",
            "make at least one output a loss head (inference-only graphs "
            "can ignore this)"))
    else:
        findings.extend(_promotion_findings(conf.net_conf, head_locs))
    return findings


def propagate_types(conf):
    """Public propagation helper: the InputType each vertex/layer OUTPUTS.

    MultiLayer -> list aligned with conf.layers (entry i = layer i's
    output type); graph -> dict vertex/input name -> type. Unknown types
    are None. Used by the jaxpr auditor to shape abstract batches."""
    if isinstance(conf, MultiLayerConfiguration):
        it = conf.input_type
        out = []
        for i, layer in enumerate(conf.layers):
            pp = conf.preprocessors.get(str(i))
            if pp is not None and it is not None:
                try:
                    it = pp.output_type(it)
                except Exception:
                    it = None
            if it is not None:
                try:
                    it = layer.output_type(it)
                except Exception:
                    it = None
            out.append(it)
        return out
    types: Dict[str, Optional[object]] = {}
    if conf.input_types is not None:
        types.update(zip(conf.inputs, conf.input_types))
    try:
        order = conf.topological_order()
    except ValueError:
        return types
    for name in order:
        if name in types:
            continue
        v = conf.vertices.get(name)
        if v is None:
            continue
        its = [types.get(i) for i in conf.vertex_inputs.get(name, [])]
        if any(i is None for i in its) or not its:
            types[name] = None
            continue
        try:
            if isinstance(v, LayerVertex):
                it = its[0]
                if v.preprocessor is not None:
                    it = v.preprocessor.output_type(it)
                types[name] = v.layer.output_type(it)
            else:
                types[name] = v.output_type(its)
        except Exception:
            types[name] = None
    return types


def check_configuration(conf) -> List[Finding]:
    """Entry point: dispatch on configuration type."""
    if isinstance(conf, MultiLayerConfiguration):
        return check_multilayer(conf)
    if isinstance(conf, ComputationGraphConfiguration):
        return check_compgraph(conf)
    raise TypeError(
        f"check_configuration wants a MultiLayerConfiguration or "
        f"ComputationGraphConfiguration, got {type(conf).__name__}")
