"""Latency bookkeeping for serving paths: a bounded, thread-safe window
of recent request latencies with percentile readout (p50/p99 for the
inference server's /metrics and the serving bench). Window semantics —
percentiles describe the last `window` requests, not all time — which is
what an operator watching a live endpoint wants."""

from __future__ import annotations

import threading
from collections import deque


def percentile(sorted_values, q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) over an ascending list."""
    if not sorted_values:
        return float("nan")
    rank = max(0, min(len(sorted_values) - 1,
                      int(round(q / 100.0 * (len(sorted_values) - 1)))))
    return float(sorted_values[rank])


class LatencyTracker:
    def __init__(self, window: int = 2048):
        self._lock = threading.Lock()
        self._window = deque(maxlen=int(window))
        self._count = 0
        self._total = 0.0

    def record(self, seconds: float):
        with self._lock:
            self._window.append(float(seconds))
            self._count += 1
            self._total += float(seconds)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile_seconds(self, q: float):
        """Nearest-rank percentile over the window, in seconds (None
        until something was recorded). The admission-control estimate in
        parallel/inference reads rolling batch latency through this."""
        with self._lock:
            vals = sorted(self._window)
        return percentile(vals, q) if vals else None

    def snapshot(self) -> dict:
        """{"count", "mean_ms", "p50_ms", "p99_ms"} over the window
        (count/mean are all-time)."""
        with self._lock:
            vals = sorted(self._window)
            count, total = self._count, self._total
        ms = 1e3
        return {
            "count": count,
            "mean_ms": round(total / count * ms, 3) if count else None,
            "p50_ms": round(percentile(vals, 50) * ms, 3) if vals else None,
            "p99_ms": round(percentile(vals, 99) * ms, 3) if vals else None,
        }
