"""DeepWalk graph embeddings (reference: graph/models/deepwalk/
DeepWalk.java:95 fit(IGraph, walkLength) — random walks fed to
skip-gram with GraphHuffman hierarchical softmax over
InMemoryGraphLookupTable).

TPU-first: walks are generated host-side (cheap pointer chasing) and the
skip-gram/HS updates run as the SAME batched device step the NLP stack
uses (nlp/learning.py — the AggregateSkipGram analog); the graph-specific
Huffman coding degenerates to the NLP Huffman over vertex frequencies in
the walk corpus, which is exactly what DeepWalk's degree-weighted coding
approximates."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walkers import RandomWalkIterator
from deeplearning4j_tpu.nlp.sequencevectors import (
    SequenceVectors,
    VectorsConfiguration,
)


class GraphVectors:
    """Read-side API over trained vertex embeddings (reference:
    graph/models/GraphVectors.java)."""

    def __init__(self, sv: SequenceVectors, num_vertices: int):
        self._sv = sv
        self.num_vertices = num_vertices

    def vertex_vector(self, v: int) -> np.ndarray:
        return self._sv.lookup.vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._sv.lookup.similarity(str(a), str(b))

    def verts_nearest(self, v: int, top_n: int = 10) -> List[int]:
        return [int(w) for w, _ in
                self._sv.lookup.words_nearest(str(v), top_n)]


class DeepWalk:
    """Builder-style API mirroring DeepWalk.Builder (vectorSize,
    windowSize, learningRate) + fit(graph, walk_length)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, walks_per_vertex: int = 10,
                 seed: int = 0, batch_size: int = 1024):
        self.vector_size = int(vector_size)
        self.window_size = int(window_size)
        self.learning_rate = float(learning_rate)
        self.walks_per_vertex = int(walks_per_vertex)
        self.seed = seed
        self.batch_size = batch_size
        self.vectors: Optional[GraphVectors] = None

    def _make_walker(self, graph: Graph, walk_length: int, weighted: bool,
                     epoch: int):
        """Walk-iterator factory — the only thing subclasses override."""
        return RandomWalkIterator(graph, walk_length, weighted=weighted,
                                  seed=self.seed + epoch)

    def fit(self, graph: Graph, walk_length: int = 40,
            weighted: bool = False) -> GraphVectors:
        walks: List[List[str]] = []
        for epoch in range(self.walks_per_vertex):
            it = self._make_walker(graph, walk_length, weighted, epoch)
            walks.extend([str(v) for v in walk] for walk in it)
        conf = VectorsConfiguration(
            layer_size=self.vector_size,
            window=self.window_size,
            learning_rate=self.learning_rate,
            min_word_frequency=1,
            use_hierarchic_softmax=True,   # DeepWalk's GraphHuffman analog
            negative=0,
            epochs=1,
            batch_size=self.batch_size,
            seed=self.seed,
        )
        sv = SequenceVectors(conf, walks)
        sv.fit()
        self.vectors = GraphVectors(sv, graph.num_vertices)
        return self.vectors


class Node2Vec(DeepWalk):
    """node2vec = DeepWalk with biased 2nd-order walks (p: return
    parameter, q: in-out parameter) feeding the same SequenceVectors
    device step. Reference intent: models/node2vec/Node2Vec.java (a
    deprecated stub wiring a GraphWalker into SequenceVectors — here the
    wiring actually works)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, walks_per_vertex: int = 10,
                 p: float = 1.0, q: float = 1.0, seed: int = 0,
                 batch_size: int = 1024):
        super().__init__(vector_size=vector_size, window_size=window_size,
                         learning_rate=learning_rate,
                         walks_per_vertex=walks_per_vertex, seed=seed,
                         batch_size=batch_size)
        self.p = float(p)
        self.q = float(q)

    def _make_walker(self, graph: Graph, walk_length: int, weighted: bool,
                     epoch: int):
        from deeplearning4j_tpu.graph.walkers import Node2VecWalkIterator

        return Node2VecWalkIterator(
            graph, walk_length, p=self.p, q=self.q, weighted=weighted,
            seed=self.seed + epoch)
