"""Helper SPI — the vendor-kernel plugin point.

Reference: the cuDNN Helper interfaces (ConvolutionHelper.java:35,
BatchNormalizationHelper.java:29, ...) loaded reflectively by layer impls
(ConvolutionLayer.java:68-72) with checkSupported() fallback to the
built-in path. TPU-native shape: layers ask get_helper("op") before their
default XLA lowering; a registered helper answers `supported(**ctx)` and,
when true, its `fn` replaces the default. Pallas kernels register here
(ops/pallas_lstm.py); anything unsupported falls back silently, exactly
like the reference's cuDNN fallback.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, Optional

from deeplearning4j_tpu.utils import faultpoints as _faults
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import tracing as _tracing

logger = logging.getLogger("deeplearning4j_tpu")


def _count(metric: str, op: str, helper: str, family: str,
           reason: Optional[str] = None):
    """Helper SPI events in the shared registry: selection hits,
    builtin-path fallbacks (with why), and auto-disables, each carrying
    the kernel FAMILY (e.g. conv3x3s2, bn_bwd) so per-family hit rates
    are scrape-able — one op slot can route many shapes to many kernels.
    Family values come from the registration's `family(**ctx)` callable,
    which must return a bounded slug set (the metrics tests assert the
    cardinality stays bounded). These happen at trace time, not per
    device step, so a registry lookup per event is fine — and it makes
    PR 2's "helper silently auto-disabled mid-run" kill switch a
    scrape-able series instead of a bench-only check."""
    reg = _metrics.get_registry()
    if reason is None:
        reg.counter(metric, "Helper SPI events",
                    ("op", "helper", "family")).labels(op, helper,
                                                       family).inc()
    else:
        reg.counter(metric, "Helper SPI events",
                    ("op", "helper", "family",
                     "reason")).labels(op, helper, family, reason).inc()
    if metric != "helper_hit_total":
        # fallbacks and auto-disables are rare, diagnosis-relevant events
        # — they ride in the flight recorder so a crash dump shows the
        # kernel story leading up to the failure (hits would be noise)
        from deeplearning4j_tpu.utils import blackbox as _blackbox

        _blackbox.get_recorder().record_event(
            metric.replace("_total", ""), op=op, helper=helper,
            **({"reason": reason} if reason else {}))


class HelperError(RuntimeError):
    """A registered helper fn raised at trace/run time. The helper has
    already been disabled and the failure logged; callers catch this and
    retry their built-in lowering (the reference behaves the same way: a
    cuDNN helper that throws is dropped and the layer falls back)."""


@dataclasses.dataclass
class Helper:
    name: str
    fn: Callable
    supported: Callable[..., bool] = lambda **ctx: True
    enabled: bool = True
    family: Optional[Callable[..., str]] = None


_HELPERS: Dict[str, Helper] = {}


def register_helper(op: str, fn: Callable,
                    supported: Optional[Callable[..., bool]] = None,
                    name: Optional[str] = None,
                    family: Optional[Callable[..., str]] = None) -> None:
    """Install a helper for an op slot ("lstm_sequence", "conv2d", ...).
    Last registration wins (the reference loads exactly one helper class
    per layer type). `family(**ctx)` maps a call context to the bounded
    kernel-family slug the helper metrics are labeled with (default: the
    op name itself, which is trivially bounded)."""
    _HELPERS[op] = Helper(
        name=name or getattr(fn, "__name__", op),
        fn=fn,
        supported=supported or (lambda **ctx: True),
        family=family,
    )


def _family_of(op: str, h: Helper, ctx: dict) -> str:
    if h.family is None:
        return op
    try:
        return str(h.family(**ctx))
    except Exception:  # a broken family fn must never kill the metric
        return op


def get_helper(op: str, **ctx) -> Optional[Callable]:
    """The helper's fn if one is registered, enabled, and supports this
    call context; else None (caller uses its built-in path).

    The returned callable is guarded: a helper fn that raises (e.g. a
    Pallas lowering failure at trace time) is logged and DISABLED, and the
    call raises HelperError so the caller retries its built-in path —
    without the guard a broken kernel would kill the layer with no
    fallback even though the probe passed."""
    h = _HELPERS.get(op)
    if h is None:
        return None
    fam = _family_of(op, h, ctx)
    if not h.enabled:
        _count("helper_fallback_total", op, h.name, fam, "disabled")
        return None
    try:
        if not h.supported(**ctx):
            _count("helper_fallback_total", op, h.name, fam, "unsupported")
            return None
    except Exception as e:  # a broken probe must never kill the fallback
        logger.warning("helper %s probe failed: %s", h.name, e)
        _count("helper_fallback_total", op, h.name, fam, "probe_error")
        return None
    _count("helper_hit_total", op, h.name, fam)

    def guarded(*args, **kwargs):
        try:
            # chaos hook: an `error` fault here IS a raising helper fn —
            # it rides the real auto-disable + HelperError + builtin-
            # retry path below, so injected kernel failures exercise
            # exactly the degradation the PR 2 kill switch promises
            _faults.fault_point("helper_fn", op=op, helper=h.name)
            return h.fn(*args, **kwargs)
        except Exception as e:
            h.enabled = False
            logger.warning(
                "helper %s (op %s) raised %s: %s — helper disabled, "
                "falling back to the built-in path", h.name, op,
                type(e).__name__, e)
            _count("helper_auto_disable_total", op, h.name, fam)
            _count("helper_fallback_total", op, h.name, fam, "raised")
            _tracing.instant("helper/auto_disable", op=op, helper=h.name,
                             error=f"{type(e).__name__}: {e}")
            raise HelperError(f"helper {h.name} failed: {e}") from e

    return guarded


def set_helper_enabled(op: str, enabled: bool) -> None:
    if op in _HELPERS:
        _HELPERS[op].enabled = bool(enabled)


def helper_enabled(op: str) -> Optional[bool]:
    """Current enabled state (None when no helper is registered) — lets
    callers snapshot/restore the kill switch and detect a mid-run
    auto-disable (a helper fn that raised)."""
    h = _HELPERS.get(op)
    return None if h is None else h.enabled


def helper_names() -> Dict[str, str]:
    return {op: h.name for op, h in _HELPERS.items()}
