"""Shared network machinery for MultiLayerNetwork and ComputationGraph.

The reference factors this via the Model interface + BaseLayer inheritance
(nn/api/Model.java); here it is a small base class holding the pieces that
are identical for sequential and DAG networks: listener management, the
epoch/iteration fit loop (with async prefetch and ETL timing), the
batch-transform hook used by parallel.ParallelWrapper, and the flattened
parameter view API (params()/setParams(), reference:
MultiLayerNetwork.java:102-104 flattenedParams).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.nn.params import (
    flat_to_params,
    num_params,
    param_table,
    params_to_flat,
)
from deeplearning4j_tpu.utils import blackbox as _blackbox
from deeplearning4j_tpu.utils import devprof as _devprof
from deeplearning4j_tpu.utils import faultpoints as _faults
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import locktrace as _locktrace
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import resourcemeter as _resourcemeter
from deeplearning4j_tpu.utils import runledger as _runledger
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.train import sentinel as _sentinel

logger = logging.getLogger("deeplearning4j_tpu")


class NetworkBase:
    """Common trainable-network state + loops. Subclasses implement
    `_fit_dataset(ds)` (one optimizer step or TBPTT segment loop) and
    `_ordered_layer_confs()` (layer configs aligned with params_list)."""

    def __init__(self):
        self.listeners = []
        self.iteration = 0
        self.epoch = 0
        self.params_list = None
        self.state_list = None
        self.upd_state = None
        self._score = None  # last minibatch score (device array, lazy read)
        self._last_etl_ms = 0.0
        # opt-in per-iteration grad/update/param mean-magnitude collection
        # for the stats/UI pipeline (reference: BaseStatsListener payloads)
        self._collect_stats = False
        self._last_stats = None
        # hook applied to each DataSet before the step — installed by
        # set_mesh (the MeshPlan's shard_batch) to shard the batch across
        # the mesh. Under async_prefetch it runs inside the device-prefetch
        # worker thread (off the dispatch critical path); staged batches
        # carry `_pipeline_staged` so the loop never applies it twice
        self._batch_transform = None
        # the attached parallel.sharded.MeshPlan (set_mesh): params and
        # updater state live on its mesh, batches shard on its "data"
        # axis, and every step jit gets its NamedSharding in-shardings.
        # None = single-device semantics. fit() auto-attaches one on
        # multi-device platforms (DL4J_AUTO_MESH=0 disables).
        self._mesh_plan = None
        # on-device batch transform (data/transforms.DeviceBatchTransform)
        # applied after placement — set_input_transform
        self._input_transform = None
        # device-prefetch queue depth (staged batches held ahead of the
        # step; device memory bound = depth + 1 batches)
        self._prefetch_depth = 2
        # fuse K consecutive same-shape minibatches into ONE jitted
        # dispatch (set_fused_steps) — the dispatch-latency amortizer
        self._fused_k = 1
        # forward (`output`) traces compiled so far — bumped by the
        # subclasses' shape-keyed output caches; serving layers surface it
        # so a compile storm is a metric, not a latency mystery. The lock
        # makes concurrent cache misses on one key produce ONE entry
        # (ParallelInference calls output() from several threads)
        self._output_compiles = 0
        self._output_cache_lock = threading.Lock()
        # shared-registry fit instruments, resolved ONCE on first use so
        # the per-step hot path touches cached children only (the ISSUE's
        # overhead guard: zero registry lookups per step)
        self._fit_instruments = None
        # donate_argnums the step builders actually used (recorded by
        # _step_donate_argnums) — the doctor's JX006 check audits THIS,
        # not a reconstruction of the policy
        self._donate_argnums = None
        # the watchdog heartbeat of the CURRENT fit (utils/health) — set
        # for the duration of _run_fit; the step path beats it
        self._fit_heartbeat = None
        # mid-epoch resume bookkeeping (train_state()): epoch, batches
        # consumed within it, and the data iterator's epoch-start state —
        # captured by the fit loop, embedded in checkpoints, replayed by
        # fit(resume_from=...)
        self._train_state = None
        # where the hang action dumped the flight recorder before raising
        # StepHangError into the fit thread (read when enriching the
        # async-raised bare exception)
        self._hang_dump_path = None
        # the attached train/sentinel.DivergenceSentinel (set_sentinel).
        # None = the fit loop pays one attribute read per dispatch
        self._sentinel = None
        # in-graph step diagnostic: a [loss, grad_norm] 2-vector every
        # step body returns next to the score — ONE device transfer
        # resolves both for the sentinel's per-step judgment
        self._step_diag = None

    # -- to be provided by subclasses ----------------------------------------

    def init(self):
        raise NotImplementedError

    def _fit_dataset(self, ds):
        raise NotImplementedError

    def _ordered_layer_confs(self) -> List:
        """Layer configs in flattening order, aligned with params_list."""
        raise NotImplementedError

    def _require_init(self):
        if self.params_list is None:
            self.init()

    @property
    def output_compile_count(self) -> int:
        """Forward traces compiled by `output()` so far — one per distinct
        (training, input shape/dtype) key. Steady state for a serving
        workload is a constant (one per batch bucket); growth under
        traffic means shape churn is forcing recompiles."""
        return self._output_compiles

    def _cached_output_fn(self, key, make_fn):
        """Shape-keyed get-or-insert into the `output()` jit cache, bumping
        `output_compile_count` on insert. Under the lock so concurrent
        cache misses on one key (ParallelInference calls output() from
        several threads) produce ONE entry; the actual trace happens at
        call time outside the lock and jax serializes it internally."""
        with self._output_cache_lock:
            if not isinstance(self._output_fn, dict):
                self._output_fn = {}
            fn = self._output_fn.get(key)
            if fn is None:
                fn = self._output_fn[key] = make_fn()
                self._output_compiles += 1
                self._note_compile("output", key)
            return fn

    def _note_compile(self, kind: str, key=None):
        """Record a jit-cache insertion (a fresh trace/compile) as a
        first-class event: `compile_total{kind}` in the shared registry
        plus a trace instant carrying the shape signature — compile
        storms become a scrape-able number with the shapes that caused
        them, instead of mystery tail latency."""
        _metrics.get_registry().counter(
            "compile_total", "jit cache insertions (fresh traces)",
            ("kind",)).labels(kind).inc()
        _tracing.instant("compile", kind=kind,
                         key=None if key is None else str(key))
        _blackbox.get_recorder().record_event(
            "compile", compile_kind=kind,
            key=None if key is None else str(key))

    def _step_donate_argnums(self):
        """donate_argnums for jitted optimizer steps: params (0) and
        updater state (2) are donated on device backends so the update
        reuses their buffers instead of holding old+new copies; cpu
        makes donation a no-op (jax warns), so it is skipped there. The
        ONE definition every step builder uses — and records on the net,
        so analysis/jaxpr_audit's JX006 check audits the value the jits
        actually got, not a parallel reconstruction of this rule."""
        import jax

        donate = (0, 2) if jax.default_backend() != "cpu" else ()
        self._donate_argnums = donate
        return donate

    def _jit_step(self, step, *, data_argnums=(3,), stacked_data=False):
        """jit an optimizer-step body — the ONE place every step builder
        (standard, truncated, fused-TBPTT, multi-batch; MultiLayerNetwork
        and ComputationGraph) gets its jit, so the donation rule AND the
        mesh sharding policy are single-sourced. Without a mesh plan
        this is plain `jax.jit(step, donate_argnums=...)`; with one the
        program is built with explicit NamedSharding in-shardings (batch
        argnums sharded on the data axis, params/updater per their live
        placement) and the same donation — the sharded signature JX006
        audits via the recorded `_donate_argnums`."""
        import jax

        donate = self._step_donate_argnums()
        plan = self._mesh_plan
        if plan is None:
            return jax.jit(step, donate_argnums=donate)
        return plan.jit_step(self, step, donate_argnums=donate,
                             data_argnums=data_argnums,
                             stacked_data=stacked_data)

    # -- multi-device mesh ----------------------------------------------------

    def _reset_step_programs(self):
        """Drop every cached jitted program (train steps, fused variants,
        output cache) — placement or signature changed."""
        self._train_step_fn = None
        self._output_fn = None
        for attr in ("_trunc_step_fn", "_fused_tbptt_fn", "_multi_fit_fn",
                     "_tbptt_batched_fn"):
            if hasattr(self, attr):
                setattr(self, attr, None)

    def set_mesh(self, mesh=None, *, plan=None, bucket_bytes=None,
                 grad_dtype=None):
        """Attach a device mesh: the mainline multi-chip training path.
        Params/layer state/updater state are committed to the mesh
        replicated (tp/pp placements already on the mesh are honored),
        each fit batch is sharded on the "data" axis by the input
        pipeline, and the optimizer step compiles to ONE donated SPMD
        program with the gradient all-reduce in-graph — bucketed per the
        plan's CollectivePlan (see parallel/sharded.py). `mesh=None`
        builds a 1-D "data" mesh over all visible devices; `plan`
        overrides the MeshPlan (the multi-host DCN plan does).
        `bucket_bytes` sets the gradient-bucket size (0 = monolithic
        tail-end reduction; default DL4J_GRAD_BUCKET_BYTES or 4 MiB);
        `grad_dtype="bf16"` opts the all-reduce wire payload into bf16
        (f32 accumulation after the reduce — never the default). `fit()`
        calls this automatically when more than one device is visible
        (DL4J_AUTO_MESH=0 disables)."""
        from deeplearning4j_tpu.parallel.sharded import MeshPlan

        self._require_init()
        if mesh is None:
            from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh

            mesh = data_parallel_mesh()
        if plan is None:
            plan = MeshPlan(mesh, bucket_bytes=bucket_bytes,
                            grad_dtype=grad_dtype)
        elif bucket_bytes is not None or grad_dtype is not None:
            raise ValueError(
                "bucket_bytes/grad_dtype are MeshPlan knobs — pass them "
                "to the plan's constructor, not alongside plan=")
        plan.place_net(self)
        self._mesh_plan = plan
        self._batch_transform = plan.shard_batch
        self._reset_step_programs()
        return self

    def unset_mesh(self):
        """Detach the mesh plan (single-device semantics again). Params/
        state/updater are re-committed to the default device: leaving
        them committed to the multi-device mesh would hand the rebuilt
        un-sharded jit arguments on incompatible device sets (mesh-
        committed params vs default-device batches) — and the leftover
        NamedSharding would also block auto-mesh from re-attaching."""
        if self._mesh_plan is not None:
            import jax

            dev = jax.devices()[0]
            put = lambda t: jax.tree_util.tree_map(
                lambda a: jax.device_put(a, dev), t)
            self.params_list = put(self.params_list)
            self.state_list = put(self.state_list)
            self.upd_state = put(self.upd_state)
            self._mesh_plan = None
            self._batch_transform = None
            self._reset_step_programs()
        return self

    def _maybe_auto_mesh(self):
        """The fit-loop default: on a multi-device platform with no mesh
        attached and no caller-installed batch transform, engage the
        sharded data-parallel step over all devices — multi-chip training
        is the mainline, not an opt-in wrapper. DL4J_AUTO_MESH=0 opts a
        process out (tests/conftest.py does, so tier-1's 8-virtual-device
        suite doesn't shard every tiny fit)."""
        if self._mesh_plan is not None or self._batch_transform is not None:
            return
        from deeplearning4j_tpu.parallel.sharded import auto_mesh_enabled

        if not auto_mesh_enabled():
            return
        import jax

        if len(jax.devices()) < 2:
            return
        if self.params_list is not None:
            from jax.sharding import NamedSharding

            for leaf in jax.tree_util.tree_leaves(self.params_list):
                if isinstance(getattr(leaf, "sharding", None), NamedSharding):
                    # params already carry a mesh placement (shard_params_tp
                    # or an explicit set_mesh/unset_mesh sequence): that is
                    # a deliberate parallelism decision — don't clobber it
                    # with an auto data mesh
                    return
        logger.info(
            "multi-device platform (%d devices): engaging the sharded "
            "data-parallel train step (net.set_mesh; DL4J_AUTO_MESH=0 "
            "disables)", len(jax.devices()))
        self.set_mesh()

    # -- model FLOPs (the MFU numerator) -------------------------------------

    def model_flops_per_example(self):
        """(per-example optimizer-step FLOPs, source) for live MFU
        accounting (utils/devprof, PerformanceListener). Lazily the
        analytic per-layer estimate; upgraded to the jaxpr cost model
        when one is attached (`attach_cost_model` — bench.py and
        `cli perf` do). (None, source) when the conf carries no
        InputType to estimate from."""
        v = getattr(self, "_flops_per_example", None)
        if v is None:
            from deeplearning4j_tpu.utils import flops as _flops

            v = self._flops_per_example = \
                _flops.analytic_step_flops_per_example(self.conf)
        return v

    def set_model_flops_per_example(self, flops, source: str = "costmodel"):
        self._flops_per_example = (float(flops), str(source))
        return self

    def attach_cost_model(self, cm, batch: Optional[int] = None):
        """Adopt an analysis/costmodel.CostModel as this net's FLOP and
        static-memory accounting: live MFU gauges switch to its model
        FLOPs (source "costmodel") and the `device_memory_bytes{kind=
        activations_est}` watermark and OOM forensics use its
        liveness-based activation peak."""
        b = batch or cm.batch or 1
        self.set_model_flops_per_example(cm.model_flops / max(1, b))
        self._cost_model_meta = {
            "activation_peak_bytes": cm.activation_peak_bytes,
            "resident_bytes": cm.resident_bytes,
            "largest_activation": cm.largest_activation,
            "model_flops": cm.model_flops,
            "batch": b,
            "source": "costmodel",
        }
        return self

    def set_tenant(self, tenant):
        """Register this net under a tenant identity — the SAME identity
        the serving tier books under (utils/tenancy). When process-wide
        metering is enabled (utils/resourcemeter), the net's devprof
        device-time windows, HBM residency, and all-reduce wire bytes
        are attributed to that tenant; unmetered, this is just an
        interned attribute."""
        _resourcemeter.register_net(self, tenant)
        return self

    # -- static analysis -----------------------------------------------------

    def doctor(self, *, batch_size: int = 2, timesteps: int = 8,
               jaxpr: bool = True):
        """Pre-flight static analysis of this network: shape/dtype flow
        over the configuration (analysis/shapeflow — nIn/nOut wiring,
        missing preprocessors, merge conflicts, dead vertices) and, when
        the config is sound and `jaxpr` is True, one abstract trace of
        the train-step loss audited for TPU hazards (analysis/jaxpr_audit
        — f64, widening casts, folded constants, host callbacks, dead
        weights, donation). No compile, no device step, no mutation.

        Returns a list of analysis.Finding; `cli doctor` is this method
        with a command line. Opt-in by design — construction stays
        cheap; call it before committing real device time to a model."""
        from deeplearning4j_tpu.analysis import doctor_network

        return doctor_network(self, batch_size=batch_size,
                              timesteps=timesteps, jaxpr=jaxpr)

    # -- listeners -----------------------------------------------------------

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    def set_collect_stats(self, flag: bool = True):
        """Toggle fused per-iteration grad/update/param mean-magnitude
        collection (used by ui.StatsListener). Rebuilds the train step."""
        flag = bool(flag)
        if flag != self._collect_stats:
            self._collect_stats = flag
            self._train_step_fn = None
            if hasattr(self, "_trunc_step_fn"):
                self._trunc_step_fn = None
        return self

    def set_sentinel(self, sentinel):
        """Attach a train/sentinel.DivergenceSentinel: every optimizer
        step is judged against the in-graph (loss, grad-norm) diagnostic;
        anomalous steps are discarded (quarantine), persistent anomalies
        restore the last-good checkpoint (rollback) and bounded failures
        raise TrainingDivergedError. Pass None to detach. A judged step
        blocks on its own diagnostic, so the sentinel trades the async
        dispatch pipeline's lookahead for per-step safety — attach it to
        runs that must survive numerical failure, not to microbenchmarks.
        Disables step fusion (each step must be judged individually)."""
        self._sentinel = sentinel
        return self

    def set_fused_steps(self, k: int):
        """Run up to `k` consecutive same-shape minibatches as ONE jitted
        dispatch (a `lax.scan` over the stacked batches — same math, same
        per-step lr/rng/iteration bookkeeping, k-1 fewer host->device
        round-trips). The host-side analog of the reference's
        AsyncDataSetIterator throughput role (MultiLayerNetwork.java:
        1023-1025) taken to its XLA conclusion: when dispatch latency is
        the bottleneck (small models, remote links), amortize it.

        Fusion engages only when it is observationally equivalent to the
        per-step loop: no listeners (per-iteration callbacks must see
        their iteration's params), no stats collection, no batch
        transform, and the subclass supports it (`_fused_fit_supported`);
        partial/ragged chunks fall back to per-step fits."""
        self._fused_k = max(1, int(k))
        return self

    def set_input_transform(self, transform):
        """Install an on-device batch transform (e.g.
        data.transforms.DeviceBatchTransform): under async_prefetch it
        runs jitted on the staged device batch inside the prefetch
        worker; with prefetch off it runs inline before the step — same
        math, same per-batch rng step, either way. Pass None to remove."""
        self._input_transform = transform
        return self

    def set_prefetch_depth(self, depth: int):
        """How many device-staged batches the input pipeline holds ahead
        of the train step (see data.prefetch.DevicePrefetchIterator)."""
        self._prefetch_depth = max(1, int(depth))
        return self

    def _fused_fit_supported(self) -> bool:
        """Whether this network can run `_fit_datasets_fused`."""
        return False

    def _fit_datasets_fused(self, ds_list):
        raise NotImplementedError

    @staticmethod
    def _step_rng_and_t(key, t0, i):
        """Per-step (rng, t) inside a fused scan: t0 is the iteration
        counter as EXACT uint32 (float32 would collapse consecutive
        steps' dropout rng past 2^24 iterations), i the scan index. The
        ONE derivation every fused program shares with `_run_step`'s
        per-step fold_in(key, iteration)."""
        import jax
        import jax.numpy as jnp

        ti = t0 + jnp.asarray(i, t0.dtype)
        return jax.random.fold_in(key, ti), ti.astype(jnp.float32)

    def _ds_signature(self, ds):
        """Shape/mask signature — only identically-shaped consecutive
        batches are stacked into one fused dispatch."""
        sh = lambda a: None if a is None else tuple(a.shape)
        if hasattr(ds, "features_masks"):  # MultiDataSet
            return (
                tuple(sh(f) for f in ds.features),
                tuple(sh(y) for y in ds.labels),
                None if ds.features_masks is None
                else tuple(sh(m) for m in ds.features_masks),
                None if ds.labels_masks is None
                else tuple(sh(m) for m in ds.labels_masks),
            )
        return (sh(ds.features), sh(ds.labels), sh(ds.features_mask),
                sh(ds.labels_mask))

    def _notify(self, batch_size, ds=None):
        if not self.listeners:
            return
        info = {
            "score": lambda: self._score,
            "batch_size": batch_size,
            "etl_ms": self._last_etl_ms,
            "stats": lambda: self._last_stats,
            # the batch that produced this iteration (activation-visualizing
            # listeners forward it through the net; lambda keeps it lazy)
            "batch": lambda: ds,
        }
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration - 1, info)

    # -- fit-loop observability ----------------------------------------------

    def _fit_obs(self):
        """Fit-loop instruments from the shared registry, resolved ONCE
        per network and cached — the per-step hot path touches these
        children only, never the registry (the ISSUE's overhead guard)."""
        ins = self._fit_instruments
        if ins is None:
            reg = _metrics.get_registry()
            ins = self._fit_instruments = {
                "steps": reg.counter(
                    "fit_step_total", "optimizer steps run").labels(),
                "examples": reg.counter(
                    "fit_examples_total",
                    "training examples consumed").labels(),
                "data_wait": reg.histogram(
                    "fit_data_wait_seconds",
                    "time blocked on the data iterator (ETL) before a "
                    "dispatch").labels(),
                "dispatch": reg.histogram(
                    "fit_dispatch_seconds",
                    "host time in the train-step call (trace + dispatch; "
                    "excludes device sync)").labels(),
                "sync": reg.histogram(
                    "fit_device_sync_seconds",
                    "device sync to the step's score — measured only "
                    "while tracing is enabled, so the default fit path "
                    "never adds blocking syncs").labels(),
                "examples_unknown": reg.counter(
                    "fit_examples_unknown_total",
                    "fit batches whose example count could not be "
                    "determined (excluded from fit_examples_total — "
                    "an under-report made explicit, not silent)").labels(),
                "allreduce_bytes": reg.counter(
                    "allreduce_bytes_total",
                    "gradient bytes all-reduced in-graph by the sharded "
                    "train step (logical payload: summed gradient leaf "
                    "bytes per optimizer step)").labels(),
                "collective_seconds": reg.counter(
                    "train_step_collective_seconds",
                    "time attributed to the train step's gradient "
                    "all-reduce, by accounting source (estimate = ring "
                    "wire bytes / ICI bandwidth — a cost model, not a "
                    "measurement; measured = sampled blocking dispatch "
                    "of a reduction-only probe with the live bucket "
                    "schedule)", ("source",)).labels("estimate"),
                "collective_seconds_measured": reg.counter(
                    "train_step_collective_seconds",
                    "time attributed to the train step's gradient "
                    "all-reduce, by accounting source",
                    ("source",)).labels("measured"),
                "recorder": _blackbox.get_recorder(),
                "devprof": _devprof.get_profiler(),
            }
        return ins

    def _timed_fit(self, fit_fn, data_wait: float, n_examples: int,
                   n_batches: int = 1, batches=None):
        """Run one dispatch (a single `_fit_dataset` or a fused flush)
        under the step-phase timers: data-wait / dispatch / device-sync,
        each a histogram in the shared registry and a span when tracing
        is on. Device-sync is only MEASURED (a blocking read of the
        step's score) when tracing is enabled — observability must not
        change the async dispatch pipeline it observes. `batches` names
        the DataSet(s) behind this dispatch for the divergence sentinel's
        quarantine records and the `nan` fault kind's batch taint."""
        ins = self._fit_obs()
        it0 = self.iteration
        sync = None
        # resume bookkeeping BEFORE the dispatch: a checkpoint listener
        # firing inside it (post-step _notify) must record this batch as
        # consumed — the snapshot's params already include its update
        ts = self._train_state
        if ts is not None:
            ts["batch_in_epoch"] += n_batches
        # beat on entry AND exit: each phase (data wait, dispatch) must
        # individually exceed hang_timeout to read as a stall, instead of
        # their sum tripping the watchdog on an input-bound step
        hb0 = self._fit_heartbeat
        if hb0 is not None:
            hb0.beat()
        # sentinel pre-capture: one attribute read with no sentinel
        # attached (the <10us off-path contract); with one, the pre-step
        # references that make an anomalous step's update discardable
        pre = _sentinel.pre_step(self)
        t0 = time.perf_counter()
        with _tracing.span("fit/step", data_wait_ms=round(data_wait * 1e3, 3)):
            with _tracing.span("fit/dispatch"):
                # chaos hook: an `oom` fault here is a device allocator
                # failure mid-fit — it unwinds through _run_fit's OOM
                # forensics exactly as a real RESOURCE_EXHAUSTED would;
                # a `nan` fault taints this batch's features so the
                # divergence makes it into the REAL dispatch (NaN loss,
                # NaN grads — exactly what the sentinel exists to catch)
                injected = _faults.fault_point("train_step")
                if injected == "nan" and batches:
                    _faults.taint_nan(batches[0])
                # CN003 probe: entering the jitted step with a traced
                # lock held stalls every contender for a whole device
                # program (off = one module-global read)
                _locktrace.note_dispatch("fit/dispatch")
                fit_fn()
            dispatch = time.perf_counter() - t0
            if _tracing.is_enabled() and self._score is not None:
                import jax

                t1 = time.perf_counter()
                with _tracing.span("fit/device_sync"):
                    jax.block_until_ready(self._score)
                sync = time.perf_counter() - t1
                ins["sync"].observe(sync)
        n_steps = max(1, self.iteration - it0)
        ins["steps"].inc(n_steps)
        ins["examples"].inc(n_examples)
        ins["data_wait"].observe(data_wait)
        ins["dispatch"].observe(dispatch)
        # collective books: each sharded optimizer step all-reduced one
        # gradient payload in-graph — scrape-able evidence the reduction
        # runs on the interconnect, not through host averaging
        plan = self._mesh_plan
        if plan is not None and plan.n_data_shards > 1:
            payload = plan.grad_payload_bytes(self) * n_steps
            ins["allreduce_bytes"].inc(payload)
            # tenant wire-bytes attribution for the same payload (a net
            # registered via set_tenant; one global read unmetered)
            _resourcemeter.note_wire(getattr(self, "_tenant", None),
                                     _resourcemeter.TIER_TRAINING, payload)
            ins["collective_seconds"].inc(
                plan.collective_seconds_estimate(self) * n_steps)
            # the estimate's falsifier: every sample_every-th sharded
            # step, ONE blocking dispatch of the reduction-only probe
            # (same wire payload + bucket schedule), attributed to the
            # steps since the last sample — devprof's sampling contract,
            # so tier-1 (sample_every=0) never blocks here
            measured = plan.maybe_measure_collective(
                self, n_steps, ins["devprof"].sample_every)
            if measured is not None:
                ins["collective_seconds_measured"].inc(measured)
        # black box + liveness: one ring append (score kept as a device
        # reference — never synced here) and a heartbeat refresh
        ins["recorder"].record_step(self.iteration - 1, score=self._score,
                                    data_wait=data_wait, dispatch=dispatch,
                                    sync=sync)
        # device-side accounting: two integer ops on unsampled steps,
        # one blocking score read every sample_every-th (utils/devprof)
        ins["devprof"].on_step(self, n_examples, self._score)
        # run-ledger hook: ONE module-global read with no ledger
        # attached (the off-by-default overhead contract); sampling
        # itself lives on the ledger's own daemon, never here
        _runledger.note_fit_step(self)
        # sentinel judgment AFTER the step's own forensics recorded it:
        # an anomalous step stays visible in the flight recorder even
        # though its update is about to be discarded. May raise
        # RollbackSignal (answered by _run_fit) or TrainingDivergedError.
        if pre is not None:
            _sentinel.post_step(self, pre, batches)
        hb = self._fit_heartbeat
        if hb is not None:
            hb.beat()

    def _ds_examples(self, ds) -> int:
        """Example count for `fit_examples_total`. Only structural
        can't-know failures (no such method/attribute, malformed shape)
        degrade to 0 — and those are counted under
        `fit_examples_unknown_total` so the under-report is visible. A
        real iterator bug raising anything else propagates; the old bare
        `except Exception` swallowed those."""
        try:
            return int(getattr(ds, "reported_examples", None)
                       or ds.num_examples())
        except (AttributeError, TypeError, IndexError):
            self._fit_obs()["examples_unknown"].inc()
            return 0

    # -- the fit loop --------------------------------------------------------

    def _run_fit(self, iterator, epochs: int, async_prefetch: bool,
                 prefetch_buffer: int = 4,
                 hang_timeout: Optional[float] = None,
                 resume_from: Optional[str] = None,
                 run_ledger=None):
        # run-ledger opt-in (ONE knob): a path builds a RunLedger there
        # (closed when the fit ends — the per-run artifact), an instance
        # is attached for the fit's duration and left open for its
        # owner. Hooks stay a single flag check when this is None.
        owned_ledger = attached_ledger = None
        if run_ledger is not None:
            if isinstance(run_ledger, str):
                owned_ledger = _runledger.RunLedger(run_ledger)
                attached_ledger = _runledger.attach(owned_ledger)
            else:
                attached_ledger = _runledger.attach(run_ledger)
        # multi-device default: engage the sharded data-parallel step
        # BEFORE restore/staging so the restored state lands on the mesh
        # and the pipeline stages batches with the mesh sharding
        self._maybe_auto_mesh()
        if self._mesh_plan is not None:
            self._mesh_plan.reset_pad_target()
        skip_batches = 0
        if resume_from is not None:
            # restore BEFORE staging: the iterator state lands on the
            # caller's iterator, not the pipeline wrappers about to be
            # composed around it
            skip_batches, epochs, _ = self._restore_for_resume(
                resume_from, iterator, epochs)
            if self._mesh_plan is not None:
                # checkpoint arrays arrive as host numpy: re-commit them
                # to the mesh so the sharded step's in-shardings match
                self._mesh_plan.place_net(self)
        owned = None
        if async_prefetch:
            staged = self._stage_input_pipeline(iterator, prefetch_buffer)
            if staged is not iterator:
                iterator = owned = staged
        # a caller-installed batch transform disables fusion (per-batch
        # hooks must see their own batch) — EXCEPT the mesh plan's own
        # shard_batch: sharded batches stack fine, and the stacked fused
        # programs shard batch dim 1 (stacked_data in _jit_step), so
        # mesh-attached nets keep their dispatch-fusion opt-in. The
        # divergence sentinel also disables fusion: quarantine must be
        # able to discard ONE step's update, not a fused group's.
        plan_shard = (None if self._mesh_plan is None
                      else self._mesh_plan.shard_batch)
        fuse_k = self._fused_k if (
            self._fused_k > 1
            and not self.listeners
            and not self._collect_stats
            and self._sentinel is None
            and (self._batch_transform is None
                 or self._batch_transform == plan_shard)
            and self._fused_fit_supported()
        ) else 1
        # sentinel wiring: resolve the rollback directory (explicit >
        # resume_from > an attached CheckpointListener) and reset the
        # per-fit escalation counters
        if self._sentinel is not None:
            self._sentinel.bind(self, resume_dir=resume_from)
        # the epoch target the rollback loop restores toward: `epochs`
        # is already "remaining" here (the initial resume consumed the
        # completed ones), so the absolute target is epoch + remaining
        total_epoch_target = int(self.epoch) + int(epochs)
        # liveness: the fit thread holds a busy slot on the "fit"
        # heartbeat for the whole run and beats once per dispatch
        # (_timed_fit). With hang_timeout the watchdog's stall action
        # dumps the flight recorder and raises StepHangError here —
        # a wedged step becomes a diagnosable exception, not a hang.
        hb = _health.get_health().register(
            "fit",
            stall_after=hang_timeout if hang_timeout else 600.0,
            on_stall=self._hang_action() if hang_timeout else None)
        self._fit_heartbeat = hb
        try:
            with hb.busy():
                while True:
                    try:
                        self._fit_epochs(iterator, epochs, fuse_k,
                                         skip_batches)
                        break
                    except _sentinel.RollbackSignal:
                        # the sentinel's escalation: restore the last-
                        # good checkpoint and replay — bounded attempts
                        # (note_rollback raises TrainingDivergedError
                        # past the budget)
                        skip_batches, epochs = self._rollback_restore(
                            iterator, total_epoch_target)
        except _health.StepHangError as e:
            if e.dump_path is not None:
                raise  # already carries its forensics
            raise _health.StepHangError(
                f"fit step exceeded hang_timeout={hang_timeout}s without "
                f"progress (see flight-recorder dump)",
                dump_path=self._hang_dump_path) from None
        except Exception as e:
            # device allocator failure: capture the largest live buffers
            # + the static activation estimate BEFORE unwinding (the
            # buffers are gone once the frames release their references),
            # then let the original exception carry on
            if _devprof.is_oom(e):
                path = _devprof.oom_forensics("fit", e, net=self)
                logger.error("RESOURCE_EXHAUSTED in fit; OOM forensics "
                             "dump at %s", path)
            raise
        finally:
            # the ledger scope ends with the fit: an owned (path-built)
            # ledger takes its final sample and closes; a caller-owned
            # one is only detached (its recording thread lives on)
            if owned_ledger is not None:
                owned_ledger.close()
            elif attached_ledger is not None:
                _runledger.detach(attached_ledger)
            self._fit_heartbeat = None
            # resume coordinates die with the fit: a preemption save
            # AFTER a completed fit must record a clean epoch boundary,
            # not a stale mid-epoch position
            self._train_state = None
            # the devprof sampling window dies with the fit too: a
            # stale last-sample timestamp would make the NEXT fit's
            # first window span the inter-fit idle gap and publish
            # garbage step-time/MFU gauges
            self._devprof_state = None
            _health.get_health().unregister(hb)
            # pipeline workers this fit created must die with it, raise
            # or return (the generators' own finally handles the common
            # case; this covers anything still live after an exception)
            if owned is not None:
                owned.close()
            # fires even when an epoch raises: listeners that flipped
            # process-global state for the run (TracingListener) restore
            # it here instead of leaking it past a failed fit
            for lst in self.listeners:
                hook = getattr(lst, "on_fit_end", None)
                if hook is not None:
                    hook(self)
        return self

    def _hang_action(self):
        """The watchdog-side stall action for fit(hang_timeout=...):
        runs on the dl4j-watchdog thread — dump the black box first (the
        forensics must exist before the exception unwinds the fit), then
        async-raise StepHangError into the fitting thread."""
        fit_tid = threading.get_ident()

        def on_stall(hb, stalled_for):
            self._hang_dump_path = _blackbox.get_recorder().dump(
                reason=f"fit step hang: no progress for "
                       f"{stalled_for:.3f}s (hang_timeout={hb.stall_after}s)")
            # the dump takes real time: re-check the fit is still OURS
            # and still stalled before the irrevocable async raise — a
            # step that unblocked (or a fit that finished) meanwhile must
            # not receive a StepHangError in its cleanup or afterwards
            if self._fit_heartbeat is not hb:
                return
            state, _, _ = hb.check()
            if state == _health.OK:
                return
            if not _health._async_raise(fit_tid, _health.StepHangError):
                logger.error(
                    "fit hang detected but StepHangError could not be "
                    "delivered; dump at %s", self._hang_dump_path)

        return on_stall

    def _stage_input_pipeline(self, iterator, prefetch_buffer: int):
        """Compose the staged input pipeline around a fit's iterator:

            [caller's host ETL] -> AsyncDataSetIterator -> device prefetch

        * If the caller already built a DevicePrefetchIterator, it IS the
          pipeline — used as-is (bench/resnet pass pre-staged batches).
        * A caller-provided host stage (AsyncDataSetIterator or
          ParallelDataSetIterator multi-worker ETL) is kept; otherwise a
          single async host-prefetch thread is added (the pre-pipeline
          behavior).
        * The device stage runs `_batch_transform` (the mesh plan's
          per-shard batch split under set_mesh) — or a committed
          default-device `device_put` — plus the on-device input
          transform, all in its worker thread, `_prefetch_depth` batches
          ahead: host->device transfer leaves the dispatch critical path.
        """
        from deeplearning4j_tpu.data.prefetch import (
            DevicePrefetchIterator,
            ParallelDataSetIterator,
        )

        if isinstance(iterator, DevicePrefetchIterator):
            # caller-built pipeline: it must carry the net's configured
            # staging, or the loop would silently train unsharded /
            # untransformed (staged batches skip the inline application)
            for mine, theirs, what in (
                (self._batch_transform, iterator.placement,
                 "batch transform (mesh batch sharding)"),
                (self._input_transform, iterator.transform,
                 "input transform"),
            ):
                # `!=`, not `is not`: bound methods (the MeshPlan's
                # shard_batch) are fresh objects per attribute access
                # but compare equal on (__self__, __func__)
                if mine is not None and theirs != mine:
                    raise ValueError(
                        f"a DevicePrefetchIterator was passed to fit() but "
                        f"the network has a {what} configured that the "
                        f"iterator does not apply — build the iterator "
                        f"with it (placement=/transform=), or pass the "
                        f"un-staged base iterator and let fit compose "
                        f"the pipeline")
            return iterator
        host = iterator
        wrapped = False
        if not isinstance(host, (AsyncDataSetIterator,
                                 ParallelDataSetIterator)):
            host = AsyncDataSetIterator(host, prefetch_buffer)
            wrapped = True
        return DevicePrefetchIterator(
            host, depth=self._prefetch_depth,
            placement=self._batch_transform,
            transform=self._input_transform,
            close_base=wrapped)

    def _capture_iterator_state(self, iterator) -> Optional[dict]:
        """The iterator's epoch-start state (the data/iterators
        `state()` protocol), JSON-safe, for checkpoints. None when the
        iterator is stateless or its capture fails — resume then replays
        positionally only."""
        state_fn = getattr(iterator, "state", None)
        if not callable(state_fn):
            return None
        try:
            return state_fn()
        except Exception:
            logger.warning("iterator state capture failed; checkpoints "
                           "will resume positionally only", exc_info=True)
            return None

    def train_state(self) -> Optional[dict]:
        """Point-in-time resume coordinates of the CURRENT fit: epoch,
        batches consumed within it, and the iterator's epoch-start state.
        Embedded into checkpoints (utils/model_serializer trainState.json)
        and replayed by fit(resume_from=...). None outside a fit."""
        ts = self._train_state
        return None if ts is None else dict(ts)

    def _restore_for_resume(self, directory: str, iterator,
                            epochs: int, require_finite: bool = False,
                            lr_drift_ok: bool = False,
                            reject_iterations=()):
        """Load the newest GOOD checkpoint in `directory` into this net
        and prime the mid-epoch replay: restores the iterator's
        epoch-start state and returns (batches to skip in the first
        epoch, epochs remaining out of the requested total, the restored
        path or None). An empty/missing directory is a fresh start — the
        same command line works on first boot and after a preemption.

        "Good" is enforced, not assumed: each candidate's per-entry
        SHA-256 manifest is verified before the load (a bit-flipped or
        torn zip is skipped — loudly, counted — and the previous
        checkpoint is used instead), a candidate that fails to
        deserialize is skipped the same way, and the sentinel's rollback
        path additionally rejects checkpoints whose restored params
        carry NaN/Inf (`require_finite`) or whose iteration falls inside
        a quarantined step (`reject_iterations` — a listener can save
        DURING the anomalous dispatch, before the sentinel judged it) —
        "last-good" must actually be good."""
        from deeplearning4j_tpu.train.checkpoint import (
            NoUsableCheckpointError,
            checkpoint_candidates,
            note_bad_checkpoint,
            verified_checkpoints,
        )
        from deeplearning4j_tpu.utils.model_serializer import (
            ConfigMismatchError,
            restore_fit_state,
        )

        meta = path = None
        for cand_path, cand_meta in verified_checkpoints(directory):
            if reject_iterations and int(
                    cand_meta.get("iteration", -1)) in reject_iterations:
                # a save captured DURING a quarantined step holds the
                # very update the sentinel discarded — finite, digest-
                # clean, and still not "good"
                note_bad_checkpoint(
                    cand_path, "captured from a quarantined step")
                continue
            try:
                meta = restore_fit_state(self, cand_path,
                                         ignore_lr=lr_drift_ok)
            except ConfigMismatchError:
                # a changed architecture is a USER error every candidate
                # repeats — raise it, don't silently discard the whole
                # checkpoint history and "start fresh"
                raise
            except Exception as e:
                note_bad_checkpoint(
                    cand_path, f"restore failed: {type(e).__name__}: {e}")
                meta = None
                continue
            if require_finite and not self._params_finite():
                note_bad_checkpoint(
                    cand_path, "restored parameters are non-finite")
                meta = None
                continue
            path = cand_path
            break
        if meta is None:
            if any(True for _ in checkpoint_candidates(directory)):
                # checkpoints EXIST but every one was rejected: raising
                # beats silently restarting from iteration 0 (which
                # would then GC the corrupt zips — progress AND evidence
                # gone); the rollback path converts this to
                # TrainingDivergedError
                raise NoUsableCheckpointError(
                    f"resume_from={directory!r}: checkpoints exist but "
                    f"every candidate was rejected (see "
                    f"checkpoint_integrity_failures_total and the "
                    f"checkpoint_corrupt events) — not starting fresh "
                    f"over a corrupted history")
            logger.info("resume_from=%r: no checkpoint found — starting "
                        "fresh", directory)
            return 0, epochs, None
        ts = meta.get("train_state") or {}
        skip = int(ts.get("batch_in_epoch", 0))
        it_state = ts.get("iterator_state")
        if it_state is not None:
            restore = getattr(iterator, "restore_state", None)
            if callable(restore):
                restore(it_state)
            else:
                logger.warning(
                    "checkpoint carries iterator state but the iterator "
                    "has no restore_state(); mid-epoch replay may not be "
                    "deterministic")
        remaining = max(0, int(epochs) - int(self.epoch))
        if remaining == 0 and skip > 0:
            remaining = 1  # died inside the final epoch: finish it
        logger.info(
            "resumed from %s: iteration=%d epoch=%d, replaying %d "
            "batch(es), %d epoch(s) remaining", path, self.iteration,
            self.epoch, skip, remaining)
        _blackbox.get_recorder().record_event(
            "resume", checkpoint=path, iteration=int(self.iteration),
            epoch=int(self.epoch), skip_batches=skip)
        return skip, remaining, path

    def _params_finite(self) -> bool:
        """Host check that every parameter leaf is finite — the
        rollback path's guard against restoring a checkpoint that was
        saved after the divergence already poisoned the params."""
        import jax

        for leaf in jax.tree_util.tree_leaves(self.params_list):
            if not np.all(np.isfinite(np.asarray(leaf))):
                return False
        return True

    def _rollback_restore(self, iterator, total_epoch_target: int):
        """Answer a sentinel RollbackSignal: account the attempt
        (bounded; optional LR backoff), tear down the abandoned
        mid-epoch pipeline run, restore the newest checkpoint that
        verifies AND loads AND is finite, and re-commit it to the mesh.
        Returns the (skip_batches, epochs_remaining) the replay needs."""
        sent = self._sentinel
        directory = sent.note_rollback(self)
        hb = self._fit_heartbeat
        if hb is not None:
            hb.beat()
        # the RollbackSignal left `for ds in iterator` mid-iteration:
        # close the run (its worker would keep consuming the base
        # concurrently with the replay's fresh run) and rewind to the
        # epoch start — restore_state below overrides the position when
        # the iterator supports the resume protocol
        close = getattr(iterator, "close", None)
        if callable(close):
            close()
        iterator.reset()
        # lr_drift_ok: a previous rollback's lr backoff (or this one's)
        # must not disqualify checkpoints saved at the original rate
        from deeplearning4j_tpu.train.checkpoint import (
            NoUsableCheckpointError,
        )

        try:
            skip, remaining, path = self._restore_for_resume(
                directory, iterator, total_epoch_target,
                require_finite=True, lr_drift_ok=True,
                reject_iterations=sent.tainted_iterations)
        except NoUsableCheckpointError as e:
            sent.diverged(str(e))
        if path is None:
            sent.diverged(
                f"rollback found no usable checkpoint in {directory!r}")
        if self._mesh_plan is not None:
            # checkpoint arrays arrive as host numpy: re-commit to the
            # mesh so the sharded step's in-shardings stay valid
            self._mesh_plan.place_net(self)
        self._step_diag = None
        if hb is not None:
            hb.beat()
        return skip, remaining

    def _fit_epochs(self, iterator, epochs: int, fuse_k: int,
                    skip_batches: int = 0):
        skip = int(skip_batches)
        for _ in range(epochs):
            for lst in self.listeners:
                lst.on_epoch_start(self, self.epoch)
            # resume coordinates for this epoch: captured BEFORE the
            # first batch is pulled, so a checkpoint taken anywhere in
            # the epoch can restore the iterator to the same epoch start
            # (e.g. the shuffle permutation) and skip forward
            self._train_state = {
                "epoch": int(self.epoch),
                "batch_in_epoch": 0,
                "iterator_state": self._capture_iterator_state(iterator),
            }
            t_etl = time.perf_counter()
            buf, sig = [], None
            # data-wait accumulates across buffered (fused) batches so a
            # fused dispatch's histogram entry covers ALL the iterator
            # blocking it amortizes, not just the last batch's
            wait_accum = 0.0
            n_buf = 0
            for ds in iterator:
                wait = time.perf_counter() - t_etl
                self._last_etl_ms = wait * 1e3
                if not getattr(ds, "_pipeline_staged", False):
                    # prefetch-off path: staging work runs inline (same
                    # ops, same order — byte-identical to the pipeline)
                    if self._batch_transform is not None:
                        ds = self._batch_transform(ds)
                    if self._input_transform is not None:
                        ds = self._input_transform(ds)
                if skip > 0:
                    # mid-epoch replay: this batch was trained before the
                    # checkpoint. It is CONSUMED — pulled through the
                    # pipeline and transformed, so every stage's rng/step
                    # counter advances exactly as in the original run —
                    # but not dispatched (its update is already in the
                    # restored params).
                    skip -= 1
                    self._train_state["batch_in_epoch"] += 1
                    t_etl = time.perf_counter()
                    continue
                if (self._sentinel is not None
                        and self._sentinel.should_skip_batch(self, ds)):
                    # quarantined batch re-encountered (post-rollback
                    # replay, or the next epoch's pass over bad data):
                    # consume it without dispatching — re-running it
                    # would deterministically diverge again
                    self._train_state["batch_in_epoch"] += 1
                    t_etl = time.perf_counter()
                    continue
                if fuse_k > 1:
                    s = self._ds_signature(ds)
                    if buf and s != sig:
                        # flush BEFORE charging this batch's wait: it
                        # belongs to the group this batch starts, not the
                        # one it closes
                        flushed, n = list(buf), n_buf
                        self._timed_fit(
                            lambda: self._flush_fused(flushed, fuse_k),
                            wait_accum, n, n_batches=len(flushed),
                            batches=flushed)
                        wait_accum, n_buf = 0.0, 0
                        buf = []
                    wait_accum += wait
                    sig = s
                    buf.append(ds)
                    n_buf += self._ds_examples(ds)
                    if len(buf) == fuse_k:
                        flushed, n = list(buf), n_buf
                        self._timed_fit(
                            lambda: self._flush_fused(flushed, fuse_k),
                            wait_accum, n, n_batches=len(flushed),
                            batches=flushed)
                        wait_accum, n_buf = 0.0, 0
                        buf = []
                else:
                    wait_accum += wait
                    self._timed_fit(lambda: self._fit_dataset(ds),
                                    wait_accum, self._ds_examples(ds),
                                    batches=[ds])
                    wait_accum = 0.0
                t_etl = time.perf_counter()
            if buf:
                flushed, n = list(buf), n_buf
                self._timed_fit(lambda: self._flush_fused(flushed, fuse_k),
                                wait_accum, n, n_batches=len(flushed),
                                batches=flushed)
            if skip > 0:
                # the resumed epoch ended with replay batches still owed:
                # the iterator yields fewer batches than the checkpoint's
                # batch_in_epoch said (dataset shrank, batch size grew,
                # or the iterator state failed to restore). Dropping the
                # leftover into the NEXT epoch would silently swallow its
                # first `skip` real batches — reset instead, loudly.
                logger.warning(
                    "resume fast-forward ran out of batches with %d still "
                    "to skip (iterator shorter than at checkpoint time); "
                    "continuing from the next epoch start", skip)
                skip = 0
            for lst in self.listeners:
                lst.on_epoch_end(self, self.epoch)
            self.epoch += 1
            iterator.reset()

    def _flush_fused(self, buf, fuse_k):
        """Full chunks run fused; ragged tails fall back to per-step fits
        (one jitted program per chunk size would defeat the cache)."""
        if len(buf) == fuse_k:
            self._fit_datasets_fused(buf)
        else:
            for ds in buf:
                self._fit_dataset(ds)

    # -- flattened params API ------------------------------------------------

    def params(self):
        """Flattened parameter vector (reference: Model.params())."""
        self._require_init()
        return params_to_flat(self._ordered_layer_confs(), self.params_list)

    def set_params(self, flat):
        self._require_init()
        self.params_list = flat_to_params(
            self._ordered_layer_confs(), self.params_list, flat
        )

    def num_params(self) -> int:
        self._require_init()
        return num_params(self._ordered_layer_confs(), self.params_list)

    def param_table(self):
        self._require_init()
        return param_table(self._ordered_layer_confs(), self.params_list)

    def summary(self) -> str:
        self._require_init()
        lines = ["=" * 70]
        total = 0
        for i, (conf, p) in enumerate(
            zip(self._ordered_layer_confs(), self.params_list)
        ):
            n = sum(int(np.prod(v.shape)) for v in p.values())
            total += n
            lines.append(f"{i:>3}  {type(conf).__name__:<28} params: {n}")
        lines.append(f"total params: {total}")
        lines.append("=" * 70)
        return "\n".join(lines)
