"""Numerical-failure resilience — the divergence sentinel and its
quarantine/rollback policy.

PRs 6-8 made *crash-shaped* failures survivable (hang watchdog, SIGKILL
resume, overload shedding); this module closes the remaining gap:
*silent* numerical failures. A NaN/Inf loss or an exploding gradient
corrupts every parameter from that step on without tripping any crash
guard — the fit "succeeds" and ships garbage. The resilience loop:

* **Detect (in-graph)**: `_make_step_body` (nn/multilayer, nn/compgraph)
  computes a global gradient-norm scalar next to the loss and returns
  both packed as one 2-vector diagnostic (`net._step_diag`) — the check
  rides the score the host was going to observe anyway, so ONE device
  read per judged step resolves loss AND grad norm; no second sync.
* **Classify (host)**: `DivergenceSentinel.judge` marks each step
  ok / `nonfinite_loss` / `grad_norm_spike` (grad norm > k x the rolling
  median of recent healthy steps). Every anomaly lands in
  `train_anomaly_total{kind}`, the flight recorder, and an SN001
  finding; the grad norm itself is exported as the `train_grad_norm`
  gauge (the run ledger records it, analysis/slo's default pack carries
  a rate-of-change precursor rule on it).
* **Quarantine**: an anomalous step's params/state/updater are discarded
  — the fit loop captured the pre-step references, and jax arrays are
  immutable, so restoring them IS the undo — and the offending batch is
  recorded (iterator position + content digest) so a post-rollback
  replay skips it instead of deterministically diverging on it again
  (`quarantined_batches_total{action}`).
* **Rollback**: `rollback_after` CONSECUTIVE anomalies means quarantine
  alone is not stabilizing the run — the sentinel raises a
  `RollbackSignal` the fit loop answers by restoring the last-good
  checkpoint through the PR 7 `fit(resume_from=)` machinery (digest-
  verified, re-committed to the mesh under PR 10's set_mesh), with an
  optional learning-rate backoff. Attempts are bounded: past
  `max_rollbacks` the run raises a diagnosable `TrainingDivergedError`
  carrying the flight-recorder dump path.

Off-path contract: with no sentinel attached the fit loop pays one
attribute read per dispatch (`pre_step` returns immediately) — pinned
<10us by test, the same bar as utils/devprof and utils/runledger.

The whole loop is deterministically replayable: the `nan` fault kind
(utils/faultpoints, point `train_step`) taints a chosen batch's features
through the real dispatch path, so `cli chaos --preset divergence`
rehearses detect -> quarantine -> rollback -> recover end to end.
"""

from __future__ import annotations

import hashlib
import logging
import math
import statistics
import weakref
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.utils import blackbox as _blackbox
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import tracing as _tracing

logger = logging.getLogger("deeplearning4j_tpu")

OK = "ok"
NONFINITE_LOSS = "nonfinite_loss"
GRAD_NORM_SPIKE = "grad_norm_spike"

_MAX_FINDINGS = 64


class TrainingDivergedError(RuntimeError):
    """Training diverged past what quarantine + rollback could repair
    (or no checkpoint existed to roll back to). `.dump_path` names the
    flight-recorder dump written at raise time — the forensics: the
    anomalous steps' scores, the quarantine/rollback event trail, and
    the grad-norm trajectory leading in."""

    def __init__(self, message: str, dump_path: Optional[str] = None):
        super().__init__(message)
        self.dump_path = dump_path


class RollbackSignal(Exception):
    """Internal control flow: the sentinel asks the fit loop to restore
    the last-good checkpoint. Never escapes `fit()` — the loop either
    answers it or converts it to TrainingDivergedError."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def batch_digest(ds) -> Optional[str]:
    """Content digest of a batch's (first) feature array — the
    position-independent half of a quarantine record, so a shuffled
    replay still recognizes a poisoned batch. None when the features
    cannot be hashed (never fatal: position matching still works)."""
    try:
        feats = getattr(ds, "features", None)
        if isinstance(feats, (list, tuple)):
            feats = feats[0] if feats else None
        if feats is None:
            return None
        a = np.asarray(feats)
        h = hashlib.blake2b(digest_size=16)
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()
    except Exception:
        return None


class DivergenceSentinel:
    """Host-side policy over the in-graph (loss, grad-norm) diagnostic.

    grad_norm_factor: a step whose global grad norm exceeds this multiple
        of the rolling median of recent healthy steps is anomalous.
    window / min_history: rolling-median width, and how many healthy
        steps must be seen before spike judgment engages (the first
        steps of a fresh run are legitimately noisy).
    rollback_after: this many CONSECUTIVE anomalous steps escalate from
        per-step quarantine to a checkpoint rollback.
    max_rollbacks: bounded attempts per fit; exceeding it raises
        TrainingDivergedError.
    lr_backoff: optional factor (<1) applied to the configuration's
        learning rate on every rollback — retry the stretch the run
        diverged on with a gentler step.
    checkpoint_dir: where rollback restores from. None = discovered at
        fit start from an attached CheckpointListener (or the fit's
        resume_from directory); still-None disables rollback, so
        `rollback_after` consecutive anomalies raise directly.
    digest_window: how many batch checks after an anomaly keep
        CONTENT-digest matching armed (each batch hashed to recognize a
        quarantined batch that moved — shuffled replay); position
        matching stays on forever at ~zero cost. 0 disables hashing.
    on_event: optional callable(kind, payload) mirror of every emitted
        event — test/operator hook (the divergence chaos child prints
        these so the parent can SIGKILL mid-rollback deterministically).
    """

    def __init__(self, *, grad_norm_factor: float = 10.0,
                 window: int = 64, min_history: int = 8,
                 rollback_after: int = 3, max_rollbacks: int = 2,
                 lr_backoff: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 digest_window: int = 1024,
                 on_event: Optional[Callable] = None):
        self.grad_norm_factor = float(grad_norm_factor)
        self.window = max(4, int(window))
        self.min_history = max(2, int(min_history))
        self.rollback_after = max(1, int(rollback_after))
        self.max_rollbacks = max(0, int(max_rollbacks))
        self.lr_backoff = None if lr_backoff is None else float(lr_backoff)
        self.checkpoint_dir = checkpoint_dir
        self.digest_window = max(0, int(digest_window))
        self.on_event = on_event
        self._bound_dir: Optional[str] = None
        self._bound_net = None  # weakref: which net this run's state is for
        self._norms: deque = deque(maxlen=self.window)
        self.streak = 0
        self.anomalies = 0
        self.quarantined = 0
        self.rollbacks = 0
        self.findings: List = []
        # quarantine records: {"epoch", "batch_in_epoch", "digest",
        # "anomaly", "iteration"} — consulted by the fit loop's replay
        # skip
        self.records: List[dict] = []
        # iteration indices whose optimizer updates were DISCARDED: a
        # checkpoint captured by a listener during the anomalous
        # dispatch (before judgment) carries exactly those updates —
        # the rollback restore rejects candidates in this set
        self.tainted_iterations: set = set()
        # content-digest matching runs for this many more batch checks
        # (re-armed by every quarantine/match); past it only the cheap
        # position match remains — hashing every batch forever after
        # one transient anomaly would tax the whole rest of the run
        self._digest_checks_left = 0
        reg = _metrics.get_registry()
        self._m_anomaly = _anomaly_counter()
        self._m_quarantine = _quarantine_counter()
        self._m_rollback = reg.counter(
            "train_rollback_total",
            "checkpoint rollbacks triggered by consecutive anomalous "
            "steps").labels()
        self._m_gnorm = reg.gauge(
            "train_grad_norm",
            "global gradient norm of the last judged optimizer step "
            "(in-graph, read with the score)").labels()

    # -- wiring ---------------------------------------------------------------

    def bind(self, net, resume_dir: Optional[str] = None):
        """Fit-start wiring: resolve the rollback directory (explicit >
        fit resume_from > an attached CheckpointListener) and reset the
        per-fit escalation state. Anomaly/quarantine totals persist
        across fits of the SAME net — they describe the run — but
        attaching to a DIFFERENT net clears the run-scoped state
        (quarantine records, tainted iterations, grad-norm history):
        another run's batch positions would otherwise silently match
        and drop this run's batches."""
        prev = self._bound_net() if self._bound_net is not None else None
        if prev is not net:
            self.records.clear()
            self.tainted_iterations.clear()
            self._norms.clear()
            self._digest_checks_left = 0
            self._bound_net = weakref.ref(net)
        d = self.checkpoint_dir or resume_dir
        if d is None:
            from deeplearning4j_tpu.train.checkpoint import (
                CheckpointListener,
            )

            for lst in getattr(net, "listeners", ()):
                if isinstance(lst, CheckpointListener):
                    d = lst.dir
                    break
        self._bound_dir = d
        self.streak = 0
        self.rollbacks = 0
        return self

    @property
    def rollback_dir(self) -> Optional[str]:
        return self.checkpoint_dir or self._bound_dir

    def _emit(self, event: str, **payload):
        _blackbox.get_recorder().record_event(event, **payload)
        _tracing.instant(f"sentinel/{event}", **{
            k: v for k, v in payload.items()
            if isinstance(v, (str, int, float))})
        if self.on_event is not None:
            try:
                self.on_event(event, payload)
            except Exception:
                logger.warning("sentinel on_event hook failed",
                               exc_info=True)

    def _finding(self, severity: str, location: str, message: str,
                 fix_hint: str):
        from deeplearning4j_tpu.analysis.findings import Finding

        if len(self.findings) < _MAX_FINDINGS:
            self.findings.append(Finding(
                code="SN001", severity=severity, location=location,
                message=message, fix_hint=fix_hint))

    # -- classification -------------------------------------------------------

    def judge(self, net) -> str:
        """Classify the step the net just ran. Reads the in-graph
        diagnostic (`net._step_diag`: [loss, grad_norm] — one device
        transfer resolves both); a path with no diagnostic (line-search
        optimizers) degrades to the finite check on the score alone."""
        diag = getattr(net, "_step_diag", None)
        if diag is not None:
            vals = np.asarray(diag)
            loss, gnorm = float(vals[0]), float(vals[1])
        else:
            score = net._score
            if score is None:
                return OK
            loss, gnorm = float(np.asarray(score)), None
        if gnorm is not None and math.isfinite(gnorm):
            self._m_gnorm.set(gnorm)
        step = int(net.iteration) - 1
        if not math.isfinite(loss) or (
                gnorm is not None and not math.isfinite(gnorm)):
            kind = NONFINITE_LOSS
            detail = f"loss={loss!r} grad_norm={gnorm!r}"
        elif (gnorm is not None and len(self._norms) >= self.min_history
                and gnorm > self.grad_norm_factor
                * statistics.median(self._norms)):
            kind = GRAD_NORM_SPIKE
            detail = (f"grad_norm={gnorm:.6g} > {self.grad_norm_factor:g}x "
                      f"rolling median {statistics.median(self._norms):.6g}")
        else:
            if gnorm is not None:
                self._norms.append(gnorm)
            self.streak = 0
            return OK
        self.streak += 1
        self.anomalies += 1
        self._m_anomaly.labels(kind).inc()
        self._emit("train_anomaly", anomaly=kind, step=step,
                   streak=self.streak, detail=detail)
        self._finding(
            "warning", f"step:{step}",
            f"anomalous optimizer step ({kind}): {detail}",
            "the step was quarantined; persistent anomalies roll back "
            "to the last-good checkpoint (lower the learning rate or "
            "inspect the quarantined batches if this recurs)")
        logger.warning("sentinel: anomalous step %d (%s): %s "
                       "(consecutive: %d)", step, kind, detail, self.streak)
        return kind

    # -- quarantine / escalation ----------------------------------------------

    def quarantine(self, net, batches, kind: str,
                   tainted=None):
        """Record the offending batch(es) so the replay after a rollback
        skips them instead of re-diverging deterministically, and taint
        the discarded iteration range so a checkpoint a listener saved
        DURING the anomalous dispatch can never be "last-good". Called
        by the fit loop AFTER it restored the pre-step references."""
        ts = net._train_state or {}
        if tainted is not None:
            self.tainted_iterations.update(tainted)
        self._digest_checks_left = self.digest_window
        n = len(batches) if batches else 1
        pos0 = int(ts.get("batch_in_epoch", 0)) - n
        for i in range(n):
            ds = batches[i] if batches else None
            rec = {
                "epoch": int(ts.get("epoch", net.epoch)),
                "batch_in_epoch": pos0 + i,
                "digest": None if ds is None else batch_digest(ds),
                "anomaly": kind,
                "iteration": int(net.iteration),
            }
            self.records.append(rec)
            self.quarantined += 1
            self._m_quarantine.labels("quarantined").inc()
            self._emit("batch_quarantined", **rec)
            logger.warning(
                "sentinel: quarantined batch %d of epoch %d (%s); step "
                "update discarded", rec["batch_in_epoch"], rec["epoch"],
                kind)

    def should_skip_batch(self, net, ds) -> bool:
        """Replay-side half of quarantine: does this batch match a
        quarantine record (iterator position, or content digest when the
        order changed)? The fit loop consumes a match without
        dispatching it."""
        if not self.records:
            return False
        ts = net._train_state or {}
        pos = (int(ts.get("epoch", net.epoch)),
               int(ts.get("batch_in_epoch", 0)))
        # content hashing is bounded: it pulls the features to host and
        # digests them, so it only runs for digest_window checks after
        # the latest anomaly/match — position matching (two int
        # compares) covers the steady state forever
        hash_ok = self._digest_checks_left > 0
        if hash_ok:
            self._digest_checks_left -= 1
        dg = None
        for rec in self.records:
            if (rec["epoch"], rec["batch_in_epoch"]) == pos:
                matched = rec
                break
            if hash_ok and rec["digest"] is not None:
                if dg is None:
                    dg = batch_digest(ds)
                if dg is not None and dg == rec["digest"]:
                    matched = rec
                    break
        else:
            return False
        self._digest_checks_left = self.digest_window
        self._m_quarantine.labels("replay_skipped").inc()
        self._emit("quarantined_batch_skipped", epoch=pos[0],
                   batch_in_epoch=pos[1], anomaly=matched["anomaly"])
        logger.info("sentinel: skipping quarantined batch %d of epoch %d "
                    "on replay", pos[1], pos[0])
        return True

    def escalate(self, net) -> None:
        """Called by the fit loop after a quarantine: decide whether the
        anomaly streak warrants a rollback. Raises RollbackSignal (the
        loop restores the last-good checkpoint) or TrainingDivergedError
        (no checkpoint to restore from)."""
        if self.streak < self.rollback_after:
            return
        self.streak = 0
        if self.rollback_dir is None:
            self.diverged(
                f"{self.rollback_after} consecutive anomalous steps and "
                f"no checkpoint directory to roll back to (attach a "
                f"CheckpointListener or set checkpoint_dir)")
        raise RollbackSignal(
            f"{self.rollback_after} consecutive anomalous steps")

    def note_rollback(self, net) -> str:
        """Account one rollback attempt (bounded). Returns the directory
        to restore from; raises TrainingDivergedError past the budget."""
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            self.diverged(
                f"training still diverging after {self.max_rollbacks} "
                f"checkpoint rollback(s)")
        self._m_rollback.inc()
        if self.lr_backoff is not None:
            old = net.net_conf.learning_rate
            net.net_conf.learning_rate = old * self.lr_backoff
            logger.warning("sentinel: learning-rate backoff %.3g -> %.3g",
                           old, net.net_conf.learning_rate)
        self._emit("train_rollback", attempt=self.rollbacks,
                   directory=self.rollback_dir,
                   lr=float(net.net_conf.learning_rate))
        logger.warning(
            "sentinel: rolling back to the last-good checkpoint in %r "
            "(attempt %d/%d)", self.rollback_dir, self.rollbacks,
            self.max_rollbacks)
        return self.rollback_dir

    def diverged(self, why: str):
        """Terminal: dump the flight recorder and raise the diagnosable
        error. The dump carries the anomaly/quarantine/rollback event
        trail and the last recorded steps."""
        dump = _blackbox.get_recorder().dump(
            reason=f"training diverged: {why}")
        self._emit("training_diverged", why=why, dump=dump)
        self._finding(
            "error", "fit", f"training diverged: {why}",
            "inspect the dump's grad-norm/score trail; lower the "
            "learning rate, check the input data, or raise "
            "max_rollbacks")
        raise TrainingDivergedError(
            f"training diverged: {why} (forensics: {dump})",
            dump_path=dump)


def _anomaly_counter():
    return _metrics.get_registry().counter(
        "train_anomaly_total",
        "optimizer steps the divergence sentinel classified as "
        "anomalous (the ONE numerical-failure detection path — "
        "early stopping's invalid-score condition counts here too)",
        ("kind",))


def _quarantine_counter():
    return _metrics.get_registry().counter(
        "quarantined_batches_total",
        "batches whose optimizer step was discarded by the divergence "
        "sentinel (`quarantined`) or skipped on post-rollback replay "
        "(`replay_skipped`)", ("action",))


# -- fit-loop hooks (one attribute read when no sentinel is attached) ---------

def pre_step(net):
    """Called by netbase._timed_fit BEFORE the dispatch. No sentinel:
    one attribute read and a None compare — the <10us off-path
    contract. With one: capture the pre-step references (jax arrays are
    immutable and the step REPLACES the trees, so holding the old ones
    is a consistent undo point; cost: one tuple)."""
    if net._sentinel is None:
        return None
    return (net.params_list, net.state_list, net.upd_state,
            net.iteration, net._score)


def post_step(net, pre, batches) -> Optional[str]:
    """Judge the dispatched step; on an anomaly discard its effects
    (restore the pre-step references), quarantine the batch, and let the
    sentinel escalate (RollbackSignal / TrainingDivergedError) when the
    streak crosses `rollback_after`."""
    sent = net._sentinel
    if sent is None or pre is None:
        return None
    kind = sent.judge(net)
    if kind == OK:
        return OK
    # a listener (CheckpointListener) may have SAVED during the
    # anomalous dispatch, before this judgment — those saves carry the
    # discarded update; taint their iteration range so rollback never
    # treats one as "last-good"
    tainted = range(int(pre[3]) + 1, int(net.iteration) + 1)
    (net.params_list, net.state_list, net.upd_state,
     net.iteration, net._score) = pre
    net._step_diag = None
    net._last_stats = None
    sent.quarantine(net, batches, kind, tainted=tainted)
    sent.escalate(net)
    return kind


# -- the ONE invalid-score detection path -------------------------------------

def check_score(iteration: int, score: float,
                origin: str = "earlystopping") -> bool:
    """Shared non-finite-score check: True when `score` is NaN/Inf,
    counted under `train_anomaly_total{kind="nonfinite_loss"}` with a
    flight-recorder event — so early stopping's
    InvalidScoreIterationTerminationCondition and the in-fit sentinel
    report through the SAME books instead of two ad-hoc paths."""
    try:
        finite = math.isfinite(float(score))
    except (TypeError, ValueError):
        finite = False
    if finite:
        return False
    _anomaly_counter().labels(NONFINITE_LOSS).inc()
    _blackbox.get_recorder().record_event(
        "train_anomaly", anomaly=NONFINITE_LOSS, step=int(iteration),
        origin=origin, detail=f"score={score!r}")
    logger.warning("%s: non-finite score %r at iteration %d", origin,
                   score, iteration)
    return True
